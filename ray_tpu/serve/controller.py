"""ServeController — the deployment reconciler actor.

Equivalent of the reference's ServeController + DeploymentStateManager
(reference: python/ray/serve/_private/controller.py:88 controller actor;
deployment_state.py:1155,2258 replica-set reconciler state machine;
application_state.py app lifecycle; autoscaling decisions fed by replica
metrics). One named actor; a background thread drives reconciliation:
desired replicas vs. live replicas, health checks, autoscaling.

Crash restartability (reference: the controller checkpoints to the GCS
internal KV and `_recover_state_from_checkpoint` on boot): after every
state mutation the controller writes a small versioned JSON checkpoint
of desired state + replica roster to the GCS internal KV; the raylet
restarts the named actor in place on worker death (api.py spawns it
with max_restarts > 0), and ``_recover`` rebuilds from the checkpoint —
adopting live replicas through the normal ping path, reaping orphans
the checkpoint doesn't know, and resuming in-flight drains. The data
plane (handles/proxies) keeps serving from cached routing tables for
the duration of the outage.
"""
from __future__ import annotations

import base64
import json
import logging
import threading
import time
from typing import Any

import ray_tpu
from ray_tpu._private import chaos, serialization
from ray_tpu._private.gcs import kv_del, kv_get, kv_put
from ray_tpu._private.ids import ActorID
from ray_tpu.actor import ActorClass, ActorHandle
from ray_tpu.serve.autoscaling_policy import (
    AutoscalingDecider,
    fleet_saturated,
    shed_classes,
)
from ray_tpu.serve import slo as slo_mod
from ray_tpu.serve.config import DeploymentConfig
from ray_tpu.serve.llm import obs
from ray_tpu.serve.replica import ReplicaActor
from ray_tpu.serve.trace_store import TraceStore
from ray_tpu.util import metrics, tracing

logger = logging.getLogger("ray_tpu.serve.controller")

CONTROLLER_NAME = "RT_SERVE_CONTROLLER"
# crash-recovery checkpoint location in the GCS internal KV
CHECKPOINT_KEY = b"RT_SERVE_CONTROLLER_CKPT"
CHECKPOINT_NS = "serve"
# bump on ANY incompatible change to the checkpoint payload shape;
# decode_checkpoint refuses (loudly) to recover from a version it does
# not understand — guessing at an unknown layout could adopt or reap
# the wrong replicas
CHECKPOINT_VERSION = 1
_METRIC_TTL_S = 5.0
# cadence of per-replica autoscaling_snapshot pulls (signal-capable
# deployments only) and the patience per pull
_SNAPSHOT_PERIOD_S = 0.5
_SNAPSHOT_TIMEOUT_S = 30.0
# fleet metrics plane: cadence of metrics_report pulls (EVERY replica
# and proxy, not capability-gated), patience per pull, and ring depth
# per fleet series (~3 minutes of history at the poll cadence)
_FLEET_PERIOD_S = 0.5
_FLEET_TIMEOUT_S = 30.0
_FLEET_HISTORY_SAMPLES = 360
# SLO burn-rate evaluation cadence over the history rings (each tick
# re-reads whole rings, so it runs a touch slower than the poll)
_SLO_EVAL_PERIOD_S = 1.0
# extra actor method threads beyond max_ongoing_requests, so control-plane
# calls (ping / autoscaling_snapshot / drain_status) never park behind a
# data plane running at full concurrency — a saturated replica must still
# report that it IS saturated
_CONTROL_SLOTS = 3


# ---------------- checkpoint codec ----------------
#
# The checkpoint is a small JSON envelope (human-inspectable via
# `kv_get`) with one non-JSON island: each deployment spec carries a
# pickled callable_blob / init_args, so specs ride base64(pickle)
# inside the envelope. Encoding is pure — unit-testable without a
# controller or a cluster.


def encode_spec(spec: dict) -> str:
    """Deployment spec -> base64 text safe to embed in the JSON envelope
    (specs hold bytes blobs and dataclasses JSON can't carry)."""
    return base64.b64encode(serialization.dumps(spec)).decode("ascii")


def decode_spec(blob: str) -> dict:
    return serialization.deserialize(base64.b64decode(blob))


def encode_checkpoint(payload: dict) -> bytes:
    return json.dumps(payload, separators=(",", ":")).encode()


def decode_checkpoint(blob: bytes) -> dict:
    """Parse + validate a checkpoint. Raises ValueError on an unknown
    version or a structurally broken payload — recovery must refuse to
    guess (a misread roster would reap live replicas as orphans)."""
    try:
        payload = json.loads(blob.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"serve controller checkpoint is not JSON: {e}")
    if not isinstance(payload, dict):
        raise ValueError(
            f"serve controller checkpoint must be a JSON object, "
            f"got {type(payload).__name__}"
        )
    version = payload.get("version")
    if version != CHECKPOINT_VERSION:
        raise ValueError(
            f"serve controller checkpoint version {version!r} is not "
            f"supported (this binary speaks version {CHECKPOINT_VERSION})"
        )
    for field in ("seq", "apps"):
        if field not in payload:
            raise ValueError(
                f"serve controller checkpoint missing field {field!r}"
            )
    return payload


class _ReplicaState:
    def __init__(self, handle):
        self.handle = handle
        self.actor_id = handle._actor_id
        self.state = "STARTING"  # STARTING | RUNNING | DRAINING | STOPPING
        self.started_at = time.monotonic()
        self.ping_ref = None
        self.ping_deadline = 0.0
        self.next_ping_at = 0.0
        self.probe_ref = None  # in-flight batch_configs readiness probe
        self.probe_deadline = 0.0
        # autoscaling_snapshot polling (obs.clock timeline — one-clock rule)
        self.snapshot_ref = None
        self.snapshot_deadline = 0.0
        self.next_snapshot_at = 0.0
        # fleet metrics_report polling (same obs.clock ref discipline)
        self.metrics_ref = None
        self.metrics_deadline = 0.0
        self.next_metrics_at = 0.0
        # graceful drain state machine (DRAINING replicas only)
        self.drain_ref = None   # in-flight prepare_drain / drain_status poll
        self.finish_ref = None  # in-flight finish_drain (release_all)
        self.drain_deadline = 0.0


# consecutive replica deaths before __rt first became RUNNING that flip the
# deployment UNHEALTHY and stop the respawn loop (reference: deployment_state
# CrashLoopBackoff / DEPLOY_FAILED)
_MAX_CONSECUTIVE_START_FAILURES = 3


class _DeploymentState:
    def __init__(self, spec: dict):
        self.spec = spec
        self.config: DeploymentConfig = spec["config"]
        self.target = self.config.target_num_replicas
        self.replicas: list[_ReplicaState] = []
        self.batch_configs: dict[str, dict] = {}
        self.stream_methods: list[str] = []
        self.decider = (
            AutoscalingDecider(self.config.autoscaling_config)
            if self.config.autoscaling_config
            else None
        )
        self.status = "UPDATING"  # UPDATING | HEALTHY | UNHEALTHY
        self.last_error: str | None = None
        self.consecutive_start_failures = 0
        self.deleted = False
        # engine-signal autoscaling (set from replica_metadata capability
        # flags once the first replica probes ready)
        self.signal_capable = False
        self.drain_capable = False
        # actor_id bytes -> (obs.clock pull time, AutoscalingSnapshot dict)
        self.snapshots: dict[bytes, tuple[float, dict]] = {}
        # cluster-wide admission: routers shed new work while True
        self.shed = False
        # graduated degradation: priority classes routers reject while
        # preemption is exhausted fleet-wide (batch first); independent of
        # the binary shed bit, which rejects everything
        self.shed_classes: tuple = ()


class _ProxyState:
    """One node's proxy actor (reference: proxy_state.py ProxyState —
    STARTING -> HEALTHY with ping-based health and restart-on-death)."""

    def __init__(self, handle):
        self.handle = handle
        self.state = "STARTING"  # STARTING | HEALTHY | UNHEALTHY
        self.addresses: dict = {}
        self.ping_ref = None
        self.ping_deadline = 0.0
        self.next_ping_at = 0.0
        # fleet metrics_report polling (obs.clock timeline)
        self.metrics_ref = None
        self.metrics_deadline = 0.0
        self.next_metrics_at = 0.0


class ServeController:
    """State-reconciling controller (runs as a named actor; methods are the
    RPC surface, a daemon thread is the control loop)."""

    def __init__(self, reconcile_period_s: float = 0.2):
        self._lock = threading.RLock()
        # app_name -> {"deployments": {name: _DeploymentState}, "ingress": str,
        #              "route_prefix": str|None}
        self._apps: dict[str, dict] = {}
        self._version = 0
        # router_id -> (ts, {(app, deployment): inflight})
        self._router_metrics: dict[str, tuple[float, dict]] = {}
        # per-node ingress proxies (None until start_proxies): node_id(bytes)
        # -> _ProxyState
        self._proxy_cfg: tuple[dict | None, dict | None] | None = None
        self._proxies: dict[bytes, _ProxyState] = {}
        self._proxy_failures: dict[bytes, int] = {}
        self._stopped = threading.Event()
        self._reconcile_period_s = reconcile_period_s
        self._m_desired = metrics.gauge(
            "llm_autoscale_desired_replicas",
            "Autoscaler's current replica target per deployment",
            tag_keys=("app", "deployment"),
        )
        self._m_draining = metrics.gauge(
            "llm_replicas_draining",
            "Replicas currently draining for graceful scale-down",
            tag_keys=("app", "deployment"),
        )
        self._m_prefill_pool = metrics.gauge(
            "llm_prefill_pool_replicas",
            "Running replicas in a disaggregated prefill pool "
            "(deployments declaring pool_role='prefill')",
            tag_keys=("app", "deployment"),
        )
        self._m_restarts = metrics.counter(
            "serve_controller_restarts_total",
            "Controller boots that recovered state from a checkpoint "
            "(i.e. crash restarts; a fresh start does not count)",
        )
        self._m_recovery = metrics.histogram(
            "serve_controller_recovery_seconds",
            "Wall time of _recover(): checkpoint read -> state rebuilt, "
            "replicas adopted, orphans reaped",
            boundaries=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0),
        )
        self._m_orphans = metrics.counter(
            "serve_orphan_replicas_reaped",
            "Live replica actors killed at recovery because the "
            "checkpoint did not know them (mutation crashed before its "
            "checkpoint landed, or their app was deleted mid-outage)",
        )
        # fleet metrics plane (ISSUE 13): per-source collect_families()
        # snapshots merged + ring-buffered here. Deliberately NOT in the
        # crash checkpoint — the history's job is surviving REPLICA death
        # (the aggregator never forgets a source), while a controller
        # restart re-primes it within one poll period anyway.
        self._fleet = metrics.FleetAggregator(
            history_samples=_FLEET_HISTORY_SAMPLES
        )
        self._next_self_ingest = 0.0
        # fleet trace plane (ISSUE 19): spans drained from every polled
        # process assemble here; bounded + tail-sampled, and (like the
        # history rings) deliberately NOT checkpointed — a recovered
        # controller re-collects from live traffic within one poll.
        self._traces = TraceStore()
        self._m_spans_ingested = metrics.counter(
            "serve_trace_spans_ingested_total",
            "Spans drained from replica/proxy/controller span buffers "
            "into the fleet TraceStore",
        )
        self._m_trace_ingest_errors = metrics.counter(
            "serve_trace_ingest_errors_total",
            "Polled span drains the TraceStore failed to ingest "
            "(malformed report or store error; spans dropped, logged)",
        )
        self._m_trace_store = metrics.gauge(
            "serve_trace_store_traces",
            "Traces currently resident in the controller's TraceStore",
        )
        # SLO burn-rate monitor (serve/slo.py) over the history rings
        self._slo_specs = tuple(slo_mod.default_slos())
        self._slo_results: list[dict] = []
        self._slo_burning: set[str] = set()
        self._next_slo_eval = 0.0
        self._m_slo_burn = metrics.gauge(
            "serve_slo_burn_rate",
            "Multi-window SLO burn rate (bad_fraction / error budget) "
            "per SLO and evaluation window",
            tag_keys=("slo", "window"),
        )
        self._m_slo_violations = metrics.counter(
            "serve_slo_violations_total",
            "SLO burn alarms raised (every window over its burn "
            "threshold); counted on the not-burning -> burning edge",
            tag_keys=("slo",),
        )
        # crash-recovery checkpointing: _ckpt_io_lock serializes writers
        # (RPC threads + reconciler) so a slow write can't be overtaken
        # by a staler snapshot; _ckpt_dirty marks a failed write for the
        # reconcile loop to retry
        self._ckpt_io_lock = threading.Lock()
        self._ckpt_seq = 0
        self._ckpt_dirty = False
        self._restarts = 0
        self._recovered_at: float | None = None
        self._recovery_s: float | None = None
        try:
            self._recover()
        except Exception:  # noqa: BLE001 — recovery must never brick boot
            logger.exception(
                "serve controller recovery failed; starting fresh"
            )
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconciler"
        )
        self._thread.start()

    # ---------------- RPC surface ----------------

    def deploy_application(
        self,
        app_name: str,
        deployment_specs: list[dict],
        ingress: str,
        route_prefix: str | None,
    ) -> None:
        """Set target state for an app (reference: controller.py:635
        deploy_application → reconciler convergence)."""
        with self._lock:
            old = self._apps.get(app_name, {"deployments": {}})
            new_deps: dict[str, _DeploymentState] = {}
            removed: list[_DeploymentState] = []
            for spec in deployment_specs:
                name = spec["name"]
                prev = old["deployments"].get(name)
                ds = _DeploymentState(spec)
                if prev is not None:
                    if self._same_spec(prev.spec, spec):
                        ds.replicas = prev.replicas  # adopt live replicas
                        ds.batch_configs = prev.batch_configs
                        ds.stream_methods = prev.stream_methods
                        ds.signal_capable = prev.signal_capable
                        ds.drain_capable = prev.drain_capable
                        ds.snapshots = prev.snapshots
                        if prev.decider is not None and ds.decider is not None:
                            ds.decider = prev.decider
                    else:
                        # spec changed: the old replicas run stale code —
                        # they must die, not leak
                        removed.append(prev)
                new_deps[name] = ds
            removed.extend(
                d for n, d in old["deployments"].items() if n not in new_deps
            )
            for d in removed:
                d.deleted = True
            self._apps[app_name] = {
                "deployments": new_deps,
                "ingress": ingress,
                "route_prefix": route_prefix,
            }
            self._version += 1
        for d in removed:
            self._stop_replicas(d, len(d.replicas))
        self._checkpoint("deploy")

    def delete_application(self, app_name: str) -> None:
        with self._lock:
            app = self._apps.pop(app_name, None)
            self._version += 1
            if app:
                for d in app["deployments"].values():
                    d.deleted = True
        if app:
            for d in app["deployments"].values():
                self._stop_replicas(d, len(d.replicas))
        self._checkpoint("delete")

    def list_applications(self) -> list[str]:
        with self._lock:
            return list(self._apps)

    def get_routing_table(
        self, router_id: str | None = None, metrics: dict | None = None
    ) -> dict:
        """Routing snapshot for handles/proxies; piggybacks router load
        metrics for autoscaling (reference: long-poll config push,
        serve/_private/long_poll.py — ours is versioned pull)."""
        if router_id is not None and metrics is not None:
            with self._lock:
                self._router_metrics[router_id] = (
                    obs.clock(),
                    {tuple(k): v for k, v in metrics.items()},
                )
        out: dict[str, Any] = {"version": None, "apps": {}}
        with self._lock:
            out["version"] = self._version
            for app_name, app in self._apps.items():
                deps = {}
                for name, ds in app["deployments"].items():
                    # Prefix-aware routing piggyback: each LLM replica's
                    # autoscaling snapshot carries a bounded digest
                    # summary of the prefixes its two cache tiers can
                    # serve (engine.prefix_digest_summary), plus the
                    # block_size/vocab_size constants a router needs to
                    # hash raw prompts into the same chain-digest space.
                    # Non-LLM deployments never report the field, so
                    # their tables stay exactly as before.
                    running = {
                        r.actor_id.binary()
                        for r in ds.replicas if r.state == "RUNNING"
                    }
                    summaries = {}
                    prefix_block = prefix_vocab = None
                    for aid, (_, snap) in ds.snapshots.items():
                        digests = snap.get("prefix_digests")
                        if digests is None or aid not in running:
                            continue
                        summaries[aid] = list(digests)
                        prefix_block = snap.get("block_size", prefix_block)
                        prefix_vocab = snap.get("vocab_size", prefix_vocab)
                    deps[name] = {
                        "replicas": [
                            r.handle for r in ds.replicas if r.state == "RUNNING"
                        ],
                        "max_ongoing_requests": ds.config.max_ongoing_requests,
                        "batch_configs": ds.batch_configs,
                        "stream_methods": ds.stream_methods,
                        # cluster-wide admission: routers raise
                        # EngineOverloadedError pre-dispatch while set, so
                        # doomed requests shed at the edge (503+Retry-After)
                        # instead of queueing behind a saturated fleet
                        "shed": ds.shed,
                        "shed_classes": list(ds.shed_classes),
                        "prefix_summaries": summaries,
                        "prefix_block_size": prefix_block,
                        "prefix_vocab_size": prefix_vocab,
                    }
                out["apps"][app_name] = {
                    "ingress": app["ingress"],
                    "route_prefix": app["route_prefix"],
                    "deployments": deps,
                }
        return out

    def status(self) -> dict:
        with self._lock:
            out: dict[str, Any] = {
                app_name: {
                    name: {
                        "status": ds.status,
                        "target_replicas": ds.target,
                        "running_replicas": sum(
                            1 for r in ds.replicas if r.state == "RUNNING"
                        ),
                        "draining_replicas": sum(
                            1 for r in ds.replicas if r.state == "DRAINING"
                        ),
                        "shedding": ds.shed,
                        "shed_classes": list(ds.shed_classes),
                        "message": ds.last_error or "",
                    }
                    for name, ds in app["deployments"].items()
                }
                for app_name, app in self._apps.items()
            }
            # reserved key (consumers index by app name, so it can't
            # collide): crash-recovery provenance for the load harness /
            # operators — did this controller restart, from what
            # checkpoint, and how long did recovery take
            out["_controller"] = {
                "restarts": self._restarts,
                "recovered_at": self._recovered_at,
                "recovery_seconds": self._recovery_s,
                "checkpoint_version": CHECKPOINT_VERSION,
                "checkpoint_seq": self._ckpt_seq,
            }
            # reserved like _controller: the SLO monitor's latest verdict
            out["slo"] = {
                "burning": sorted(self._slo_burning),
                "results": list(self._slo_results),
            }
            return out

    def scale_deployment(
        self, app_name: str, deployment_name: str, target: int
    ) -> bool:
        """Operator/test surface: set the replica target directly, clamped
        to the autoscaling bounds when configured. Scale-downs go through
        the same graceful drain as policy-driven ones. The chaos load
        harness uses this to schedule a deterministic drain event."""
        with self._lock:
            app = self._apps.get(app_name)
            ds = (app or {"deployments": {}})["deployments"].get(deployment_name)
            if ds is None:
                return False
            target = int(target)
            cfg = ds.config.autoscaling_config
            if cfg is not None:
                target = max(cfg.min_replicas, min(cfg.max_replicas, target))
            ds.target = target
            self._version += 1
        self._checkpoint("target_change")
        return True

    def start_proxies(self, http_options: dict | None,
                      grpc_options: dict | None) -> None:
        """Enable per-node ingress: the reconcile loop keeps one proxy
        actor on every alive node (reference: ProxyStateManager.update).
        Ports should be 0 (ephemeral) unless every node is a distinct
        host; read the bound ports back via proxy_addresses(). Calling
        again clears UNHEALTHY tombstones (crash-looped nodes retry)."""
        with self._lock:
            self._proxy_cfg = (http_options, grpc_options)
            self._proxy_failures.clear()
            for nid in [n for n, ps in self._proxies.items()
                        if ps.state == "UNHEALTHY"]:
                self._proxies.pop(nid)
        self._checkpoint("proxy_cfg")

    def proxy_addresses(self) -> dict:
        """hex node_id -> {"http": (host, port), "grpc": (host, port)}
        for HEALTHY proxies."""
        with self._lock:
            return {
                nid.hex(): dict(ps.addresses)
                for nid, ps in self._proxies.items()
                if ps.state == "HEALTHY"
            }

    def proxy_status(self) -> dict:
        with self._lock:
            return {nid.hex(): ps.state
                    for nid, ps in self._proxies.items()}

    def fleet_metrics(self) -> dict:
        """Fleet metrics plane snapshot: merged families (per-source
        relabeled series first, then rollups with ``replica_id`` dropped)
        plus the Prometheus text rendering — the dashboard serves the
        text at ``/metrics/fleet`` verbatim — and source provenance."""
        fams = self._fleet.fleet_families()
        return {
            "families": fams,
            "text": metrics.render_prometheus(fams),
            "sources": self._fleet.sources(),
        }

    def fleet_history(
        self, series: str | None = None, prefix: str | None = None
    ) -> dict:
        """Ring-buffer time series ``{series_key: [(stamp, value), ...]}``
        stamped on the controller's obs.clock. Sources are never
        forgotten, so series of killed replicas stay queryable — the
        post-mortem counterpart of the live scrape."""
        return self._fleet.history(series=series, prefix=prefix)

    # ---------------- trace plane + SLO RPC surface ----------------

    def trace_list(self, app: str | None = None,
                   status: str | None = None,
                   min_duration_s: float | None = None,
                   limit: int = 100) -> list[dict]:
        """Summaries of collected traces, newest first (the dashboard's
        ``/api/traces``). Filterable by app, tail-retention status
        (error/deadline/shed/preempted/failover/handoff-retry/slow/
        sampled) and minimum duration."""
        return self._traces.list_traces(
            app=app, status=status, min_duration_s=min_duration_s,
            limit=int(limit),
        )

    def trace_get(self, trace_id: str) -> dict | None:
        """One assembled trace tree spanning every collected process
        (``/api/traces/<id>``); None when the store never saw (or has
        evicted) the id."""
        return self._traces.assemble(str(trace_id))

    def trace_spans(self, trace_id: str) -> list[dict] | None:
        """Flat span list of one trace — the chrome-export input."""
        return self._traces.spans_of(str(trace_id))

    def trace_store_stats(self) -> dict:
        return self._traces.stats()

    def trace_push(self, spans: list[dict], source: str = "client") -> int:
        """Driver-side span delivery. The controller cannot poll the
        driver (same asymmetry as the router-side shed counters), so
        clients push their ``tracing.drain_buffered_spans()`` here to
        join the fleet assembly. Returns the number of spans ingested."""
        return self._ingest_trace_report(
            str(source), {"spans": list(spans or ())}, stamp=obs.clock())

    def slo_status(self) -> dict:
        """Latest burn-rate evaluation (``/api/slo``): every spec's
        config plus its multi-window result and exemplar trace ids."""
        return {
            "specs": [
                {
                    "name": s.name, "kind": s.kind,
                    "objective": s.objective,
                    "windows_s": list(s.windows_s),
                    "burn_threshold": s.burn_threshold,
                    "description": s.description,
                }
                for s in self._slo_specs
            ],
            "burning": sorted(self._slo_burning),
            "results": list(self._slo_results),
        }

    def shutdown(self) -> None:
        self._stopped.set()
        # drop the checkpoint FIRST: an intentional teardown must not be
        # resurrected by the next controller boot (crash recovery is for
        # crashes; shutdown means "forget everything")
        try:
            kv_del(CHECKPOINT_KEY, ns=CHECKPOINT_NS)
        except Exception as e:  # noqa: BLE001 — best-effort on teardown
            logger.warning(
                "serve controller checkpoint delete failed: %r", e
            )
        with self._lock:
            apps = list(self._apps.values())
            self._apps.clear()
            proxies = list(self._proxies.values())
            self._proxies.clear()
            self._proxy_cfg = None
        for app in apps:
            for d in app["deployments"].values():
                self._stop_replicas(d, len(d.replicas))
        for ps in proxies:
            if ps.handle is None:
                continue
            try:
                ray_tpu.kill(ps.handle)
            except Exception:  # noqa: BLE001 — already dead is fine
                pass

    # ---------------- reconciliation ----------------

    @staticmethod
    def _ref_ready(ref) -> bool:
        """Non-blocking readiness check that works for results living on
        OTHER nodes: wait() triggers the remote pull, where a bare local
        store.status() would report 'missing' forever. A locally-EVICTED
        result also counts as ready — the call ran; get() reconstructs
        from lineage."""
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
        if ready:
            return True
        worker = ray_tpu.worker.global_worker()
        return worker.store.status(ref.object_id) == "evicted"

    @staticmethod
    def _same_spec(a: dict, b: dict) -> bool:
        return (
            a["callable_blob"] == b["callable_blob"]
            and a["init_args"] == b["init_args"]
            and a["init_kwargs"] == b["init_kwargs"]
            and a["config"] == b["config"]
        )

    def _reconcile_loop(self) -> None:
        while not self._stopped.wait(self._reconcile_period_s):
            try:
                self._reconcile_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                import traceback

                traceback.print_exc()

    def _reconcile_once(self) -> None:
        with self._lock:
            work = [
                (app_name, name, ds)
                for app_name, app in self._apps.items()
                for name, ds in app["deployments"].items()
            ]
            proxy_cfg = self._proxy_cfg
        changed = False
        for app_name, name, ds in work:
            changed |= self._reconcile_deployment(app_name, name, ds)
        if proxy_cfg is not None:
            self._reconcile_proxies(proxy_cfg)
            self._poll_proxy_metrics()
        self._ingest_self_metrics()
        self._evaluate_slos()
        with self._lock:
            if changed:
                self._version += 1
            dirty = self._ckpt_dirty
        if changed:
            # roster/status drift the explicit mutation sites don't cover
            # (replica promoted/died, drain advanced) still checkpoints —
            # recovery always sees the latest converged picture
            self._checkpoint("reconcile")
        elif dirty:
            self._checkpoint("retry")

    # consecutive proxy-actor deaths before first HEALTHY that stop the
    # respawn loop for that node (mirrors the replica crash-loop guard)
    _MAX_PROXY_START_FAILURES = 3

    def _reconcile_proxies(self, proxy_cfg: tuple) -> None:
        """Desired state: one HEALTHY proxy actor per alive node. Dead or
        ping-failing proxies are removed and recreated next pass; requests
        through the other nodes' proxies keep flowing meanwhile. A node
        whose proxy dies repeatedly before ever becoming healthy (bad
        options, port conflict) flips to a sticky UNHEALTHY tombstone
        visible in proxy_status() instead of respawning 5x/second forever."""
        from ray_tpu.serve.proxy_actor import ProxyActor, proxy_actor_options

        worker = ray_tpu.worker.global_worker()
        try:
            nodes = worker.gcs.call("get_nodes")["nodes"]
        except Exception:  # noqa: BLE001 — GCS hiccup; retry next pass
            return
        alive = {n["node_id"] for n in nodes if n.get("alive")}
        # a wildcard bind must be advertised as the node's REACHABLE ip
        node_ip = {n["node_id"]: n.get("address", "").rsplit(":", 1)[0]
                   for n in nodes}
        now = time.monotonic()
        with self._lock:
            current = dict(self._proxies)
        # reap proxies on dead nodes (and their failure tombstones)
        for nid in list(current):
            if nid not in alive:
                ps = current.pop(nid)
                with self._lock:
                    self._proxies.pop(nid, None)
                    self._proxy_failures.pop(nid, None)
                if ps.handle is not None:
                    try:
                        ray_tpu.kill(ps.handle)
                    except Exception:  # noqa: BLE001
                        pass
        # health-check and promote the rest
        for nid, ps in current.items():
            if ps.state == "UNHEALTHY":
                continue  # sticky tombstone: operator must re-start_proxies
            try:
                info = worker.gcs.call(
                    "get_actor", {"actor_id": ps.handle._actor_id.binary()}
                )["actor"]
            except Exception:  # noqa: BLE001
                info = None
            if (info or {}).get("state") == "DEAD":
                self._proxy_died(nid, ps)
                continue
            if ps.ping_ref is not None:
                if self._ref_ready(ps.ping_ref):
                    try:
                        # ping returns the bound addresses, so promotion
                        # never needs a second, blocking RPC
                        ps.addresses = ray_tpu.get(ps.ping_ref, timeout=5)
                        host, port = ps.addresses.get("http", (None, None))
                        if host in ("0.0.0.0", "::", ""):
                            ps.addresses["http"] = (node_ip.get(nid, host),
                                                    port)
                        ps.state = "HEALTHY"
                        with self._lock:
                            self._proxy_failures.pop(nid, None)
                        ps.next_ping_at = now + 1.0
                    except Exception:  # noqa: BLE001 — failed check
                        self._proxy_died(nid, ps, kill=True)
                    ps.ping_ref = None
                elif now > ps.ping_deadline:
                    self._proxy_died(nid, ps, kill=True)
            elif now >= ps.next_ping_at:
                try:
                    ps.ping_ref = ps.handle.ping.remote()
                    ps.ping_deadline = now + 30.0
                except Exception:  # noqa: BLE001 — dead; reaped above
                    pass
        # start proxies for uncovered nodes
        http_options, grpc_options = proxy_cfg
        with self._lock:
            covered = set(self._proxies)
        for nid in alive - covered:
            try:
                handle = ActorClass(
                    ProxyActor,
                    name=f"RT_SERVE_PROXY:{nid.hex()[:12]}",
                    **proxy_actor_options(nid),
                ).remote(http_options, grpc_options)
            except Exception:  # noqa: BLE001 — e.g. stale name not yet
                continue       # reaped by GCS; retry next pass
            with self._lock:
                if self._proxy_cfg is None:
                    # shutdown raced us between the cfg snapshot and here
                    # — don't leak the just-created actor (mirrors
                    # _start_replica's ds.deleted guard)
                    pass
                else:
                    self._proxies[nid] = _ProxyState(handle)
                    continue
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass

    def _proxy_died(self, nid: bytes, ps: "_ProxyState",
                    kill: bool = False) -> None:
        """Remove a dead/failed proxy; repeated pre-healthy deaths leave a
        sticky UNHEALTHY tombstone instead of a respawn loop."""
        if kill and ps.handle is not None:
            try:
                ray_tpu.kill(ps.handle)
            except Exception:  # noqa: BLE001
                pass
        with self._lock:
            if ps.state == "STARTING":
                n = self._proxy_failures.get(nid, 0) + 1
                self._proxy_failures[nid] = n
                if n >= self._MAX_PROXY_START_FAILURES:
                    tomb = _ProxyState(None)
                    tomb.state = "UNHEALTHY"
                    self._proxies[nid] = tomb
                    return
            self._proxies.pop(nid, None)

    def _remove_proxy(self, nid: bytes, ps: "_ProxyState") -> None:
        with self._lock:
            self._proxies.pop(nid, None)
        try:
            ray_tpu.kill(ps.handle)
        except Exception:  # noqa: BLE001
            pass

    def _reconcile_deployment(self, app_name: str, name: str, ds: _DeploymentState) -> bool:
        changed = False
        worker = ray_tpu.worker.global_worker()
        # 1. promote STARTING replicas that came alive; drop dead ones.
        # GCS reads happen outside the lock; list mutations under it.
        for r in list(ds.replicas):
            try:
                info = worker.gcs.call(
                    "get_actor", {"actor_id": r.actor_id.binary()}
                )["actor"]
            except Exception:
                continue
            state = (info or {}).get("state")
            if state == "ALIVE" and r.state == "STARTING":
                # non-blocking readiness probe: a slow-starting replica must
                # not stall the reconcile loop (which also drives every other
                # deployment's health checks)
                if r.probe_ref is None:
                    r.probe_ref = r.handle.replica_metadata.remote()
                    r.probe_deadline = time.monotonic() + 120.0
                elif self._ref_ready(r.probe_ref):
                    try:
                        meta = ray_tpu.get(r.probe_ref, timeout=30)
                        with self._lock:
                            ds.batch_configs = meta["batch_configs"]
                            ds.stream_methods = meta["stream_methods"]
                            ds.signal_capable = meta.get(
                                "has_autoscaling_snapshot", False
                            )
                            ds.drain_capable = meta.get("has_drain", False)
                            r.state = "RUNNING"
                            ds.consecutive_start_failures = 0
                        changed = True
                    except Exception as e:  # noqa: BLE001
                        ds.last_error = f"replica probe failed: {e}"
                    r.probe_ref = None
                elif time.monotonic() > getattr(r, "probe_deadline", 0):
                    self._kill_unhealthy(ds, r, "readiness probe timed out")
                    with self._lock:
                        ds.consecutive_start_failures += 1
                    changed = True
            elif state == "DEAD":
                with self._lock:
                    if r in ds.replicas:
                        ds.replicas.remove(r)
                    if r.state == "STARTING":
                        ds.consecutive_start_failures += 1
                    ds.last_error = "replica actor died"
                changed = True
        # 2. health-check RUNNING replicas via ping round-trips
        changed |= self._health_check(ds)
        # 2b. fleet metrics plane: pull metrics_report from every live
        # replica — unconditional, unlike the autoscaling snapshots (every
        # ReplicaActor exposes it; no capability gate, no decider needed)
        self._poll_fleet_metrics(app_name, name, ds)
        # 2c. engine-signal snapshots — polled for every signal-capable
        # deployment (the method self-gates), not just autoscaling ones:
        # the snapshot now carries the prefix-digest summary that feeds
        # prefix-aware routing, which a fixed-size fleet wants too
        self._poll_snapshots(ds)
        # 3. crash-loop detection: repeated death-before-RUNNING means the
        # user code fails at startup — stop respawning, mark UNHEALTHY
        if ds.consecutive_start_failures >= _MAX_CONSECUTIVE_START_FAILURES:
            if ds.status != "UNHEALTHY":
                with self._lock:
                    ds.status = "UNHEALTHY"
                    ds.last_error = (
                        f"{ds.consecutive_start_failures} consecutive replicas "
                        f"died before becoming ready: {ds.last_error or ''}"
                    )
                return True
            return False
        # 4. autoscaling decision — engine signals when the deployment
        # exports AutoscalingSnapshot (serve.llm), router-reported
        # in-flight load otherwise
        if ds.decider is not None:
            running = sum(1 for r in ds.replicas if r.state == "RUNNING")
            new_target = ds.target
            if ds.signal_capable:
                snaps = self._aggregate_signals(ds)
                # decide only on a converged fleet with a full signal set:
                # scaling while a replica warms (or with half the fleet's
                # snapshots stale) would double-count the same saturation
                if running == ds.target and len(snaps) == running and running > 0:
                    new_target = ds.decider.decide_from_signals(snaps, ds.target)
                shed = fleet_saturated(
                    ds.config.autoscaling_config, snaps, ds.target
                )
                shed_cls = shed_classes(
                    ds.config.autoscaling_config, snaps, ds.target
                )
                if shed != ds.shed or shed_cls != ds.shed_classes:
                    with self._lock:
                        ds.shed = shed
                        ds.shed_classes = shed_cls
                    self._checkpoint("shed_flip")
                    changed = True
            else:
                total = self._aggregate_inflight(app_name, name)
                if running > 0 or total > 0:
                    new_target = ds.decider.decide(total, ds.target)
            if new_target != ds.target:
                chaos.fire(
                    "controller_scale",
                    app=app_name,
                    deployment=name,
                    current=ds.target,
                    target=new_target,
                )
                with self._lock:
                    ds.target = new_target
                self._checkpoint("target_change")
                changed = True
            self._m_desired.set(
                ds.target, tags={"app": app_name, "deployment": name}
            )
        # 5. converge replica count (scale-down drains gracefully when the
        # deployment supports it), then advance in-flight drains
        with self._lock:
            live = [r for r in ds.replicas if r.state in ("STARTING", "RUNNING")]
            deficit = ds.target - len(live) if not ds.deleted else 0
            excess = len(live) - ds.target if not ds.deleted else 0
        if deficit > 0:
            for _ in range(deficit):
                self._start_replica(app_name, ds)
                changed = True
        elif excess > 0:
            if ds.drain_capable:
                self._drain_replicas(ds, excess)
            else:
                self._stop_replicas(ds, excess)
            changed = True
        changed |= self._advance_drains(ds)
        with self._lock:
            draining = sum(1 for r in ds.replicas if r.state == "DRAINING")
        self._m_draining.set(
            draining, tags={"app": app_name, "deployment": name}
        )
        # 6. status rollup
        with self._lock:
            running = sum(1 for r in ds.replicas if r.state == "RUNNING")
            new_status = "HEALTHY" if running >= ds.target else "UPDATING"
            if getattr(ds.config, "pool_role", None) == "prefill":
                self._m_prefill_pool.set(
                    running, tags={"app": app_name, "deployment": name}
                )
            if new_status != ds.status:
                ds.status = new_status
                changed = True
        return changed

    def _health_check(self, ds: _DeploymentState) -> bool:
        """Ping RUNNING replicas (reference: deployment_state health-check
        loop driving user check_health via the replica actor). A replica
        whose ping doesn't land within 3 periods is killed and replaced by
        the convergence step."""
        period = ds.config.health_check_period_s
        if period <= 0:
            return False
        now = time.monotonic()
        changed = False
        for r in list(ds.replicas):
            if r.state != "RUNNING":
                continue
            if r.ping_ref is not None:
                if self._ref_ready(r.ping_ref):
                    try:
                        ray_tpu.get(r.ping_ref, timeout=1)
                        r.ping_ref = None
                        r.next_ping_at = now + period
                    except Exception as e:  # noqa: BLE001 — failed check
                        self._kill_unhealthy(ds, r, f"health check failed: {e}")
                        changed = True
                elif now > r.ping_deadline:
                    self._kill_unhealthy(ds, r, "health check timed out")
                    changed = True
            elif now >= r.next_ping_at:
                try:
                    r.ping_ref = r.handle.ping.remote()
                    # Pings share the replica's one-at-a-time queue with data
                    # calls, so the deadline must exceed worst-case request
                    # latency (handles allow 120s) — this catches truly
                    # wedged replicas, not slow ones.
                    r.ping_deadline = now + max(6 * period, 150.0)
                except Exception:  # noqa: BLE001 — dead; step 1 reaps it
                    pass
        return changed

    def _kill_unhealthy(self, ds: _DeploymentState, r, reason: str) -> None:
        with self._lock:
            if r in ds.replicas:
                ds.replicas.remove(r)
            ds.last_error = reason
        # terminal span flush: a replica that failed its health check can
        # often still answer one last actor-level drain (a dead ENGINE
        # leaves the actor alive — the common failover case). Without it,
        # the kill races the 0.5s poll and the spans of the requests that
        # died WITH the engine are lost — precisely the traces tail
        # retention exists to keep. Bounded small so a truly dead process
        # can't stall the reconcile loop; only the trace buffer is taken
        # (the metrics families stay last-known in the aggregator).
        try:
            rep = ray_tpu.get(r.handle.metrics_report.remote(), timeout=3)
            self._ingest_trace_report(
                f"replica:{r.actor_id.hex()[:12]}", rep, stamp=obs.clock()
            )
        except Exception:  # noqa: BLE001 — process is gone; its buffered
            pass           # spans die with it
        try:
            ray_tpu.kill(r.handle)
        except Exception:  # noqa: BLE001
            pass

    def _aggregate_inflight(self, app_name: str, dep_name: str) -> float:
        """Sum router-reported in-flight load (one-clock rule: freshness
        judged on obs.clock, the same clock get_routing_table stamps)."""
        now = obs.clock()
        total = 0.0
        with self._lock:
            for rid, (ts, m) in list(self._router_metrics.items()):
                if now - ts > _METRIC_TTL_S:
                    del self._router_metrics[rid]
                    continue
                total += m.get((app_name, dep_name), 0.0)
        return total

    def _poll_snapshots(self, ds: _DeploymentState) -> None:
        """Pull AutoscalingSnapshot from every RUNNING replica of a
        signal-capable deployment, non-blocking (same ref discipline as
        pings/probes: a slow replica must not stall the reconcile loop).
        Snapshots are stamped with obs.clock at arrival (one-clock rule);
        _aggregate_signals judges freshness on the same clock."""
        if not ds.signal_capable:
            return
        now = obs.clock()
        for r in list(ds.replicas):
            if r.state != "RUNNING":
                continue
            if r.snapshot_ref is not None:
                if self._ref_ready(r.snapshot_ref):
                    try:
                        snap = ray_tpu.get(r.snapshot_ref, timeout=5)
                        with self._lock:
                            ds.snapshots[r.actor_id.binary()] = (now, snap)
                    except Exception:  # noqa: BLE001 — dead/failing replica;
                        pass           # the health check owns its fate
                    r.snapshot_ref = None
                    r.next_snapshot_at = now + _SNAPSHOT_PERIOD_S
                elif now > r.snapshot_deadline:
                    r.snapshot_ref = None
                    r.next_snapshot_at = now + _SNAPSHOT_PERIOD_S
            elif now >= r.next_snapshot_at:
                try:
                    r.snapshot_ref = r.handle.rt_call.remote(
                        "autoscaling_snapshot", (), {}
                    )
                    r.snapshot_deadline = now + _SNAPSHOT_TIMEOUT_S
                except Exception:  # noqa: BLE001 — dead; step 1 reaps it
                    pass

    def _poll_fleet_metrics(
        self, app_name: str, name: str, ds: _DeploymentState
    ) -> None:
        """Pull ``metrics_report()`` from every live replica into the
        fleet aggregator, non-blocking (same ref discipline as pings and
        snapshot polls: a slow replica must not stall the reconcile
        loop). Reports are ingested with the CONTROLLER's obs.clock as
        the stamp — per-process perf_counter timelines aren't comparable
        across actors, so last-write ordering and history stamps ride one
        clock: ours. Dispatched actor-level (not rt_call): the poll must
        never queue behind a saturated data plane. DRAINING replicas
        still report — their in-flight streams keep moving counters until
        retirement, and the history keeps their series after it."""
        pool_role = getattr(ds.config, "pool_role", None) or ""
        now = obs.clock()
        for r in list(ds.replicas):
            if r.state not in ("RUNNING", "DRAINING"):
                continue
            if r.metrics_ref is not None:
                if self._ref_ready(r.metrics_ref):
                    try:
                        rep = ray_tpu.get(r.metrics_ref, timeout=5)
                        self._fleet.ingest(
                            f"replica:{r.actor_id.hex()}",
                            rep["families"],
                            {
                                "app": app_name,
                                "deployment": name,
                                "replica_id": r.actor_id.hex(),
                                "pool_role": pool_role,
                            },
                            stamp=now,
                        )
                        self._ingest_trace_report(
                            f"replica:{r.actor_id.hex()[:12]}", rep,
                            stamp=now,
                        )
                    except Exception:  # noqa: BLE001 — dead/failing
                        pass           # replica; the health check owns it
                    r.metrics_ref = None
                    r.next_metrics_at = now + _FLEET_PERIOD_S
                elif now > r.metrics_deadline:
                    r.metrics_ref = None
                    r.next_metrics_at = now + _FLEET_PERIOD_S
            elif now >= r.next_metrics_at:
                try:
                    r.metrics_ref = r.handle.metrics_report.remote()
                    r.metrics_deadline = now + _FLEET_TIMEOUT_S
                except Exception:  # noqa: BLE001 — dead; step 1 reaps it
                    pass

    def _poll_proxy_metrics(self) -> None:
        """Same non-blocking metrics_report pull over HEALTHY per-node
        proxies — the serve_* ingress counters (shed responses, access
        status codes) live in proxy processes, not in any replica."""
        now = obs.clock()
        with self._lock:
            current = list(self._proxies.items())
        for nid, ps in current:
            if ps.state != "HEALTHY" or ps.handle is None:
                continue
            if ps.metrics_ref is not None:
                if self._ref_ready(ps.metrics_ref):
                    try:
                        rep = ray_tpu.get(ps.metrics_ref, timeout=5)
                        self._fleet.ingest(
                            f"proxy:{nid.hex()}",
                            rep["families"],
                            {
                                "deployment": "_proxy",
                                "replica_id": f"proxy:{nid.hex()[:12]}",
                            },
                            stamp=now,
                        )
                        self._ingest_trace_report(
                            f"proxy:{nid.hex()[:12]}", rep, stamp=now,
                        )
                    except Exception:  # noqa: BLE001 — dead/failing
                        pass           # proxy; its ping path owns it
                    ps.metrics_ref = None
                    ps.next_metrics_at = now + _FLEET_PERIOD_S
                elif now > ps.metrics_deadline:
                    ps.metrics_ref = None
                    ps.next_metrics_at = now + _FLEET_PERIOD_S
            elif now >= ps.next_metrics_at:
                try:
                    ps.metrics_ref = ps.handle.metrics_report.remote()
                    ps.metrics_deadline = now + _FLEET_TIMEOUT_S
                except Exception:  # noqa: BLE001 — dead; reaped above
                    pass

    def _ingest_self_metrics(self) -> None:
        """Fold the controller's OWN registry (autoscale targets, drain
        gauges, recovery counters) into the fleet plane, so one scrape
        target really does cover the whole control+data plane."""
        now = obs.clock()
        if now < self._next_self_ingest:
            return
        self._next_self_ingest = now + _FLEET_PERIOD_S
        self._fleet.ingest(
            "controller",
            metrics.collect_families(),
            {"deployment": "_controller", "replica_id": "controller"},
            stamp=now,
        )
        # the controller process records spans too (driver-side clients
        # sharing this process); same drain, same store
        self._ingest_trace_report(
            "controller", {"spans": tracing.drain_buffered_spans()},
            stamp=now,
        )

    def _ingest_trace_report(self, source: str, rep: dict,
                             stamp: float) -> int:
        """Fold one polled report's piggybacked span drain into the
        TraceStore. Must never raise (it sits on the non-blocking poll
        path) and must never swallow silently either — failures are
        counted and logged (sanitizer-lint enforced). Returns the number
        of spans the store accepted."""
        spans = rep.get("spans") or ()
        if not spans:
            return 0
        try:
            n = self._traces.ingest(list(spans), source=source, stamp=stamp)
            if n:
                self._m_spans_ingested.inc(n)
            self._m_trace_store.set(float(len(self._traces)))
            return n
        except Exception as e:  # noqa: BLE001 — poll path stays alive
            self._m_trace_ingest_errors.inc()
            logger.warning("trace ingest from %s failed: %r", source, e)
            return 0

    def _evaluate_slos(self) -> None:
        """Evaluate the declarative SLO specs over the fleet history
        rings (multi-window burn rates — serve/slo.py), refresh the
        ``serve_slo_burn_rate`` gauges, count newly-burning violations,
        and attach exemplar trace ids from the TraceStore — the link
        from a burning SLO back to the traces that show why."""
        now = obs.clock()
        if now < self._next_slo_eval:
            return
        self._next_slo_eval = now + _SLO_EVAL_PERIOD_S
        try:
            results = slo_mod.evaluate(
                self._slo_specs, self._fleet.history(), now
            )
        except Exception as e:  # noqa: BLE001 — monitor must not kill
            logger.warning("slo evaluation failed: %r", e)  # the loop
            return
        specs = {s.name: s for s in self._slo_specs}
        burning_now: set[str] = set()
        for res in results:
            spec = specs[res["name"]]
            for wname, w in res["windows"].items():
                self._m_slo_burn.set(
                    w["burn_rate"], tags={"slo": res["name"],
                                          "window": wname},
                )
            res["exemplar_trace_ids"] = []
            if res["burning"]:
                burning_now.add(res["name"])
                if res["name"] not in self._slo_burning:
                    self._m_slo_violations.inc(tags={"slo": res["name"]})
                if spec.exemplar == "slowest_ttft":
                    res["exemplar_trace_ids"] = self._traces.exemplar_ids(
                        slowest_ttft=True)
                else:
                    res["exemplar_trace_ids"] = (
                        self._traces.exemplar_ids(flags=(spec.exemplar,))
                        or self._traces.exemplar_ids(slowest_ttft=True)
                    )
        self._slo_burning = burning_now
        self._slo_results = results

    def _aggregate_signals(self, ds: _DeploymentState) -> list[dict]:
        """Fresh snapshots, one per RUNNING replica (stale or orphaned
        entries pruned). Freshness is judged on obs.clock against
        AutoscalingConfig.signal_ttl_s — same clock the poll stamped."""
        now = obs.clock()
        cfg = ds.config.autoscaling_config
        ttl = cfg.signal_ttl_s if cfg is not None else _METRIC_TTL_S
        out = []
        with self._lock:
            running = {
                r.actor_id.binary()
                for r in ds.replicas
                if r.state == "RUNNING"
            }
            for aid in list(ds.snapshots):
                ts, snap = ds.snapshots[aid]
                if aid not in running or now - ts > ttl:
                    del ds.snapshots[aid]
                    continue
                out.append(snap)
        return out

    def _drain_replicas(self, ds: _DeploymentState, n: int) -> None:
        """Graceful scale-down: STARTING victims (serving nothing) die
        immediately; RUNNING victims — least-loaded first, by their last
        snapshot's active_streams — flip to DRAINING, which removes them
        from the routing table (only RUNNING replicas are routed) while
        their in-flight streams keep decoding. _advance_drains retires
        them once idle (after release_all) or at the drain deadline."""
        to_kill: list[_ReplicaState] = []
        to_drain: list[_ReplicaState] = []
        with self._lock:
            starting = [r for r in ds.replicas if r.state == "STARTING"]
            to_kill = starting[:n]
            want = n - len(to_kill)
            if want > 0:
                def load(r):
                    entry = ds.snapshots.get(r.actor_id.binary())
                    return entry[1].get("active_streams", 0) if entry else 0

                running = sorted(
                    (r for r in ds.replicas if r.state == "RUNNING"), key=load
                )
                to_drain = running[:want]
            for r in to_kill:
                ds.replicas.remove(r)
            # drain deadlines ride obs.clock so the checkpoint can
            # persist remaining-time and recovery can resume the countdown
            # on the same clock (one-clock rule)
            deadline = (
                obs.clock() + ds.config.graceful_shutdown_timeout_s
            )
            for r in to_drain:
                r.state = "DRAINING"
                r.drain_deadline = deadline
                r.drain_ref = None
                r.finish_ref = None
        for r in to_kill:
            try:
                ray_tpu.kill(r.handle)
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        if to_drain:
            # persist the drain BEFORE prepare_drain lands: a controller
            # crash right after this point must recover replicas already
            # latched non-admitting as DRAINING, not as routable RUNNING
            self._checkpoint("drain_start")
        for r in to_drain:
            try:
                # prepare_drain stops admissions replica-side and returns a
                # drain_status dict, so it doubles as the first poll
                r.drain_ref = r.handle.rt_call.remote("prepare_drain", (), {})
            except Exception:  # noqa: BLE001 — dead; step 1 reaps it
                pass

    def _advance_drains(self, ds: _DeploymentState) -> bool:
        """Drive DRAINING replicas to retirement. States per replica:
        polling drain_status (finish or hand off in-flight streams) ->
        finish_drain once idle (release_all returns every KV block) ->
        kill + leave ds.replicas. A replica that dies mid-drain — or one
        still serving at the deadline — is killed as-is: its streams
        resume byte-identically on survivors via the failover path.
        Deadlines ride obs.clock (checkpointed as remaining-time, so a
        recovered controller resumes the countdown, not restarts it)."""
        changed = False
        now = obs.clock()
        for r in [r for r in ds.replicas if r.state == "DRAINING"]:
            if r.finish_ref is not None:
                # releasing: wait for finish_drain's release_all to land
                if self._ref_ready(r.finish_ref) or now > r.drain_deadline:
                    self._retire_drained(ds, r)
                    changed = True
                continue
            idle = False
            dead = False
            if r.drain_ref is not None:
                if self._ref_ready(r.drain_ref):
                    try:
                        status = ray_tpu.get(r.drain_ref, timeout=5)
                        idle = status.get("active_streams", 0) == 0
                    except Exception:  # noqa: BLE001 — died mid-drain; the
                        dead = True    # failover path owns its streams
                    r.drain_ref = None
            else:
                try:
                    r.drain_ref = r.handle.rt_call.remote(
                        "drain_status", (), {}
                    )
                except Exception:  # noqa: BLE001
                    dead = True
            if dead:
                self._retire_drained(ds, r)
                changed = True
            elif idle:
                try:
                    r.finish_ref = r.handle.rt_call.remote(
                        "finish_drain", (), {}
                    )
                    # short grace for the block release to land
                    r.drain_deadline = now + 5.0
                except Exception:  # noqa: BLE001
                    self._retire_drained(ds, r)
                changed = True
            elif now > r.drain_deadline:
                self._retire_drained(ds, r)
                changed = True
        return changed

    def _retire_drained(self, ds: _DeploymentState, r: _ReplicaState) -> None:
        with self._lock:
            if r in ds.replicas:
                ds.replicas.remove(r)
            ds.snapshots.pop(r.actor_id.binary(), None)
        try:
            ray_tpu.kill(r.handle)
        except Exception:  # noqa: BLE001 — already dead is fine
            pass
        self._checkpoint("drain_finish")

    def _start_replica(self, app_name: str, ds: _DeploymentState) -> None:
        spec = ds.spec
        opts = dict(ds.config.ray_actor_options)
        num_cpus = opts.pop("num_cpus", 1)
        num_tpus = opts.pop("num_tpus", 0)
        # replicas serve up to max_ongoing_requests concurrently on the
        # worker's method pool (reference: replicas are async actors bounded
        # by max_ongoing_requests) — overridable via ray_actor_options
        max_concurrency = int(
            opts.pop("max_concurrency", 0) or ds.config.max_ongoing_requests or 1
        )
        resources = dict(opts.pop("resources", None) or {})
        # remaining numeric keys are custom resources ({"TPU": 1} rides here
        # per DeploymentConfig's contract) — never drop them silently
        for k in list(opts):
            v = opts.pop(k)
            if isinstance(v, (int, float)):
                resources[k] = float(v)
            else:
                raise ValueError(
                    f"unsupported ray_actor_options key {k!r} for deployment "
                    f"{spec['name']!r}"
                )
        actor_cls = ActorClass(
            ReplicaActor,
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources or None,
            max_restarts=0,  # the reconciler owns restarts, not the raylet
            # headroom beyond the data-plane bound so control calls (ping /
            # autoscaling_snapshot / drain_status) don't park behind
            # max_ongoing_requests concurrent streams; routers still cap
            # data dispatches at max_ongoing_requests
            max_concurrency=max_concurrency + _CONTROL_SLOTS,
        )
        handle = actor_cls.remote(
            spec["name"],
            spec["callable_blob"],
            spec["init_args"],
            spec["init_kwargs"],
            ds.config.user_config,
            max_concurrency,
        )
        rs = _ReplicaState(handle)
        appended = False
        with self._lock:
            if ds.deleted:
                # deleted while we were starting it — don't leak the actor
                pass
            else:
                ds.replicas.append(rs)
                appended = True
        if appended:
            # the actor exists but no checkpoint knows it yet: a crash in
            # this window leaks a replica unless recovery reaps it — the
            # kill fire makes the window a deterministic chaos site for
            # exactly that orphan-reconciliation proof
            chaos.fire(
                "controller.kill",
                reason="replica_starting",
                deployment=spec["name"],
            )
            self._checkpoint("replica_added")
            return
        try:
            ray_tpu.kill(handle)
        except Exception:  # noqa: BLE001
            pass

    def _stop_replicas(self, ds: _DeploymentState, n: int) -> None:
        with self._lock:
            victims, keep = ds.replicas[:n], ds.replicas[n:]
            ds.replicas = keep
        for r in victims:
            try:
                ray_tpu.kill(r.handle)
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        if victims:
            self._checkpoint("replica_stopped")

    # ---------------- crash-recovery checkpointing ----------------

    def _checkpoint(self, reason: str) -> None:
        """Persist desired state + replica roster to the GCS internal KV.

        Called after every state mutation (deploy/delete, target change,
        shed flip, drain start/finish, replica add/retire, proxy config).
        The write is one atomic kv_put of a small JSON blob — there is no
        half-written state to recover from. A failed write degrades to
        warn-and-retry (_ckpt_dirty; the reconcile loop retries every
        pass), never an inconsistent controller. The ``controller.kill``
        fire after a SUCCESSFUL write is the chaos anchor crash-recovery
        tests kill at, so the checkpoint provably contains the mutation
        the test expects recovery to honor."""
        if self._stopped.is_set():
            return  # tearing down: shutdown() already deleted the key
        with self._ckpt_io_lock:
            with self._lock:
                self._ckpt_dirty = False
                self._ckpt_seq += 1
                payload = self._build_checkpoint_locked()
            try:
                chaos.fire(
                    "controller.checkpoint", reason=reason,
                    seq=payload["seq"],
                )
                kv_put(
                    CHECKPOINT_KEY, encode_checkpoint(payload),
                    ns=CHECKPOINT_NS,
                )
            except Exception as e:  # noqa: BLE001 — degrade, never crash
                with self._lock:
                    self._ckpt_dirty = True
                logger.warning(
                    "serve controller checkpoint write failed (%s), "
                    "will retry: %r", reason, e,
                )
                return
        chaos.fire("controller.kill", reason=reason)

    def _build_checkpoint_locked(self) -> dict:
        """Snapshot desired state + roster (caller holds self._lock)."""
        now = obs.clock()
        apps: dict[str, Any] = {}
        for app_name, app in self._apps.items():
            deps = {}
            for name, ds in app["deployments"].items():
                deps[name] = {
                    "spec_blob": encode_spec(ds.spec),
                    "target": ds.target,
                    "status": ds.status,
                    # shed is persisted for inspection only; recovery
                    # recomputes it from fresh snapshots (see _recover)
                    "shed": ds.shed,
                    "shed_classes": list(ds.shed_classes),
                    "signal_capable": ds.signal_capable,
                    "drain_capable": ds.drain_capable,
                    "batch_configs": ds.batch_configs,
                    "stream_methods": list(ds.stream_methods),
                    "replicas": [
                        {
                            "actor_id": r.actor_id.hex(),
                            "state": r.state,
                            # remaining time, not an absolute deadline:
                            # obs.clock doesn't survive the process
                            "drain_remaining_s": (
                                max(0.0, r.drain_deadline - now)
                                if r.state == "DRAINING"
                                else None
                            ),
                        }
                        for r in ds.replicas
                        if r.state != "STOPPING"
                    ],
                }
            apps[app_name] = {
                "ingress": app["ingress"],
                "route_prefix": app["route_prefix"],
                "deployments": deps,
            }
        return {
            "version": CHECKPOINT_VERSION,
            "seq": self._ckpt_seq,
            "written_at": obs.wall(),
            "restarts": self._restarts,
            "reconciler_version": self._version,
            "apps": apps,
            "proxy_cfg": (
                list(self._proxy_cfg) if self._proxy_cfg else None
            ),
        }

    def _recover(self) -> None:
        """Rebuild state from the last checkpoint after a crash restart.

        Steps: load + validate the checkpoint (unknown versions are
        rejected loudly — boot fresh rather than guess); re-resolve each
        checkpointed replica actor against the GCS, adopting live ones
        (RUNNING replicas re-enter the ping path immediately, DRAINING
        ones resume their drain with the checkpointed remaining time and
        an idempotent re-latch of prepare_drain); reap orphan replica
        actors the checkpoint doesn't know — they were created in the
        window between a mutation and its checkpoint, or belong to an
        app deleted mid-outage; re-adopt per-node proxies by name. Shed
        flags are NOT restored: fresh autoscaling snapshots recompute
        them within a reconcile pass, so a stale flag from before the
        crash can't fail-close a now-healthy fleet. Idempotent — running
        it twice converges to the same state."""
        chaos.fire("controller.recover")
        t0 = obs.clock()
        try:
            blob = kv_get(CHECKPOINT_KEY, ns=CHECKPOINT_NS)
        except Exception as e:  # noqa: BLE001 — GCS unreachable
            logger.error(
                "controller recovery: checkpoint read failed: %r", e
            )
            return
        if blob is None:
            return  # fresh boot: nothing to recover, nothing to reap
        try:
            ckpt = decode_checkpoint(bytes(blob))
        except Exception as e:  # noqa: BLE001 — unknown version/corrupt:
            logger.error(        # refuse to guess; boot fresh and loud
                "controller recovery: checkpoint rejected: %r", e
            )
            return
        known: set[bytes] = set()
        apps: dict[str, dict] = {}
        adopted = 0
        for app_name, app in ckpt["apps"].items():
            deps: dict[str, _DeploymentState] = {}
            for name, d in app["deployments"].items():
                try:
                    ds = _DeploymentState(decode_spec(d["spec_blob"]))
                except Exception as e:  # noqa: BLE001 — one bad spec
                    logger.error(       # must not sink the whole recovery
                        "controller recovery: spec for %s/%s unreadable, "
                        "dropping the deployment: %r", app_name, name, e,
                    )
                    continue
                ds.target = int(d["target"])
                ds.signal_capable = bool(d.get("signal_capable"))
                ds.drain_capable = bool(d.get("drain_capable"))
                ds.batch_configs = d.get("batch_configs") or {}
                ds.stream_methods = list(d.get("stream_methods") or ())
                for rep in d.get("replicas", ()):
                    aid = bytes.fromhex(rep["actor_id"])
                    known.add(aid)
                    r = self._adopt_replica(aid, rep, ds.config)
                    if r is not None:
                        ds.replicas.append(r)
                        adopted += 1
                deps[name] = ds
            apps[app_name] = {
                "deployments": deps,
                "ingress": app["ingress"],
                "route_prefix": app.get("route_prefix"),
            }
        reaped = self._reap_orphans(known)
        proxies = self._readopt_proxies(ckpt.get("proxy_cfg"))
        with self._lock:
            self._apps = apps
            self._proxies = proxies
            pc = ckpt.get("proxy_cfg")
            if pc is not None:
                self._proxy_cfg = (pc[0], pc[1])
            self._ckpt_seq = int(ckpt["seq"])
            self._restarts = int(ckpt.get("restarts", 0)) + 1
            # keep routing-table versions advancing across restarts so
            # proxy route-sync loops never skip the post-recovery update
            self._version = int(ckpt.get("reconciler_version", 0)) + 1
        self._m_restarts.inc()
        self._recovery_s = obs.clock() - t0
        self._m_recovery.observe(self._recovery_s)
        self._recovered_at = obs.wall()
        logger.warning(
            "serve controller recovered from checkpoint seq=%s: %d app(s), "
            "%d replica(s) adopted, %d orphan(s) reaped, in %.3fs",
            ckpt["seq"], len(apps), adopted, reaped, self._recovery_s,
        )
        self._checkpoint("recovered")

    def _adopt_replica(
        self, aid: bytes, rep: dict, cfg: DeploymentConfig
    ) -> _ReplicaState | None:
        """Re-resolve one checkpointed replica actor; None when it died
        during the outage (the convergence step replaces it)."""
        worker = ray_tpu.worker.global_worker()
        try:
            info = worker.gcs.call("get_actor", {"actor_id": aid})["actor"]
        except Exception as e:  # noqa: BLE001 — GCS hiccup: treat as dead
            logger.warning(
                "controller recovery: get_actor(%s) failed: %r",
                aid.hex(), e,
            )
            return None
        if info is None or info.get("state") == "DEAD":
            return None
        r = _ReplicaState(
            ActorHandle(ActorID(aid), info.get("class_name", "ReplicaActor"))
        )
        state = rep.get("state", "STARTING")
        if state == "RUNNING":
            # adopt via the existing ping path: next_ping_at=0 makes the
            # first health-check pass validate it NOW; a replica wedged
            # during the outage is killed and replaced like any other
            r.state = "RUNNING"
            r.next_ping_at = 0.0
        elif state == "DRAINING":
            r.state = "DRAINING"
            remaining = rep.get("drain_remaining_s")
            if remaining is None:
                remaining = cfg.graceful_shutdown_timeout_s
            r.drain_deadline = obs.clock() + float(remaining)
            try:
                # idempotent re-latch: the pre-crash prepare_drain may or
                # may not have landed; this also doubles as the first
                # drain_status poll for _advance_drains
                r.drain_ref = r.handle.rt_call.remote(
                    "prepare_drain", (), {}
                )
            except Exception as e:  # noqa: BLE001 — died just now; the
                logger.warning(     # reconcile pass reaps it
                    "controller recovery: prepare_drain re-latch failed "
                    "for %s: %r", aid.hex(), e,
                )
        # STARTING replicas stay STARTING: the readiness probe re-runs
        return r

    def _reap_orphans(self, known: set[bytes]) -> int:
        """Kill live ReplicaActors the checkpoint doesn't know. Only ever
        called with a checkpoint in hand — a fresh boot must not reap
        (it has no roster to judge against)."""
        worker = ray_tpu.worker.global_worker()
        try:
            actors = worker.gcs.call("list_actors")["actors"]
        except Exception as e:  # noqa: BLE001 — skip the sweep this boot
            logger.warning(
                "controller recovery: list_actors failed, orphan sweep "
                "skipped: %r", e,
            )
            return 0
        reaped = 0
        for a in actors:
            if a.get("class_name") != "ReplicaActor":
                continue
            if a.get("state") == "DEAD" or a["actor_id"] in known:
                continue
            try:
                ray_tpu.kill(
                    ActorHandle(ActorID(a["actor_id"]), "ReplicaActor")
                )
                reaped += 1
            except Exception as e:  # noqa: BLE001 — died on its own
                logger.warning(
                    "controller recovery: orphan %s kill failed: %r",
                    a["actor_id"].hex(), e,
                )
        if reaped:
            self._m_orphans.inc(reaped)
            logger.warning(
                "controller recovery: reaped %d orphan replica(s) the "
                "checkpoint did not know", reaped,
            )
        return reaped

    def _readopt_proxies(
        self, proxy_cfg
    ) -> dict[bytes, "_ProxyState"]:
        """Re-adopt per-node proxy actors by their well-known names.
        Adopted proxies re-enter the ping path as STARTING, which
        re-learns their bound addresses without a restart."""
        proxies: dict[bytes, _ProxyState] = {}
        if proxy_cfg is None:
            return proxies
        worker = ray_tpu.worker.global_worker()
        try:
            nodes = worker.gcs.call("get_nodes")["nodes"]
        except Exception as e:  # noqa: BLE001 — reconcile restarts them
            logger.warning(
                "controller recovery: get_nodes failed, proxies will be "
                "restarted by reconcile: %r", e,
            )
            return proxies
        for n in nodes:
            if not n.get("alive"):
                continue
            nid = n["node_id"]
            try:
                handle = ray_tpu.get_actor(
                    f"RT_SERVE_PROXY:{nid.hex()[:12]}"
                )
            except ValueError:
                logger.info(
                    "controller recovery: no proxy on node %s yet",
                    nid.hex()[:12],
                )
                continue  # reconcile starts one
            proxies[nid] = _ProxyState(handle)
        return proxies
