"""gRPC ingress proxy — unary and server-streaming entry into Serve.

Equivalent of the reference's gRPC proxy (reference:
python/ray/serve/_private/proxy.py:975 gRPCProxy; serve.proto
RayServeAPIService). Design difference: instead of protoc-generated user
services, a single generic service with byte payloads — no codegen step,
any gRPC client can call it:

  service: ray_tpu.serve.ServeAPI
    rpc Call   (bytes) returns (bytes)          — unary request/response
    rpc Stream (bytes) returns (stream bytes)   — server streaming (LLM
                                                  token decode)
    rpc Healthz (bytes) returns (bytes)         — controller-independent
                                                  readiness probe

Request bytes are a JSON payload (or raw bytes if not JSON). Routing
metadata keys (matching the reference's proxy metadata contract):
  "application" — app name (default "default")
  "method"      — deployment method (default "__call__")
  "x-ray-tpu-priority" — LLM scheduling class ("interactive" |
                  "default" | "batch"), injected into dict payloads as
                  ``priority`` (docs/SERVING_LLM.md "Priority &
                  preemption")
Response chunks: bytes pass through raw; any other value is JSON-encoded.
"""
from __future__ import annotations

import contextlib
import json
import logging
import threading
import time
import uuid
from typing import Any

from ray_tpu.exceptions import (
    DeadlineExceededError,
    EngineOverloadedError,
    RequestCancelledError,
    TaskError,
)
from ray_tpu.serve.proxy import (
    PRIORITY_HEADER,
    TRACE_HEADER,
    TRACE_ID_HEADER,
    head_sampler,
    log_access,
)
from ray_tpu.util import tracing

logger = logging.getLogger("ray_tpu.serve.grpc")

SERVICE_NAME = "ray_tpu.serve.ServeAPI"
CALL_METHOD = f"/{SERVICE_NAME}/Call"
STREAM_METHOD = f"/{SERVICE_NAME}/Stream"
HEALTHZ_METHOD = f"/{SERVICE_NAME}/Healthz"

_APP_CACHE_TTL_S = 2.0


def _encode(value: Any) -> bytes:
    if isinstance(value, bytes):
        return value
    return json.dumps({"result": value}).encode()


def _decode(request: bytes) -> Any:
    if not request:
        return None
    try:
        return json.loads(request)
    except (json.JSONDecodeError, UnicodeDecodeError):
        return request


def _unwrap(e: BaseException) -> BaseException:
    if isinstance(e, TaskError) and e.cause is not None:
        return e.cause
    return e


def _code_for(e: BaseException, priority: str | None = None):
    """Degradation statuses (mirrors the HTTP proxy's _status_for):
    overload -> RESOURCE_EXHAUSTED (retryable), blown deadline ->
    DEADLINE_EXCEEDED, cancelled -> CANCELLED, else INTERNAL. Overload
    responses are counted per priority class (``priority`` comes from
    the request's metadata/payload) so operators can see WHICH class is
    being degraded — under class-aware shedding, batch sheds first."""
    import grpc

    from ray_tpu.util import metrics

    e = _unwrap(e)
    if isinstance(e, EngineOverloadedError):
        metrics.counter(
            "serve_requests_shed",
            "Requests rejected with an overload status at a proxy, "
            "by priority class",
            tag_keys=("proxy", "priority"),
        ).inc(tags={"proxy": "grpc", "priority": priority or "default"})
        return grpc.StatusCode.RESOURCE_EXHAUSTED
    if isinstance(e, DeadlineExceededError):
        return grpc.StatusCode.DEADLINE_EXCEEDED
    if isinstance(e, RequestCancelledError):
        return grpc.StatusCode.CANCELLED
    if isinstance(e, ValueError):
        # request validation (incl. structured.GrammarError for a bad
        # response_format) — the client's error, mirrors HTTP 400
        return grpc.StatusCode.INVALID_ARGUMENT
    return grpc.StatusCode.INTERNAL


class GrpcProxy:
    def __init__(self, options):
        self.options = options
        self._head_sample = head_sampler(
            f"grpc:{options.host}:{options.port}",
            getattr(options, "trace_sample_rate", 0.0))
        self._sample_lock = threading.Lock()  # handlers run on a pool
        self._server = None
        self.port: int | None = None
        # app name -> (ingress deployment, fetched_at)
        self._ingress_cache: dict[str, tuple[str, float]] = {}
        self._cache_lock = threading.Lock()

    # -- routing --

    def _ingress_for(self, app_name: str) -> str:
        now = time.monotonic()
        with self._cache_lock:
            hit = self._ingress_cache.get(app_name)
            if hit is not None and now - hit[1] < _APP_CACHE_TTL_S:
                return hit[0]
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            table = ray_tpu.get(
                controller.get_routing_table.remote(), timeout=5
            )
        except Exception as e:  # noqa: BLE001 — controller outage: keep
            if hit is not None:  # serving the expired-but-known mapping
                logger.warning(
                    "gRPC ingress lookup for %r failed (controller "
                    "down?); serving cached mapping: %r", app_name, e,
                )
                return hit[0]
            raise
        app = table["apps"].get(app_name)
        if app is None:
            raise KeyError(f"no serve application named {app_name!r}")
        with self._cache_lock:
            self._ingress_cache[app_name] = (app["ingress"], now)
        return app["ingress"]

    def _target(self, context) -> tuple[str, str]:
        md = {k: v for k, v in (context.invocation_metadata() or ())}
        return md.get("application", "default"), md.get("method", "__call__")

    def _traced(self, md: dict) -> bool:
        """Trace when the client opted in via metadata, else head-sample
        (handlers run on a thread pool, so the shared seeded RNG is
        guarded by a lock)."""
        if TRACE_HEADER in md:
            return True
        with self._sample_lock:
            return self._head_sample()

    def _dispatch(self, request: bytes, context, state: dict | None = None):
        """-> (response, cancel) where cancel() best-effort cancels the
        request on whichever replica serves it (None for unary calls).
        ``state`` (access-log accumulator) picks up the request id."""
        from ray_tpu.serve.handle import DeploymentHandle

        app_name, method = self._target(context)
        md = {k: v for k, v in (context.invocation_metadata() or ())}
        ingress = self._ingress_for(app_name)
        handle = DeploymentHandle(ingress, app_name).options(
            stream_chunk_timeout_s=self.options.request_timeout_s)
        payload = _decode(request)
        cancel = None
        if isinstance(payload, dict):
            try:
                streaming = method in handle.stream_methods()
            except Exception:  # noqa: BLE001 — best-effort tag
                streaming = False
            if streaming:
                payload = dict(payload)
                payload.setdefault("request_id", uuid.uuid4().hex)
                # priority class rides the metadata (payload key wins);
                # class-aware shedding + per-class overload accounting
                # key on it
                if PRIORITY_HEADER in md:
                    payload.setdefault("priority", md[PRIORITY_HEADER])
                rid = payload["request_id"]
                if state is not None:
                    state["request_id"] = rid

                def cancel():
                    threading.Thread(
                        target=lambda: handle.broadcast("cancel", rid),
                        daemon=True, name="serve-grpc-cancel",
                    ).start()

            if state is not None and payload.get("priority"):
                state["priority"] = str(payload["priority"])

        if method == "__call__":
            return handle.remote(payload), cancel
        return getattr(handle, method).remote(payload), cancel

    # -- rpc handlers --

    def _call(self, request: bytes, context) -> bytes:
        import grpc

        from ray_tpu.serve.handle import DeploymentResponseGenerator

        md = {k: v for k, v in (context.invocation_metadata() or ())}
        state: dict = {"t0": time.perf_counter()}
        # gRPC handlers run on their own worker thread, so the root span
        # opens inline (cf. the HTTP proxy, which must open it on the
        # executor thread); opt-in via the TRACE_HEADER metadata key
        root = (
            tracing.span("grpc.request", rpc="Call",
                         method=md.get("method", "__call__"))
            if self._traced(md) else contextlib.nullcontext({})
        )
        try:
            with root as ctx:
                if ctx.get("trace_id"):
                    state["trace_id"] = ctx["trace_id"]
                    context.send_initial_metadata(
                        ((TRACE_ID_HEADER, ctx["trace_id"]),))
                response, _cancel = self._dispatch(request, context, state)
                if isinstance(response, DeploymentResponseGenerator):
                    # unary call on a streaming method: drain into a list.
                    # Deliberate but surprising — tell the client (the
                    # Stream rpc is the intended entry; reference proxies
                    # reject this)
                    import logging

                    logging.getLogger("ray_tpu.serve").warning(
                        "unary Call on a streaming deployment method — "
                        "draining the full stream into one response; use "
                        "the Stream rpc for incremental chunks")
                    context.set_trailing_metadata(
                        (("ray-tpu-streaming-drained", "true"),))
                    # the drain respects the TOTAL request budget, not just
                    # per-chunk gaps — else a slow long generator pins one
                    # of the fixed worker threads indefinitely
                    budget = self.options.request_timeout_s
                    deadline = (time.monotonic() + budget
                                if budget is not None else None)
                    chunks = []
                    for chunk in response:
                        chunks.append(chunk)
                        if deadline is not None and time.monotonic() > deadline:
                            context.abort(
                                grpc.StatusCode.DEADLINE_EXCEEDED,
                                f"streaming drain exceeded request_timeout_s="
                                f"{budget}; use the Stream rpc")
                    state["tokens"] = len(chunks)
                    log_access("grpc", CALL_METHOD, state, status="OK")
                    return _encode(chunks)
                out = response.result(
                    timeout=self.options.request_timeout_s)
                log_access("grpc", CALL_METHOD, state, status="OK")
                return _encode(out)
        except KeyError as e:
            log_access("grpc", CALL_METHOD, state,
                       status="NOT_FOUND", error=str(e))
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except Exception as e:  # noqa: BLE001 — surface to the client
            code = _code_for(e, state.get("priority"))
            log_access("grpc", CALL_METHOD, state,
                       status=code.name, error=str(e))
            context.abort(code, str(e))

    def _stream(self, request: bytes, context):
        import grpc

        from ray_tpu.serve.handle import DeploymentResponseGenerator

        md = {k: v for k, v in (context.invocation_metadata() or ())}
        state: dict = {"t0": time.perf_counter()}
        try:
            # span covers the dispatch only — the .remote() below captures
            # trace_ctx into the task spec; chunk pulls need no context
            root = (
                tracing.span("grpc.request", rpc="Stream",
                             method=md.get("method", "__call__"))
                if self._traced(md) else contextlib.nullcontext({})
            )
            with root as ctx:
                if ctx.get("trace_id"):
                    state["trace_id"] = ctx["trace_id"]
                response, cancel = self._dispatch(request, context, state)
        except KeyError as e:
            log_access("grpc", STREAM_METHOD, state,
                       status="NOT_FOUND", error=str(e))
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            return
        except Exception as e:  # noqa: BLE001
            code = _code_for(e, state.get("priority"))
            log_access("grpc", STREAM_METHOD, state,
                       status=code.name, error=str(e))
            context.abort(code, str(e))
            return
        if "trace_id" in state:
            # echo the assigned trace id before the first chunk, mirroring
            # the HTTP proxy's response header
            context.send_initial_metadata(
                ((TRACE_ID_HEADER, state["trace_id"]),))
        finished = threading.Event()
        if cancel is not None:
            # fires when the RPC terminates for ANY reason; only a client
            # cancel/disconnect leaves `finished` unset -> free the
            # replica-side sequence instead of generating into the void
            context.add_callback(
                lambda: None if finished.is_set() else cancel())
        try:
            if isinstance(response, DeploymentResponseGenerator):
                for chunk in response:
                    if "ttft_ms" not in state:
                        state["ttft_ms"] = round(
                            (time.perf_counter() - state["t0"]) * 1000.0, 3)
                    state["tokens"] = state.get("tokens", 0) + 1
                    yield _encode(chunk)
            else:
                yield _encode(
                    response.result(timeout=self.options.request_timeout_s))
            finished.set()
            log_access("grpc", STREAM_METHOD, state, status="OK")
        except Exception as e:  # noqa: BLE001
            finished.set()
            code = _code_for(e, state.get("priority"))
            log_access("grpc", STREAM_METHOD, state,
                       status=code.name, error=str(e))
            context.abort(code, str(e))

    def _healthz(self, request: bytes, context) -> bytes:
        """Controller-independent readiness probe (mirrors the HTTP
        proxy's /healthz): answers from purely local state so load
        balancers keep this proxy in rotation through a controller
        outage — requests still route from cached tables."""
        return b'{"status":"ok"}'

    # -- server lifecycle --

    def start(self) -> None:
        import grpc
        from concurrent import futures

        identity = lambda x: x  # noqa: E731 — raw-bytes (de)serializer

        handlers = {
            "Call": grpc.unary_unary_rpc_method_handler(
                self._call, request_deserializer=identity,
                response_serializer=identity,
            ),
            "Stream": grpc.unary_stream_rpc_method_handler(
                self._stream, request_deserializer=identity,
                response_serializer=identity,
            ),
            "Healthz": grpc.unary_unary_rpc_method_handler(
                self._healthz, request_deserializer=identity,
                response_serializer=identity,
            ),
        }
        generic = grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="serve-grpc"
            )
        )
        self._server.add_generic_rpc_handlers((generic,))
        self.port = self._server.add_insecure_port(
            f"{self.options.host}:{self.options.port}"
        )
        if self.port == 0:
            raise RuntimeError(
                f"gRPC proxy failed to bind "
                f"{self.options.host}:{self.options.port}"
            )
        self._server.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None
