"""Fleet trace store — central span collection with tail-based sampling.

The controller drains every replica/proxy process's bounded span buffer
(``tracing.drain_buffered_spans`` piggybacked on the ``metrics_report``
poll) into one ``TraceStore`` per controller: a bounded, ring-style map
of trace id -> span list, assembled on demand into per-trace trees that
cross process boundaries (proxy -> router -> prefill replica -> decode
replica). Like the ``FleetAggregator`` history rings it is deliberately
NOT checkpointed — traces are a debugging aid, not serving state, and a
recovered controller starts collecting again from live traffic.

Retention is TAIL-based: a trace's fate is decided by what happened to
it, not at ingest. The store always keeps traces that hit an error /
deadline expiry / admission shed / preemption / mid-stream failover /
handoff retry, plus a reservoir of the slowest-TTFT traces; the
remaining (boring) traces survive eviction only if a deterministic
per-trace-id sample selects them. Eviction only triggers past
``max_traces`` and removes the least interesting, oldest traces first.
"""
from __future__ import annotations

import zlib

__all__ = ["TraceStore", "RETENTION_FLAGS"]

# every tail-retention trigger the classifier can raise; docs list these
RETENTION_FLAGS = (
    "error", "deadline", "shed", "preempted", "failover", "handoff-retry",
)

# terminal engine finish_reasons mapped to retention flags
_ERROR_REASONS = frozenset({"failed", "cancelled", "shutdown"})


def sample_decision(trace_id: str, rate: float) -> bool:
    """Deterministic head/tail sampling decision for one trace id: the
    same id always lands on the same side of the rate, so every process
    (and every test) agrees without coordination. No RNG state — the
    decision is a pure hash of the id."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(trace_id.encode()) % 10_000) < rate * 10_000


class _Trace:
    __slots__ = ("trace_id", "spans", "flags", "first_stamp", "last_stamp",
                 "ttft_s", "app", "engine_requests", "span_ids")

    def __init__(self, trace_id: str, stamp: float):
        self.trace_id = trace_id
        self.spans: list[dict] = []
        self.flags: set[str] = set()
        self.first_stamp = stamp
        self.last_stamp = stamp
        self.ttft_s: float | None = None
        self.app: str | None = None
        self.engine_requests = 0
        self.span_ids: set[str] = set()

    @property
    def start(self) -> float:
        return min(s["start"] for s in self.spans)

    @property
    def end(self) -> float:
        return max(s["end"] for s in self.spans)


class TraceStore:
    """Bounded per-controller trace collection (see module docstring).

    ``max_traces`` bounds the trace count and ``max_spans_per_trace``
    bounds any one trace (a runaway stream must not eat the store);
    ``sample_rate`` is the keep-probability for traces no retention
    trigger fired on; ``ttft_reservoir`` is how many slowest-TTFT traces
    ride out eviction regardless of sampling."""

    def __init__(self, *, max_traces: int = 512,
                 max_spans_per_trace: int = 512,
                 sample_rate: float = 0.1,
                 ttft_reservoir: int = 32):
        self.max_traces = int(max_traces)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.sample_rate = float(sample_rate)
        self.ttft_reservoir = int(ttft_reservoir)
        self._traces: dict[str, _Trace] = {}
        self.ingested_spans = 0
        self.dropped_spans = 0       # per-trace span-cap overflow
        self.evicted_traces = 0
        self.retained_traces = 0     # evictions AVOIDED by a flag/reservoir

    # ---------------- ingest ----------------

    def ingest(self, spans: list[dict], *, source: str,
               stamp: float) -> int:
        """Fold one process's drained span buffer in. ``source`` labels
        each span with the process it came from (``replica:<id>`` /
        ``proxy:<id>`` / ``controller``); ``stamp`` is the controller's
        clock at ingest (eviction ordering — span start/end stay wall
        times from the emitting process)."""
        n = 0
        for s in spans:
            tid = s.get("trace_id")
            sid = s.get("span_id")
            if not tid or not sid:
                continue  # not a span shape we understand: skip, count
            t = self._traces.get(tid)
            if t is None:
                t = self._traces[tid] = _Trace(tid, stamp)
            if sid in t.span_ids:
                continue  # re-delivered (poll retry) — exactly-once
            if len(t.spans) >= self.max_spans_per_trace:
                self.dropped_spans += 1
                continue
            rec = dict(s)
            rec["source"] = source
            t.spans.append(rec)
            t.span_ids.add(sid)
            t.last_stamp = stamp
            self._classify(t, rec)
            n += 1
        self.ingested_spans += n
        if len(self._traces) > self.max_traces:
            self._evict()
        return n

    def _classify(self, t: _Trace, span: dict) -> None:
        """Raise retention flags from one span — the tail-sampling
        triggers. Called per ingested span so a trace's fate is always
        current when eviction runs."""
        name = span.get("name") or ""
        attrs = span.get("attrs") or {}
        if name == "engine.request":
            t.engine_requests += 1
            if t.engine_requests >= 2:
                # two engine.request spans under one trace = the stream
                # was re-dispatched to a second replica mid-flight
                t.flags.add("failover")
            reason = attrs.get("finish_reason")
            if reason == "expired":
                t.flags.add("deadline")
            elif reason in _ERROR_REASONS:
                t.flags.add("error")
            if attrs.get("preempt_count"):
                t.flags.add("preempted")
            ttft = attrs.get("ttft_s")
            if ttft is not None:
                # a resumed stream's second engine.request has no first
                # token of its own — keep the first observed TTFT
                if t.ttft_s is None:
                    t.ttft_s = float(ttft)
        elif name == "engine.preempted":
            t.flags.add("preempted")
        elif name == "handle.resume":
            t.flags.add("failover")
        elif name == "handle.shed":
            t.flags.add("shed")
        elif name.startswith("handoff."):
            if attrs.get("attempt"):
                t.flags.add("handoff-retry")
        elif name in ("handle.dispatch", "http.request", "grpc.call",
                      "grpc.stream"):
            dep = attrs.get("deployment") or attrs.get("app")
            if dep and t.app is None:
                t.app = str(dep).split("/", 1)[0]

    # ---------------- eviction (tail sampling) ----------------

    def _keep_rank(self, t: _Trace, reservoir: set[str]) -> int:
        """2 = always keep (flagged, or slowest-TTFT reservoir member),
        1 = kept by the deterministic sample, 0 = evict first."""
        if t.flags or t.trace_id in reservoir:
            return 2
        if sample_decision(t.trace_id, self.sample_rate):
            return 1
        return 0

    def _ttft_reservoir_ids(self) -> set[str]:
        with_ttft = [t for t in self._traces.values() if t.ttft_s is not None]
        with_ttft.sort(key=lambda t: -t.ttft_s)
        return {t.trace_id for t in with_ttft[: self.ttft_reservoir]}

    def _evict(self) -> None:
        reservoir = self._ttft_reservoir_ids()
        order = sorted(
            self._traces.values(),
            key=lambda t: (self._keep_rank(t, reservoir), t.first_stamp),
        )
        excess = len(self._traces) - self.max_traces
        for t in order[:excess]:
            if self._keep_rank(t, reservoir) == 2:
                # the store is full of must-keep traces: count the
                # retention we honored, then age out the oldest anyway
                # (bounded beats complete)
                self.retained_traces += 1
            del self._traces[t.trace_id]
            self.evicted_traces += 1

    # ---------------- query ----------------

    def __len__(self) -> int:
        return len(self._traces)

    def __contains__(self, trace_id: str) -> bool:
        return trace_id in self._traces

    def status_of(self, t: _Trace, reservoir: set[str] | None = None) -> list:
        out = sorted(t.flags)
        if not out:
            if reservoir is None:
                reservoir = self._ttft_reservoir_ids()
            out = ["slow" if t.trace_id in reservoir else "sampled"]
        return out

    def _summary(self, t: _Trace, reservoir: set[str]) -> dict:
        start, end = t.start, t.end
        return {
            "trace_id": t.trace_id,
            "app": t.app,
            "status": self.status_of(t, reservoir),
            "spans": len(t.spans),
            "start": start,
            "duration_s": round(end - start, 6),
            "ttft_s": t.ttft_s,
        }

    def list_traces(self, *, app: str | None = None,
                    status: str | None = None,
                    min_duration_s: float | None = None,
                    limit: int = 100) -> list[dict]:
        """Trace summaries, newest first, filtered by app / retention
        status / minimum duration — the ``/api/traces`` payload."""
        reservoir = self._ttft_reservoir_ids()
        rows = []
        for t in sorted(self._traces.values(),
                        key=lambda t: -t.last_stamp):
            if not t.spans:
                continue
            row = self._summary(t, reservoir)
            if app is not None and row["app"] != app:
                continue
            if status is not None and status not in row["status"]:
                continue
            if (min_duration_s is not None
                    and row["duration_s"] < float(min_duration_s)):
                continue
            rows.append(row)
            if len(rows) >= limit:
                break
        return rows

    def spans_of(self, trace_id: str) -> list[dict] | None:
        t = self._traces.get(trace_id)
        if t is None:
            return None
        return list(t.spans)

    def assemble(self, trace_id: str) -> dict | None:
        """One trace as a nested span tree (children under
        parent_span_id; spans whose parent was never collected — e.g.
        sampled out on another process — surface as roots so a partial
        trace still renders). The ``/api/traces/<id>`` payload."""
        t = self._traces.get(trace_id)
        if t is None or not t.spans:
            return None
        by_id = {s["span_id"]: dict(s, children=[]) for s in t.spans}
        roots = []
        for node in sorted(by_id.values(), key=lambda s: s["start"]):
            parent = node.get("parent_span_id")
            if parent and parent in by_id and parent != node["span_id"]:
                by_id[parent]["children"].append(node)
            else:
                roots.append(node)
        return {
            "trace_id": trace_id,
            "status": self.status_of(t),
            "app": t.app,
            "start": t.start,
            "duration_s": round(t.end - t.start, 6),
            "ttft_s": t.ttft_s,
            "span_count": len(t.spans),
            "sources": sorted({s.get("source", "") for s in t.spans}),
            "tree": roots,
        }

    def exemplar_ids(self, *, flags: tuple | None = None,
                     slowest_ttft: bool = False, n: int = 3) -> list[str]:
        """Trace ids for SLO exemplars: either the newest traces carrying
        one of ``flags``, or the slowest-TTFT traces — the link from a
        burning SLO back into the trace plane."""
        if slowest_ttft:
            with_ttft = [t for t in self._traces.values()
                         if t.ttft_s is not None]
            with_ttft.sort(key=lambda t: -t.ttft_s)
            return [t.trace_id for t in with_ttft[:n]]
        want = set(flags or ())
        hits = [t for t in self._traces.values() if t.flags & want]
        hits.sort(key=lambda t: -t.last_stamp)
        return [t.trace_id for t in hits[:n]]

    def stats(self) -> dict:
        return {
            "traces": len(self._traces),
            "ingested_spans": self.ingested_spans,
            "dropped_spans": self.dropped_spans,
            "evicted_traces": self.evicted_traces,
            "retained_over_evict": self.retained_traces,
        }
