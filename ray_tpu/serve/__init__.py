"""ray_tpu.serve — model serving on the distributed core.

Controller/reconciler + replica actors + client-side power-of-two routing +
shape-aware dynamic batching + aiohttp ingress (reference: python/ray/serve —
surveyed in SURVEY.md §2.3 A4). TPU-first: replicas hold chips via actor
resources, and batching pads to fixed size buckets so jitted models never
recompile (SURVEY.md §7 hard parts).
"""
from ray_tpu.serve.api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    grpc_port,
    proxy_addresses,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch, pad_to_bucket
from ray_tpu.serve.multiplex import multiplexed
from ray_tpu.serve.config import (
    AutoscalingConfig,
    BatchConfig,
    DeploymentConfig,
    GrpcOptions,
    HTTPOptions,
)
from ray_tpu.serve.deployment import Application, Deployment, deployment
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)

__all__ = [
    "Application",
    "AutoscalingConfig",
    "BatchConfig",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "GrpcOptions",
    "HTTPOptions",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "grpc_port",
    "multiplexed",
    "pad_to_bucket",
    "proxy_addresses",
    "run",
    "shutdown",
    "start",
    "status",
]


from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("serve")
del _rlu
