"""Dynamic batching — marker decorator + shape-bucket padding helpers.

Equivalent of the reference's @serve.batch (reference: python/ray/serve/
batching.py:337 _BatchQueue coalescing). Coalescing itself happens
REPLICA-side in replica.py's _ReplicaBatchQueue — all callers of a replica
(every driver/proxy process) share one queue, as in the reference — on the
actor's max_ongoing_requests method pool. TPU-first addition kept from the
earlier router design: batches pad to fixed size BUCKETS so a jitted model
sees a closed set of batch shapes (no XLA recompiles — SURVEY.md §7 hard
parts: shape-aware batching).
"""
from __future__ import annotations

from typing import Callable

from ray_tpu.serve._shapes import pad_to_bucket  # noqa: F401 — re-export;
# the one shared padding rule (also used by serve/llm/engine.py)
from ray_tpu.serve.config import BatchConfig

_BATCH_ATTR = "__rt_serve_batch__"


def batch(
    _func: Callable | None = None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
    size_buckets: tuple[int, ...] | None = None,
):
    """Mark a deployment method as batched: the router coalesces up to
    ``max_batch_size`` concurrent calls (waiting at most
    ``batch_wait_timeout_s``) and the method receives a LIST of the single
    call payloads, returning a list of results in order.
    """

    def wrap(func):
        setattr(
            func,
            _BATCH_ATTR,
            BatchConfig(
                max_batch_size=max_batch_size,
                batch_wait_timeout_s=batch_wait_timeout_s,
                size_buckets=size_buckets,
            ),
        )
        return func

    return wrap if _func is None else wrap(_func)


def get_batch_config(func) -> BatchConfig | None:
    return getattr(func, _BATCH_ATTR, None)
