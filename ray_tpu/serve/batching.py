"""Dynamic batching — marker decorator + shape-bucket padding helpers.

Equivalent of the reference's @serve.batch (reference: python/ray/serve/
batching.py:337 _BatchQueue coalescing). Architectural deviation, TPU-first:
our replicas execute one method at a time (ordered actor queue), so batching
happens in the ROUTER — calls are coalesced client-side and shipped as one
actor task. This also lets the batcher pad to fixed size buckets so a jitted
TPU model sees a closed set of batch shapes (no XLA recompiles), which the
reference's batcher cannot do (SURVEY.md §7 hard parts: shape-aware batching).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable

from ray_tpu.serve.config import BatchConfig

_BATCH_ATTR = "__rt_serve_batch__"


def batch(
    _func: Callable | None = None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
    size_buckets: tuple[int, ...] | None = None,
):
    """Mark a deployment method as batched: the router coalesces up to
    ``max_batch_size`` concurrent calls (waiting at most
    ``batch_wait_timeout_s``) and the method receives a LIST of the single
    call payloads, returning a list of results in order.
    """

    def wrap(func):
        setattr(
            func,
            _BATCH_ATTR,
            BatchConfig(
                max_batch_size=max_batch_size,
                batch_wait_timeout_s=batch_wait_timeout_s,
                size_buckets=size_buckets,
            ),
        )
        return func

    return wrap if _func is None else wrap(_func)


def get_batch_config(func) -> BatchConfig | None:
    return getattr(func, _BATCH_ATTR, None)


def pad_to_bucket(n: int, buckets: tuple[int, ...]) -> int:
    """Smallest bucket >= n (last bucket if none fits)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class RouterBatcher:
    """Client-side coalescer for one (deployment, method).

    submit() returns a Future resolved with that call's single result once
    the flushed actor call completes. Flush happens when max_batch_size
    accumulate or the oldest call has waited batch_wait_timeout_s.
    """

    def __init__(self, config: BatchConfig, flush_fn: Callable[[list], list]):
        self._config = config
        # a batch may never exceed the largest bucket, or the padded-shape
        # guarantee breaks (an oversized batch would ship unpadded)
        self._max_batch = config.max_batch_size
        if config.size_buckets:
            self._max_batch = min(self._max_batch, config.size_buckets[-1])
        self._flush_fn = flush_fn  # list[payload] -> list[result] (blocking)
        self._lock = threading.Lock()
        self._pending: list[tuple[Any, Future]] = []
        self._timer: threading.Timer | None = None

    def submit(self, payload: Any) -> Future:
        fut: Future = Future()
        flush_now = None
        with self._lock:
            self._pending.append((payload, fut))
            if len(self._pending) >= self._max_batch:
                flush_now = self._take_locked()
            elif self._timer is None:
                self._timer = threading.Timer(
                    self._config.batch_wait_timeout_s, self._flush_timeout
                )
                self._timer.daemon = True
                self._timer.start()
        if flush_now:
            self._run_flush(flush_now)
        return fut

    def _take_locked(self) -> list[tuple[Any, Future]]:
        batch_items, self._pending = self._pending, []
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        return batch_items

    def _flush_timeout(self) -> None:
        with self._lock:
            items = self._take_locked()
        if items:
            self._run_flush(items)

    def _run_flush(self, items: list[tuple[Any, Future]]) -> None:
        def work():
            payloads = [p for p, _ in items]
            n = len(payloads)
            if self._config.size_buckets:
                target = pad_to_bucket(n, self._config.size_buckets)
                payloads = payloads + [None] * (target - n)
            try:
                results = self._flush_fn(payloads)
            except Exception as e:  # noqa: BLE001 — fan the error out
                for _, f in items:
                    f.set_exception(e)
                return
            for (_, f), r in zip(items, results):
                f.set_result(r)

        threading.Thread(target=work, daemon=True).start()

    def flush_and_wait(self, deadline: float) -> None:
        """Test/shutdown helper: force a flush, wait for pending futures."""
        with self._lock:
            items = self._take_locked()
        if items:
            self._run_flush(items)
        for _, f in items:
            f.result(timeout=max(0.0, deadline - time.monotonic()))
