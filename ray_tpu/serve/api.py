"""Serve public API: run / start / status / delete / shutdown / handles.

Equivalent of the reference's serve api surface
(reference: python/ray/serve/api.py — serve.run:479, serve.start,
serve.status, serve.delete, serve.shutdown; handle getters
python/ray/serve/context.py get_deployment_handle).
"""
from __future__ import annotations

import time

import ray_tpu
from ray_tpu.actor import ActorClass
from ray_tpu.serve.config import GrpcOptions, HTTPOptions
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import Application
from ray_tpu.serve.handle import DeploymentHandle, _Router
from ray_tpu.serve.grpc_proxy import GrpcProxy
from ray_tpu.serve.proxy import HTTPProxy

_proxy: HTTPProxy | None = None
_grpc_proxy: GrpcProxy | None = None


# the raylet is the controller's supervisor: on worker death it restarts
# the named actor IN PLACE (same actor id — cached handles keep working)
# and __init__ -> _recover() rebuilds state from the GCS checkpoint.
# Dead-dead (restart budget exhausted) falls back to this module creating
# a fresh actor, which recovers from the same checkpoint; handles pick up
# the new actor id via _Router._invalidate_controller's re-resolve.
_CONTROLLER_MAX_RESTARTS = 100


def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    handle = ActorClass(
        ServeController,
        num_cpus=0.1,
        name=CONTROLLER_NAME,
        max_restarts=_CONTROLLER_MAX_RESTARTS,
    ).remote()
    # wait for liveness so the first deploy call doesn't race startup
    ray_tpu.get(handle.list_applications.remote(), timeout=60)
    return handle


def start(
    http_options: HTTPOptions | dict | None = None,
    grpc_options: GrpcOptions | dict | None = None,
    proxy_location: str = "Driver",
) -> None:
    """Start serve system actors (reference: serve.start;
    proxy_location mirrors serve.config.ProxyLocation).

    proxy_location:
      * "Driver" — dev mode: in-process proxy threads in this driver.
      * "EveryNode" — production shape: the controller keeps one proxy
        ACTOR per alive node, health-checked and restarted on failure
        (reference: serve/_private/proxy_state.py). Use port=0 per
        protocol unless nodes are distinct hosts; read bound ports via
        serve.proxy_addresses().
    """
    global _proxy, _grpc_proxy
    controller = _get_or_create_controller()
    if proxy_location == "EveryNode":
        ray_tpu.get(
            controller.start_proxies.remote(
                _as_dict(http_options), _as_dict(grpc_options)),
            timeout=60,
        )
        return
    if proxy_location != "Driver":
        raise ValueError(
            f"proxy_location must be 'Driver' or 'EveryNode', "
            f"got {proxy_location!r}")
    if http_options is not None and _proxy is None:
        if isinstance(http_options, dict):
            http_options = HTTPOptions(**http_options)
        _proxy = HTTPProxy(http_options)
        _proxy.start()
    if grpc_options is not None and _grpc_proxy is None:
        if isinstance(grpc_options, dict):
            grpc_options = GrpcOptions(**grpc_options)
        _grpc_proxy = GrpcProxy(grpc_options)
        _grpc_proxy.start()


def _as_dict(options) -> dict | None:
    if options is None:
        return None
    if isinstance(options, dict):
        return dict(options)
    from dataclasses import asdict

    return asdict(options)


def proxy_addresses(timeout_s: float = 30.0) -> dict:
    """hex node_id -> {"http": (host, port), ...} of HEALTHY per-node
    proxies (EveryNode mode). Blocks briefly until at least one proxy is
    up or the timeout passes."""
    controller = _get_or_create_controller()
    deadline = time.monotonic() + timeout_s
    while True:
        addrs = ray_tpu.get(controller.proxy_addresses.remote(), timeout=60)
        if addrs or time.monotonic() > deadline:
            return addrs
        time.sleep(0.1)


def run(
    target: Application,
    *,
    name: str = "default",
    route_prefix: str | None = None,
    _blocking: bool = True,
    timeout_s: float = 120.0,
) -> DeploymentHandle:
    """Deploy an application and (by default) block until healthy
    (reference: serve.run api.py:479)."""
    if not isinstance(target, Application):
        raise TypeError("serve.run expects Deployment.bind(...)")
    controller = _get_or_create_controller()
    apps = target.flatten()
    specs = [a.build_spec(name) for a in apps]
    by_name: dict[str, dict] = {}
    uniq = []
    for s in specs:
        prev = by_name.get(s["name"])
        if prev is not None:
            if (
                prev["callable_blob"] != s["callable_blob"]
                or prev["init_args"] != s["init_args"]
                or prev["init_kwargs"] != s["init_kwargs"]
                or prev["config"] != s["config"]
            ):
                raise ValueError(
                    f"two deployments named {s['name']!r} with different "
                    "bind arguments in one app — give one of them "
                    ".options(name=...) (handles route by name)"
                )
            continue
        by_name[s["name"]] = s
        uniq.append(s)
    ingress = target.deployment.name
    ray_tpu.get(
        controller.deploy_application.remote(name, uniq, ingress, route_prefix),
        timeout=60,
    )
    _Router.reset_all()  # drop stale routing tables from a previous version
    if route_prefix is not None and _proxy is not None:
        _proxy.set_route(route_prefix, name, ingress)
    if _blocking:
        _wait_healthy(controller, name, timeout_s)
    return DeploymentHandle(ingress, name)


def _wait_healthy(controller, app_name: str, timeout_s: float) -> None:
    deadline = time.monotonic() + timeout_s
    st: dict = {}
    while time.monotonic() < deadline:
        st = ray_tpu.get(controller.status.remote(), timeout=60)
        app = st.get(app_name, {})
        if app and all(d["status"] == "HEALTHY" for d in app.values()):
            return
        bad = [
            f"{n}: {d['message']}" for n, d in app.items() if d["status"] == "UNHEALTHY"
        ]
        if bad:
            raise RuntimeError(f"app {app_name} unhealthy: {bad}")
        time.sleep(0.1)
    raise TimeoutError(f"app {app_name} not healthy within {timeout_s}s: {st}")


def status() -> dict:
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=60)


def delete(name: str) -> None:
    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)
    if _proxy is not None:
        _proxy.remove_routes_for_app(name)
    _Router.reset_all()


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _get_or_create_controller()
    table = ray_tpu.get(controller.get_routing_table.remote(), timeout=60)
    app = table["apps"].get(name)
    if app is None:
        raise ValueError(f"no serve application named {name!r}")
    return DeploymentHandle(app["ingress"], name)


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def grpc_port() -> int | None:
    """Bound port of the gRPC ingress (None if not started); useful when
    GrpcOptions.port=0 picked an ephemeral port."""
    return _grpc_proxy.port if _grpc_proxy is not None else None


def shutdown() -> None:
    """Tear down all serve state (reference: serve.shutdown)."""
    global _proxy, _grpc_proxy
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        controller = None
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote(), timeout=60)
        except Exception:  # noqa: BLE001 — already dead is fine
            pass
        ray_tpu.kill(controller)
    if _proxy is not None:
        _proxy.stop()
        _proxy = None
    if _grpc_proxy is not None:
        _grpc_proxy.stop()
        _grpc_proxy = None
    _Router.reset_all()
