"""Declarative SLOs evaluated as multi-window burn rates over the
fleet metrics plane.

An ``SLOSpec`` names an objective over one of three signal shapes the
``FleetAggregator`` history rings already hold (serve/controller.py
polls them; util/metrics.py stores them):

- ``latency``: a histogram family + a threshold — "99% of requests see
  TTFT <= 200ms".  bad_fraction over a window = the fraction of events
  whose bucket is above the threshold.
- ``ratio``: bad-event counter families over total-event counter
  families — availability / error rate.
- ``gauge_floor``: a gauge family that must average >= a floor —
  goodput.  bad_fraction = how far below the floor the windowed average
  sits, as a fraction of the floor.

Burn rate follows the SRE-workbook definition: with an objective of
``p`` the error budget is ``1 - p``; ``burn = bad_fraction / (1 - p)``.
A burn of 1.0 exactly consumes the budget over the window; the monitor
alarms ("burning") only when EVERY configured window exceeds its burn
threshold — the standard multi-window guard against paging on blips
(short window confirms it's current, long window confirms it's real).

The module is pure: ``evaluate()`` takes the aggregator's ``history()``
output and the evaluation clock, returns plain dicts, and touches no
wall clock of its own — the controller stamps everything with
``obs.clock`` (the one-clock rule; lint-enforced).
"""
from __future__ import annotations

from dataclasses import dataclass

__all__ = ["SLOSpec", "default_slos", "evaluate", "parse_series_labels"]

# evaluation windows (seconds) and the burn threshold each must exceed
# before the SLO reports burning — short confirms current, long real
_DEFAULT_WINDOWS = (60.0, 300.0)


@dataclass(frozen=True)
class SLOSpec:
    """One declarative SLO (see module docstring for the three kinds)."""

    name: str                       # stable id: metric label + API key
    kind: str                       # "latency" | "ratio" | "gauge_floor"
    objective: float = 0.99         # good-event target (budget = 1 - obj)
    # latency:
    family: str | None = None       # histogram family, e.g. llm_ttft_seconds
    threshold_s: float | None = None
    # ratio:
    bad_families: tuple = ()
    total_families: tuple = ()      # totals = bad + these (bad is counted in)
    # gauge_floor:
    floor: float | None = None
    label_filters: tuple = ()       # ((key, value), ...) series must match
    windows_s: tuple = _DEFAULT_WINDOWS
    burn_threshold: float = 1.0
    # how the controller picks exemplar traces when this SLO burns:
    # "slowest_ttft" or a retention flag name from trace_store
    exemplar: str = "slowest_ttft"
    description: str = ""

    def __post_init__(self):
        if self.kind not in ("latency", "ratio", "gauge_floor"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not 0.0 < self.objective < 1.0 and self.kind != "gauge_floor":
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}")
        if self.kind == "latency" and (
                self.family is None or self.threshold_s is None):
            raise ValueError(f"latency SLO {self.name!r} needs family "
                             "and threshold_s")
        if self.kind == "ratio" and not self.bad_families:
            raise ValueError(f"ratio SLO {self.name!r} needs bad_families")
        if self.kind == "gauge_floor" and (
                self.family is None or self.floor is None):
            raise ValueError(f"gauge_floor SLO {self.name!r} needs family "
                             "and floor")


def default_slos() -> tuple:
    """The serving fleet's stock SLOs; apps override by passing their own
    specs to the controller (``serve.start(slos=...)`` stays future work
    — the controller accepts a list at construction)."""
    return (
        SLOSpec(
            name="ttft_p99",
            kind="latency",
            objective=0.99,
            family="llm_ttft_seconds",
            threshold_s=0.5,
            exemplar="slowest_ttft",
            description="99% of requests see first token within 500ms",
        ),
        SLOSpec(
            name="tpot_p99",
            kind="latency",
            objective=0.99,
            family="llm_time_per_output_token_seconds",
            threshold_s=0.2,
            exemplar="slowest_ttft",
            description="99% of inter-token gaps under 200ms",
        ),
        SLOSpec(
            name="availability",
            kind="ratio",
            objective=0.99,
            bad_families=("llm_requests_rejected", "llm_deadline_exceeded",
                          "llm_requests_shed"),
            total_families=("llm_requests_finished",),
            exemplar="error",
            description="99% of requests finish without shed/reject/"
                        "deadline-expiry",
        ),
        SLOSpec(
            name="goodput_floor",
            kind="gauge_floor",
            family="llm_goodput_tokens_per_sec",
            label_filters=(("kind", "decode"),),
            floor=1.0,
            exemplar="slowest_ttft",
            description="windowed decode goodput stays above 1 token/s "
                        "per reporting engine",
        ),
    )


# ---------------- history-ring plumbing ----------------


def parse_series_labels(series_key: str) -> tuple[str, dict]:
    """Invert ``metrics.sample_key``: ``name{k=v,k2=v2}`` ->
    (name, {k: v}). Label values in this codebase never contain commas
    or braces (ids, app names, bucket boundaries)."""
    if "{" not in series_key:
        return series_key, {}
    name, _, rest = series_key.partition("{")
    labels = {}
    for pair in rest.rstrip("}").split(","):
        if not pair:
            continue
        k, _, v = pair.partition("=")
        labels[k] = v
    return name, labels


def _window_delta(ring, now: float, window_s: float) -> float:
    """Cumulative-series delta over [now - window_s, now]: latest value
    minus the newest sample at-or-before the window start. A ring that
    does not span the window yet contributes from its earliest sample
    (conservative: never invents events)."""
    if not ring:
        return 0.0
    latest = ring[-1][1]
    cutoff = now - window_s
    base = ring[0][1]
    for stamp, value in ring:
        if stamp <= cutoff:
            base = value
        else:
            break
    return max(0.0, latest - base)


def _window_avg(ring, now: float, window_s: float) -> float | None:
    """Mean of a gauge ring's samples inside the window (None when the
    window holds no samples)."""
    cutoff = now - window_s
    vals = [v for stamp, v in ring if stamp > cutoff]
    if not vals:
        return None
    return sum(vals) / len(vals)


def _match(labels: dict, filters: tuple) -> bool:
    return all(labels.get(k) == v for k, v in filters)


def _latency_bad_fraction(spec: SLOSpec, history: dict, now: float,
                          window_s: float) -> tuple[float | None, float]:
    """(bad_fraction, events) for one histogram window — None when the
    window saw no events (nothing to judge)."""
    prefix = spec.family + "_bucket"
    # buckets are cumulative per source series: the widest le <= threshold
    # already contains every smaller one, so group by (source labels sans
    # le), take that widest bucket as "good", and the +Inf bucket as the
    # series total
    per_source: dict[tuple, dict] = {}
    for key, ring in history.items():
        name, labels = parse_series_labels(key)
        if name != prefix or not _match(labels, spec.label_filters):
            continue
        le = labels.get("le")
        if le is None:
            continue
        src = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        per_source.setdefault(src, {})[le] = _window_delta(
            ring, now, window_s)
    total = 0.0
    good = 0.0
    for buckets in per_source.values():
        inf = buckets.get("+Inf", 0.0)
        best = 0.0
        for le, delta in buckets.items():
            if le != "+Inf" and float(le) <= spec.threshold_s:
                best = max(best, delta)
        total += inf
        good += min(best, inf)
    if total <= 0.0:
        return None, 0.0
    return max(0.0, 1.0 - good / total), total


def _ratio_bad_fraction(spec: SLOSpec, history: dict, now: float,
                        window_s: float) -> tuple[float | None, float]:
    def fam_delta(families: tuple) -> float:
        out = 0.0
        for key, ring in history.items():
            name, labels = parse_series_labels(key)
            # counter samples carry the Prometheus ``_total`` suffix in
            # the history rings; specs name the bare family
            if name.endswith("_total"):
                name = name[: -len("_total")]
            if name in families and _match(labels, spec.label_filters):
                out += _window_delta(ring, now, window_s)
        return out

    bad = fam_delta(spec.bad_families)
    total = bad + fam_delta(spec.total_families)
    if total <= 0.0:
        return None, 0.0
    return min(1.0, bad / total), total


def _gauge_bad_fraction(spec: SLOSpec, history: dict, now: float,
                        window_s: float) -> tuple[float | None, float]:
    avgs = []
    for key, ring in history.items():
        name, labels = parse_series_labels(key)
        if name != spec.family or not _match(labels, spec.label_filters):
            continue
        avg = _window_avg(ring, now, window_s)
        if avg is not None:
            avgs.append(avg)
    if not avgs:
        return None, 0.0
    value = sum(avgs) / len(avgs)
    if spec.floor <= 0:
        return 0.0, float(len(avgs))
    return max(0.0, 1.0 - value / spec.floor), float(len(avgs))


_KIND_FNS = {
    "latency": _latency_bad_fraction,
    "ratio": _ratio_bad_fraction,
    "gauge_floor": _gauge_bad_fraction,
}


def evaluate(specs, history: dict, now: float) -> list[dict]:
    """Evaluate every spec over the aggregator history rings at clock
    ``now`` (the controller's ``obs.clock()``); -> one result dict per
    spec:  {name, kind, objective, description, burning,
    windows: {"60s": {burn_rate, bad_fraction, events}, ...}}.

    A window with no events contributes burn 0 (no data is not an
    outage — availability of an idle fleet is intact), and an SLO only
    reports burning when every window both saw data and exceeded its
    burn threshold."""
    results = []
    for spec in specs:
        fn = _KIND_FNS[spec.kind]
        budget = max(1e-9, 1.0 - spec.objective)
        windows = {}
        burning = True
        for w in spec.windows_s:
            bad, events = fn(spec, history, now, w)
            if bad is None:
                windows[f"{int(w)}s"] = {
                    "burn_rate": 0.0, "bad_fraction": 0.0,
                    "events": 0.0,
                }
                burning = False
                continue
            burn = bad / budget
            windows[f"{int(w)}s"] = {
                "burn_rate": round(burn, 4),
                "bad_fraction": round(bad, 6),
                "events": round(events, 2),
            }
            if burn < spec.burn_threshold:
                burning = False
        results.append({
            "name": spec.name,
            "kind": spec.kind,
            "objective": spec.objective,
            "description": spec.description,
            "burning": burning,
            "windows": windows,
        })
    return results
