"""Per-node ingress proxy actor, controller-managed.

Equivalent of the reference's proxy actors (reference:
python/ray/serve/_private/proxy_state.py:1 ProxyStateManager — the
controller keeps one HTTP/gRPC proxy actor per node with health states
and restarts them on failure; default_impl.py wires it up). The actor
hosts the same HTTPProxy/GrpcProxy servers the dev-mode driver path
uses, plus a route-sync thread that pulls the controller's versioned
routing table (the same pull protocol handles use) so `serve.run`d route
prefixes appear on every node without any push plumbing.
"""
from __future__ import annotations

import threading
import time

_ROUTE_SYNC_PERIOD_S = 0.25


class ProxyActor:
    """Runs on one node; owns that node's ingress servers."""

    def __init__(self, http_options: dict | None,
                 grpc_options: dict | None):
        from ray_tpu.serve.config import GrpcOptions, HTTPOptions

        self._http = self._grpc = None
        if http_options is not None:
            from ray_tpu.serve.proxy import HTTPProxy

            self._http = HTTPProxy(HTTPOptions(**http_options))
            self._http.start()
        if grpc_options is not None:
            from ray_tpu.serve.grpc_proxy import GrpcProxy

            self._grpc = GrpcProxy(GrpcOptions(**grpc_options))
            self._grpc.start()
        self._stopped = threading.Event()
        self._route_version = None
        if self._http is not None:
            self._sync_thread = threading.Thread(
                target=self._route_sync_loop, daemon=True,
                name="serve-proxy-route-sync")
            self._sync_thread.start()

    # -- controller surface --

    def ping(self) -> dict:
        """Health probe; carries the bound addresses so the controller
        never needs a second (potentially blocking) RPC to learn them."""
        return self.addresses()

    def addresses(self) -> dict:
        """Bound (host, port) per protocol — ports may be ephemeral."""
        out = {}
        if self._http is not None:
            out["http"] = (self._http.options.host, self._http.port)
        if self._grpc is not None:
            out["grpc"] = (self._grpc.options.host, self._grpc.port)
        return out

    def metrics_report(self) -> dict:
        """Fleet-plane snapshot of this proxy process's registry (the
        serve_* ingress counters live here, not in any replica). Same
        shape as ReplicaActor.metrics_report (incl. the piggybacked
        span-buffer drain for the fleet trace plane)."""
        from ray_tpu.util import metrics, tracing

        return {
            "clock": time.perf_counter(),
            "wall": time.time(),
            "families": metrics.collect_families(),
            "spans": tracing.drain_buffered_spans(),
        }

    def stop(self) -> str:
        self._stopped.set()
        if self._http is not None:
            self._http.stop()
        if self._grpc is not None:
            self._grpc.stop()
        return "stopped"

    # -- route sync --

    def _route_sync_loop(self) -> None:
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        controller = None
        while not self._stopped.wait(_ROUTE_SYNC_PERIOD_S):
            try:
                if controller is None:
                    controller = ray_tpu.get_actor(CONTROLLER_NAME)
                table = ray_tpu.get(
                    controller.get_routing_table.remote(), timeout=30)
            except Exception:  # noqa: BLE001 — controller down/restarting
                controller = None
                continue
            if table["version"] == self._route_version:
                continue
            self._route_version = table["version"]
            self._http.replace_routes({
                app["route_prefix"]: (app_name, app["ingress"])
                for app_name, app in table["apps"].items()
                if app.get("route_prefix")
            })


def proxy_actor_options(node_id: bytes) -> dict:
    """ActorClass kwargs pinning one proxy to one node."""
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    return {
        "num_cpus": 0.1,
        "scheduling_strategy": NodeAffinitySchedulingStrategy(
            node_id=node_id, soft=False),
    }
