"""Replica actor wrapper around the user's deployment callable.

Equivalent of the reference's RayServeReplica
(reference: python/ray/serve/_private/replica.py — user-code wrapper actor;
health check + reconfigure surface). The wrapper resolves deployment-handle
placeholder args (model composition), dispatches plain and batched calls,
and reports lifecycle state.
"""
from __future__ import annotations

import inspect
import threading
import time
from concurrent.futures import Future
from typing import Any

import ray_tpu
from ray_tpu._private import task_spec as ts
from ray_tpu.serve.batching import get_batch_config, pad_to_bucket
from ray_tpu.util import metrics


class _ReplicaBatchQueue:
    """REPLICA-side batch coalescing (reference: serve/batching.py:337
    _BatchQueue — all callers of a replica share ONE queue, so requests from
    different driver/proxy processes batch together). Shape-aware TPU
    addition: batches pad to fixed size buckets so a jitted model sees a
    closed set of shapes. Caller method-threads park in submit() while
    sibling concurrent calls (max_ongoing_requests method pool) fill the
    batch; the thread that completes a batch (or the wait timer) flushes."""

    def __init__(self, fn, config):
        self._fn = fn
        self._config = config
        self._max_batch = config.max_batch_size
        if config.size_buckets:
            # a batch may never exceed the largest bucket or padding breaks
            self._max_batch = min(self._max_batch, config.size_buckets[-1])
        self._lock = threading.Lock()
        self._pending: list[tuple[Any, Future]] = []
        self._timer: threading.Timer | None = None

    def submit(self, payload: Any):
        fut: Future = Future()
        flush_now = None
        with self._lock:
            self._pending.append((payload, fut))
            if len(self._pending) >= self._max_batch:
                flush_now = self._take_locked()
            elif self._timer is None:
                self._timer = threading.Timer(
                    self._config.batch_wait_timeout_s, self._flush_timeout
                )
                self._timer.daemon = True
                self._timer.start()
        if flush_now:
            self._flush(flush_now)
        return fut.result()  # parks this method thread until the batch runs

    def _take_locked(self):
        items, self._pending = self._pending, []
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        return items

    def _flush_timeout(self) -> None:
        with self._lock:
            items = self._take_locked()
        if items:
            self._flush(items)

    def _flush(self, items) -> None:
        payloads = [p for p, _ in items]
        n = len(payloads)
        if self._config.size_buckets:
            target = pad_to_bucket(n, self._config.size_buckets)
            payloads = payloads + [None] * (target - n)
        try:
            results = self._fn(payloads)
            if self._config.size_buckets:
                results = list(results)[:n]  # strip padding results
            if len(results) != n:
                raise ValueError(
                    f"batched method returned {len(results)} results for "
                    f"{n} inputs"
                )
        except Exception as e:  # noqa: BLE001 — fan the error out per call
            for _, f in items:
                f.set_exception(e)
            return
        for (_, f), r in zip(items, results):
            f.set_result(r)


class HandleArg:
    """Placeholder for a DeploymentHandle argument, resolved replica-side
    (model composition: Model.bind(other_app) — reference:
    serve/_private/deployment_graph_build.py)."""

    def __init__(self, deployment_name: str, app_name: str):
        self.deployment_name = deployment_name
        self.app_name = app_name


def _resolve_handle_args(value):
    from ray_tpu.serve.handle import DeploymentHandle

    if isinstance(value, HandleArg):
        return DeploymentHandle(value.deployment_name, value.app_name)
    if isinstance(value, (list, tuple)):
        return type(value)(_resolve_handle_args(v) for v in value)
    if isinstance(value, dict):
        return {k: _resolve_handle_args(v) for k, v in value.items()}
    return value


class ReplicaActor:
    """One serving replica. Created by the controller with the serialized
    user callable; methods are invoked by routers via rt_call.
    The replica actor runs up to max_ongoing_requests methods concurrently
    on its worker's method pool (reference: async replicas bounded by
    max_ongoing_requests), so I/O-bound callables overlap; a TPU-bound
    model still serializes on the chip itself."""

    def __init__(
        self,
        deployment_name: str,
        callable_blob: bytes,
        init_args: tuple,
        init_kwargs: dict,
        user_config: dict | None = None,
        max_ongoing_requests: int = 8,
    ):
        self.deployment_name = deployment_name
        self._max_ongoing = max(1, int(max_ongoing_requests))
        factory = ts.loads_function(callable_blob)
        init_args = _resolve_handle_args(init_args)
        init_kwargs = _resolve_handle_args(init_kwargs)
        if inspect.isclass(factory):
            self._instance = factory(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._instance = factory
            self._is_function = True
        if user_config is not None:
            self.reconfigure(user_config)
        self._batch_queues: dict[str, "_ReplicaBatchQueue"] = {}

    # -- control surface --

    def ping(self) -> str:
        """Liveness probe (reference: replica health check)."""
        check = getattr(self._instance, "check_health", None)
        if check is not None and not self._is_function:
            check()
        return "ok"

    def reconfigure(self, user_config: dict) -> None:
        fn = getattr(self._instance, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def batch_configs(self) -> dict[str, dict]:
        """method name -> BatchConfig fields, discovered from markers."""
        out = {}
        target = self._instance if not self._is_function else None
        if target is None:
            cfg = get_batch_config(self._instance)
            if cfg is not None:
                out["__call__"] = cfg.__dict__
            return out
        for name, member in inspect.getmembers(target, callable):
            if name.startswith("_") and name != "__call__":
                continue
            cfg = get_batch_config(member)
            if cfg is not None:
                out[name] = cfg.__dict__
        return out

    def stream_methods(self) -> list[str]:
        """Generator methods — routers dispatch these via the streaming
        call path so chunks flow out as they are produced (reference:
        serve/_private/replica.py streaming user callables)."""
        if self._is_function:
            return ["__call__"] if inspect.isgeneratorfunction(
                self._instance) else []
        out = []
        for name, member in inspect.getmembers(self._instance, callable):
            if name.startswith("_") and name != "__call__":
                continue
            fn = getattr(member, "__func__", member)
            if inspect.isgeneratorfunction(fn):
                out.append(name)
        return out

    def replica_metadata(self) -> dict:
        """One readiness probe carrying everything the controller needs."""
        return {
            "batch_configs": self.batch_configs(),
            "stream_methods": self.stream_methods(),
            # engine-signal autoscaling + graceful drain are opt-in by
            # capability: the controller only polls/drains deployments
            # whose instances expose the hooks (serve.llm LLMDeployment)
            "has_autoscaling_snapshot": (
                not self._is_function
                and callable(getattr(self._instance, "autoscaling_snapshot", None))
            ),
            "has_drain": (
                not self._is_function
                and callable(getattr(self._instance, "prepare_drain", None))
            ),
            "has_metrics_report": True,
        }

    def metrics_report(self) -> dict:
        """Cheap snapshot for the controller's fleet metrics plane: this
        replica process's whole registry as kind-preserving families plus
        a freshness stamp. Same clocks as serve/llm obs — perf_counter
        for the monotonic stamp, wall time for display. Actor-level (not
        rt_call), so the poll never queues behind user traffic. The
        process's buffered trace spans ride the same payload — one poll
        feeds both the FleetAggregator and the TraceStore."""
        from ray_tpu.util import tracing

        return {
            "clock": time.perf_counter(),
            "wall": time.time(),
            "families": metrics.collect_families(),
            "spans": tracing.drain_buffered_spans(),
        }

    # -- data surface --

    def rt_call(self, method_name: str, args: tuple, kwargs: dict):
        queue = self._batch_queue(method_name)
        if queue is not None:
            # one positional payload per call (router enforces); this method
            # thread parks in the queue while sibling concurrent calls fill
            # the batch — ALL callers of this replica share one queue
            return queue.submit(args[0])
        return self._method(method_name)(*args, **kwargs)

    def rt_call_stream(self, method_name: str, args: tuple, kwargs: dict):
        """Streaming dispatch: a generator the router invokes with
        num_returns='streaming' so every yielded chunk seals as its own
        object the consumer can fetch before the method finishes."""
        yield from self._method(method_name)(*args, **kwargs)

    def _batch_queue(self, method_name: str):
        q = self._batch_queues.get(method_name)
        if q is None:
            cfg = self.batch_configs().get(method_name)
            if cfg is None:
                return None
            from ray_tpu.serve.config import BatchConfig

            bc = BatchConfig(**cfg)
            if bc.max_batch_size > self._max_ongoing:
                # callers park in the bounded method pool, so a batch can
                # never exceed the concurrency — without the clamp every
                # batch would stall for the full wait timeout (the reference
                # warns on this misconfiguration too)
                print(
                    f"[serve] {self.deployment_name}.{method_name}: "
                    f"max_batch_size={bc.max_batch_size} exceeds "
                    f"max_ongoing_requests={self._max_ongoing}; clamping — "
                    f"raise max_ongoing_requests to batch larger",
                    flush=True,
                )
                bc.max_batch_size = self._max_ongoing
            q = self._batch_queues.setdefault(
                method_name,
                _ReplicaBatchQueue(self._method(method_name), bc),
            )
        return q

    def _method(self, name: str):
        if self._is_function:
            if name != "__call__":
                raise AttributeError(
                    f"function deployment {self.deployment_name} only supports "
                    f"__call__, got {name}"
                )
            return self._instance
        return getattr(self._instance, name)
