"""Replica actor wrapper around the user's deployment callable.

Equivalent of the reference's RayServeReplica
(reference: python/ray/serve/_private/replica.py — user-code wrapper actor;
health check + reconfigure surface). The wrapper resolves deployment-handle
placeholder args (model composition), dispatches plain and batched calls,
and reports lifecycle state.
"""
from __future__ import annotations

import inspect
from typing import Any

import ray_tpu
from ray_tpu._private import task_spec as ts
from ray_tpu.serve.batching import get_batch_config


class HandleArg:
    """Placeholder for a DeploymentHandle argument, resolved replica-side
    (model composition: Model.bind(other_app) — reference:
    serve/_private/deployment_graph_build.py)."""

    def __init__(self, deployment_name: str, app_name: str):
        self.deployment_name = deployment_name
        self.app_name = app_name


def _resolve_handle_args(value):
    from ray_tpu.serve.handle import DeploymentHandle

    if isinstance(value, HandleArg):
        return DeploymentHandle(value.deployment_name, value.app_name)
    if isinstance(value, (list, tuple)):
        return type(value)(_resolve_handle_args(v) for v in value)
    if isinstance(value, dict):
        return {k: _resolve_handle_args(v) for k, v in value.items()}
    return value


class ReplicaActor:
    """One serving replica. Created by the controller with the serialized
    user callable; methods are invoked by routers via rt_call / rt_batched.
    The replica actor runs up to max_ongoing_requests methods concurrently
    on its worker's method pool (reference: async replicas bounded by
    max_ongoing_requests), so I/O-bound callables overlap; a TPU-bound
    model still serializes on the chip itself."""

    def __init__(
        self,
        deployment_name: str,
        callable_blob: bytes,
        init_args: tuple,
        init_kwargs: dict,
        user_config: dict | None = None,
    ):
        self.deployment_name = deployment_name
        factory = ts.loads_function(callable_blob)
        init_args = _resolve_handle_args(init_args)
        init_kwargs = _resolve_handle_args(init_kwargs)
        if inspect.isclass(factory):
            self._instance = factory(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._instance = factory
            self._is_function = True
        if user_config is not None:
            self.reconfigure(user_config)

    # -- control surface --

    def ping(self) -> str:
        """Liveness probe (reference: replica health check)."""
        check = getattr(self._instance, "check_health", None)
        if check is not None and not self._is_function:
            check()
        return "ok"

    def reconfigure(self, user_config: dict) -> None:
        fn = getattr(self._instance, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def batch_configs(self) -> dict[str, dict]:
        """method name -> BatchConfig fields, discovered from markers."""
        out = {}
        target = self._instance if not self._is_function else None
        if target is None:
            cfg = get_batch_config(self._instance)
            if cfg is not None:
                out["__call__"] = cfg.__dict__
            return out
        for name, member in inspect.getmembers(target, callable):
            if name.startswith("_") and name != "__call__":
                continue
            cfg = get_batch_config(member)
            if cfg is not None:
                out[name] = cfg.__dict__
        return out

    # -- data surface --

    def rt_call(self, method_name: str, args: tuple, kwargs: dict):
        return self._method(method_name)(*args, **kwargs)

    def rt_batched(self, method_name: str, payloads: list):
        """Batched dispatch: payloads is a list of (args, kwargs) —
        possibly padded with None by the router's shape bucketing. The user
        method receives the list of first positional args (the reference's
        @serve.batch contract) and returns a list of results."""
        real = [p for p in payloads if p is not None]
        items = [a[0] for a, _k in real]  # router enforces 1 positional arg
        results = self._method(method_name)(items)
        if len(results) != len(real):
            raise ValueError(
                f"batched method {method_name} returned {len(results)} results "
                f"for {len(real)} inputs"
            )
        return list(results)

    def _method(self, name: str):
        if self._is_function:
            if name != "__call__":
                raise AttributeError(
                    f"function deployment {self.deployment_name} only supports "
                    f"__call__, got {name}"
                )
            return self._instance
        return getattr(self._instance, name)
