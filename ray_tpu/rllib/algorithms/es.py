"""ES — OpenAI Evolution Strategies (Salimans et al. 2017).

Equivalent of the reference's ES (reference: rllib/algorithms/es/es.py —
population of parameter perturbations evaluated by rollout-worker actors,
antithetic sampling, centered-rank fitness shaping, shared noise via seeds
so only integers cross the wire). Gradient-free: the "learner" is a plain
SGD step on the rank-weighted perturbation directions, so there is no
backprop and no value function — the architecture is embarrassingly
parallel rollouts, which is exactly what the actor layer provides.
"""
from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.rl_module import ActorCriticModule


def _flatten(params: dict) -> tuple[np.ndarray, list]:
    """Param tree -> flat vector + a spec to rebuild it."""
    leaves, spec = [], []
    for layer in params["policy"]:
        for key in ("w", "b"):
            arr = np.asarray(layer[key], np.float32)
            spec.append((key, arr.shape))
            leaves.append(arr.ravel())
    return np.concatenate(leaves), spec


def _unflatten(theta: np.ndarray, spec: list) -> dict:
    layers, i, cur = [], 0, {}
    for key, shape in spec:
        n = int(np.prod(shape))
        cur[key] = theta[i:i + n].reshape(shape)
        i += n
        if key == "b":
            layers.append(cur)
            cur = {}
    return {"policy": layers}


class ESWorker:
    """Rollout-evaluation actor: receives theta + noise SEEDS (integers —
    the noise is regenerated locally, the reference's shared-noise-table
    trick without the table) and returns episodic returns for the
    antithetic +/- perturbation pair of each seed."""

    def __init__(self, env_spec, hidden, sigma: float, seed: int,
                 episode_limit: int = 500):
        self.env = make_env(env_spec)
        obs0 = self.env.reset(seed=seed)
        self.obs_dim = int(np.asarray(obs0).shape[0])
        # probe action count: rllib Envs expose num_actions or action_dim
        self.num_actions = int(getattr(self.env, "num_actions", 2))
        self.module = ActorCriticModule(self.obs_dim, self.num_actions,
                                        tuple(hidden))
        self.sigma = sigma
        self.episode_limit = episode_limit
        self._spec = None

    def _episode_return(self, theta: np.ndarray, spec, seed: int) -> float:
        params = _unflatten(theta, spec)
        obs = self.env.reset(seed=seed)
        total = 0.0
        for _ in range(self.episode_limit):
            logits = ActorCriticModule._mlp_np(
                params["policy"], np.asarray(obs, np.float32)[None])
            action = int(np.argmax(logits[0]))
            obs, r, term, trunc = self.env.step(action)
            total += float(r)
            if term or trunc:
                break
        return total

    def evaluate(self, theta: np.ndarray, spec, seeds: list,
                 eval_seed: int) -> list:
        """[(ret_plus, ret_minus) per seed] — antithetic pairs."""
        out = []
        for s in seeds:
            noise = np.random.default_rng(s).standard_normal(
                theta.shape[0]).astype(np.float32)
            out.append((
                self._episode_return(theta + self.sigma * noise, spec,
                                     eval_seed),
                self._episode_return(theta - self.sigma * noise, spec,
                                     eval_seed),
            ))
        return out


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_workers = 2
        self.episodes_per_batch = 16  # perturbation pairs per iteration
        self.sigma = 0.1
        self.es_lr = 0.05
        self.episode_limit = 500
        self.algo_class = ES


class ES(Algorithm):
    """Driver holds theta; workers evaluate perturbations in parallel.
    Subclasses (ARS) swap `_worker_cls` and the update rule."""

    _worker_cls = ESWorker

    def _setup(self) -> None:
        cfg = self.config
        env = make_env(cfg.env_spec)
        obs0 = env.reset(seed=cfg.seed or 0)
        obs_dim = int(np.asarray(obs0).shape[0])
        num_actions = int(getattr(env, "num_actions", 2))
        env.close()
        self.obs_dim = obs_dim
        self.module = ActorCriticModule(obs_dim, num_actions,
                                        tuple(cfg.hidden))
        p = self.module.init(cfg.seed or 0)
        self.theta, self._spec = _flatten({"policy": p["pi"]})
        Worker = ray_tpu.remote(num_cpus=1)(type(self)._worker_cls)
        self._workers = [
            Worker.remote(cfg.env_spec, tuple(cfg.hidden), cfg.sigma,
                          (cfg.seed or 0) + i, cfg.episode_limit)
            for i in range(cfg.num_workers)
        ]
        self._rng = np.random.default_rng(cfg.seed or 0)
        self._iter = 0

    def _build_learner(self) -> None:  # pragma: no cover — gradient-free
        pass

    def training_step(self) -> dict:
        cfg = self.config
        self._iter += 1
        seeds = self._rng.integers(0, 2**31, cfg.episodes_per_batch)
        chunks = np.array_split(seeds, len(self._workers))
        eval_seed = int(self._rng.integers(0, 2**31))
        refs = [
            w.evaluate.remote(self.theta, self._spec, [int(s) for s in c],
                              eval_seed)
            for w, c in zip(self._workers, chunks) if len(c)
        ]
        pairs = [p for r in refs for p in ray_tpu.get(r, timeout=300)]
        used_seeds = [int(s) for c in chunks for s in c][: len(pairs)]
        rets = np.asarray(pairs, np.float32)  # [n, 2] (+, -)
        # centered-rank fitness shaping over the flattened return set
        flat = rets.ravel()
        ranks = np.empty_like(flat)
        ranks[np.argsort(flat)] = np.arange(flat.size, dtype=np.float32)
        shaped = (ranks / (flat.size - 1) - 0.5).reshape(rets.shape)
        grad = np.zeros_like(self.theta)
        for (s_plus, s_minus), seed in zip(shaped, used_seeds):
            noise = np.random.default_rng(seed).standard_normal(
                self.theta.shape[0]).astype(np.float32)
            grad += (s_plus - s_minus) * noise
        grad /= (len(pairs) * cfg.sigma)
        self.theta = self.theta + cfg.es_lr * grad
        return {
            "episode_return_mean": float(rets.mean()),
            "episode_return_max": float(rets.max()),
            "theta_norm": float(np.linalg.norm(self.theta)),
            "training_iteration": self._iter,
        }

    def compute_action(self, obs: np.ndarray) -> int:
        params = _unflatten(self.theta, self._spec)
        logits = ActorCriticModule._mlp_np(
            params["policy"], np.asarray(obs, np.float32)[None])
        return int(np.argmax(logits[0]))

    def stop(self) -> None:
        for w in getattr(self, "_workers", ()):
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        super().stop()

    def train(self) -> dict:
        # base train() would overwrite episode_return_mean with the (empty)
        # runner-side return tracker; ES owns its own return metrics
        metrics = self.training_step()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        return metrics
