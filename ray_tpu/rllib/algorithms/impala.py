"""IMPALA — asynchronous sampling with a V-trace off-policy learner.

Equivalent of the reference's IMPALA (reference: rllib/algorithms/impala/
impala.py — actors sample continuously with stale weights; the learner
consumes batches as they arrive and corrects off-policyness with V-trace,
Espeholt et al. 2018). TPU mapping: the V-trace recursion runs IN-GRAPH as
a reverse lax.scan inside the jitted learner step (static [T, E] shapes),
instead of the reference's torch host-side loop; env runners stay CPU
actors and are never blocked on the learner — each runner always has one
sample() in flight, and weight broadcasts are fire-and-forget.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import ActorCriticModule


def vtrace_reference_np(
    behavior_logp, target_logp, rewards, values, last_values,
    dones, terminateds, bootstrap_values, gamma,
    rho_max=1.0, c_max=1.0,
):
    """Plain-numpy V-trace oracle (loop form) used by the tests to pin the
    jitted scan implementation."""
    T, E = rewards.shape
    rhos = np.minimum(np.exp(target_logp - behavior_logp), rho_max)
    cs = np.minimum(np.exp(target_logp - behavior_logp), c_max)
    not_term = 1.0 - terminateds.astype(np.float32)
    not_done = 1.0 - dones.astype(np.float32)
    # successor value per step: next row's V, the true-final-obs bootstrap at
    # truncations, masked to 0 at terminations
    v_next = np.empty((T, E), np.float32)
    v_next[:-1] = values[1:]
    v_next[-1] = last_values
    v_next = np.where(dones, bootstrap_values, v_next)
    acc = np.zeros(E, np.float32)
    vs = np.empty((T, E), np.float32)
    for t in range(T - 1, -1, -1):
        delta = rhos[t] * (rewards[t] + gamma * not_term[t] * v_next[t] - values[t])
        acc = delta + gamma * cs[t] * not_done[t] * acc
        vs[t] = values[t] + acc
    vs_next = np.empty((T, E), np.float32)
    vs_next[:-1] = vs[1:]
    vs_next[-1] = last_values
    vs_next = np.where(dones, bootstrap_values, vs_next)
    pg_adv = rhos * (rewards + gamma * not_term * vs_next - values)
    return vs, pg_adv


def vtrace_ingraph(logp, values, batch, config):
    """In-graph V-trace (reverse lax.scan over T): returns (vs targets,
    pg advantages, raw importance ratios). Shared by the IMPALA and APPO
    losses — both correct off-policyness the same way."""
    import jax
    import jax.numpy as jnp

    _T, E = batch["rewards"].shape
    gamma = config["gamma"]
    rhos_raw = jnp.exp(jax.lax.stop_gradient(logp) - batch["behavior_logp"])
    rhos = jnp.minimum(rhos_raw, config["rho_max"])
    cs = jnp.minimum(rhos_raw, config["c_max"])
    not_term = 1.0 - batch["terminateds"].astype(jnp.float32)
    not_done = 1.0 - batch["dones"].astype(jnp.float32)
    values_sg = jax.lax.stop_gradient(values)
    v_next = jnp.concatenate(
        [values_sg[1:], batch["last_values"][None]], axis=0
    )
    v_next = jnp.where(batch["dones"], batch["bootstrap_values"], v_next)

    delta = rhos * (batch["rewards"] + gamma * not_term * v_next - values_sg)

    def scan_fn(acc, xs):
        d, c, nd = xs
        acc = d + gamma * c * nd * acc
        return acc, acc

    _, acc_seq = jax.lax.scan(
        scan_fn, jnp.zeros(E, jnp.float32), (delta, cs, not_done), reverse=True
    )
    vs = values_sg + acc_seq
    vs_next = jnp.concatenate([vs[1:], batch["last_values"][None]], axis=0)
    vs_next = jnp.where(batch["dones"], batch["bootstrap_values"], vs_next)
    pg_adv = rhos * (batch["rewards"] + gamma * not_term * vs_next - values_sg)
    return vs, pg_adv, rhos_raw


def impala_loss(module, params, batch, config):
    """V-trace actor-critic loss, fully in-graph (reverse lax.scan)."""
    import jax
    import jax.numpy as jnp

    T, E = batch["rewards"].shape
    obs = batch["obs"].reshape(T * E, -1)
    logits, values = module.forward(params, obs)
    logits = logits.reshape(T, E, -1)
    values = values.reshape(T, E)
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(logp_all, batch["actions"][..., None], axis=-1)[..., 0]

    vs, pg_adv, rhos_raw = vtrace_ingraph(logp, values, batch, config)

    policy_loss = -jnp.mean(logp * pg_adv)
    value_loss = jnp.mean(jnp.square(values - vs))
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    total = (
        policy_loss
        + config["vf_loss_coeff"] * value_loss
        - config["entropy_coeff"] * entropy
    )
    metrics = {
        "policy_loss": policy_loss,
        "vf_loss": value_loss,
        "entropy": entropy,
        "mean_rho": jnp.mean(rhos_raw),
    }
    return total, metrics


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.vtrace_rho_clip = 1.0
        self.vtrace_c_clip = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.max_sample_staleness_s = 300.0
        self.num_epochs = 1  # IMPALA consumes each async batch once
        self.algo_class = IMPALA


class IMPALA(Algorithm):
    runner_mode = "actor_critic"

    def _runner_factory(self):
        hidden = tuple(self.config.hidden)
        return lambda obs_dim, n_act: ActorCriticModule(obs_dim, n_act, hidden)

    def _build_learner(self) -> None:
        cfg = self.config
        module = ActorCriticModule(self.obs_dim, self.num_actions, cfg.hidden)
        self.learner = Learner(
            module,
            impala_loss,
            config={
                "gamma": cfg.gamma,
                "rho_max": cfg.vtrace_rho_clip,
                "c_max": cfg.vtrace_c_clip,
                "vf_loss_coeff": cfg.vf_loss_coeff,
                "entropy_coeff": cfg.entropy_coeff,
            },
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self._inflight: dict = {}  # sample ref -> runner handle
        self._broadcast_weights(self.learner.get_weights_np())

    def _collect_async(self) -> list[dict]:
        """Grab every finished rollout; resubmit sampling immediately so
        runners are NEVER blocked on the learner (the IMPALA architecture;
        reference actors likewise push batches into a learner queue)."""
        import ray_tpu

        if not self._inflight:
            self._inflight = {r.sample.remote(): r for r in self._runners}
        # block for at least one batch, then drain whatever else is ready
        ready, _ = ray_tpu.wait(
            list(self._inflight), num_returns=1,
            timeout=self.config.max_sample_staleness_s,
        )
        more, _ = ray_tpu.wait(
            [r for r in self._inflight if r not in ready],
            num_returns=len(self._inflight) - len(ready),
            timeout=0,
        )
        batches = []
        for ref in list(ready) + list(more):
            runner = self._inflight.pop(ref)
            b = ray_tpu.get(ref, timeout=60)
            self._record_batch(b)
            batches.append(b)
            self._inflight[runner.sample.remote()] = runner  # keep it busy
        return batches

    def training_step(self) -> dict:
        if self._local_runner is not None:
            batches = self._sample_all()
        else:
            batches = self._collect_async()
        metrics_acc: dict[str, list[float]] = {}
        for b in batches:
            train = {
                "obs": b["obs"],
                "actions": b["actions"].astype(np.int32),
                "behavior_logp": b["logp"],
                "rewards": b["rewards"],
                "dones": b["dones"],
                "terminateds": b["terminateds"],
                "bootstrap_values": b["bootstrap_values"],
                "last_values": b["last_values"],
            }
            # num_epochs=1 for IMPALA; APPO reuses each batch a few times
            # (its clipped surrogate tolerates the extra off-policyness)
            for _ in range(self.config.num_epochs):
                m = self.learner.update(train)
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
        # fire-and-forget broadcast: samplers pick the fresh weights up
        # between rollouts; staleness is corrected by V-trace
        w = self.learner.get_weights_np()
        if self._local_runner is not None:
            self._local_runner.set_weights(w)
        else:
            for r in self._runners:
                r.set_weights.remote(w)
        metrics = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        metrics["num_batches_consumed"] = len(batches)
        return metrics
