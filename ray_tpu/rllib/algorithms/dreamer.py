"""Dreamer — model-based RL: learn a latent world model, train the
policy inside it.

Equivalent of the reference's DreamerV3 (reference:
rllib/algorithms/dreamerv3/dreamer_v3.py:1 — an RSSM world model
[Hafner et al. 2023] trained on replayed sequences, with the
actor-critic trained entirely on imagined latent rollouts). This is a
deliberately compact instantiation of the same architecture —
GRU-deterministic + gaussian-stochastic RSSM, decoder/reward/continue
heads, lambda-return critic and REINFORCE actor over imagined
trajectories — sized for the in-tree control envs, not Atari. TPU-first
shape: BOTH phases are single jitted updates whose recurrences (sequence
posterior rollout, imagination rollout) are `lax.scan`s; nothing steps
the real env inside jit.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.replay_buffer import SequenceReplayBuffer
from ray_tpu.rllib.rl_module import _gru_init, _gru_step, _init_linear, _mlp


def _mlp_params(rng, dims, out_scale=1.0):
    layers = [_init_linear(rng, dims[i], dims[i + 1], np.sqrt(2))
              for i in range(len(dims) - 2)]
    layers.append(_init_linear(rng, dims[-2], dims[-1], out_scale))
    return layers


class DreamerModule:
    """RSSM world model + latent actor-critic, one param tree.

    Latent state = (h deterministic [H], z stochastic gaussian [Z]).
    posterior q(z|h, embed(obs)); prior p(z|h); heads decode obs, reward
    and continue from (h, z); actor/critic read (h, z).
    """

    is_recurrent = True  # EnvRunner threads (h, z) through rollouts

    def __init__(self, obs_dim: int, num_actions: int, h_dim: int = 64,
                 z_dim: int = 16, hidden: int = 64):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.h_dim = h_dim
        self.z_dim = z_dim
        self.hidden = hidden

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        H, Z, A, D = self.h_dim, self.z_dim, self.num_actions, self.obs_dim
        n = self.hidden
        return {
            "enc": _mlp_params(rng, [D, n, n]),
            "gru": _gru_init(rng, Z + A, H),
            "prior": _mlp_params(rng, [H, n, 2 * Z], 0.1),
            "post": _mlp_params(rng, [H + n, n, 2 * Z], 0.1),
            "dec": _mlp_params(rng, [H + Z, n, D]),
            # reward/continue condition on (state, action): the MuZero-ish
            # factorization keeps every training pair inside one episode
            # (no next-state needed), and imagination scores identically
            "rew": _mlp_params(rng, [H + Z + A, n, 1], 0.1),
            "cont": _mlp_params(rng, [H + Z + A, n, 1], 0.1),
            "actor": _mlp_params(rng, [H + Z, n, A], 0.01),
            "critic": _mlp_params(rng, [H + Z, n, 1], 0.1),
        }

    def initial_state(self, batch: int) -> np.ndarray:
        # packed (h, z, prev_action_onehot) so the EnvRunner's generic
        # state threading carries the action conditioning too — the
        # filter the policy deploys on matches the one it trains on
        return np.zeros(
            (batch, self.h_dim + self.z_dim + self.num_actions), np.float32)

    # -- shared math (xp = np | jnp) --

    def _split_stats(self, xp, stats):
        mean, log_std = stats[..., :self.z_dim], stats[..., self.z_dim:]
        return mean, xp.clip(log_std, -5.0, 2.0)

    def _step_core(self, xp, params, state, action_onehot, embed, noise):
        """(h,z) + a + embed(obs) -> next packed state via the POSTERIOR."""
        h, z = state[..., :self.h_dim], state[..., self.h_dim:]
        h = _gru_step(xp, params["gru"],
                      xp.concatenate([z, action_onehot], -1), h)
        stats = _mlp(xp, params["post"], xp.concatenate([h, embed], -1))
        mean, log_std = self._split_stats(xp, stats)
        z = mean + xp.exp(log_std) * noise
        return xp.concatenate([h, z], -1)

    # -- numpy path (EnvRunner action sampling) --

    def step_np(self, params, obs: np.ndarray, state: np.ndarray):
        """Posterior filter step + actor logits; returns (logits for the
        runner's argmax, next packed state). The state tail carries the
        PREVIOUS action one-hot; the runner writes the action it actually
        took via pack_action (exploration included)."""
        B = obs.shape[0]
        sz = self.h_dim + self.z_dim
        embed = _mlp(np, params["enc"], obs)
        a_prev = state[..., sz:]
        nxt = self._step_core(np, params, state[..., :sz], a_prev, embed,
                              np.zeros((B, self.z_dim), np.float32))
        logits = _mlp(np, params["actor"], nxt)
        # tail zeroed until the runner packs the chosen action
        return logits, np.concatenate(
            [nxt, np.zeros((B, self.num_actions), np.float32)], -1)

    def pack_action(self, state: np.ndarray, actions: np.ndarray) -> np.ndarray:
        """Record the action the runner CHOSE (epsilon-greedy included) in
        the state tail so the next filter step conditions on the truth."""
        out = state.copy()
        sz = self.h_dim + self.z_dim
        out[..., sz:] = 0.0
        out[np.arange(len(actions)), sz + actions] = 1.0
        return out

    # -- jax: world-model loss over [B, T] sequences --

    def observe(self, params, obs_seq, actions, resets, packed_state0, key):
        """Posterior rollout over a [B, T] sequence: returns states
        [B,T,H+Z] and prior/post stats. The packed state0 carries the
        window's true first prev-action; later steps shift `actions`."""
        import jax
        import jax.numpy as jnp

        B, T, _ = obs_seq.shape
        sz = self.h_dim + self.z_dim
        state0 = packed_state0[..., :sz]
        a0 = packed_state0[..., sz:]
        act1 = jax.nn.one_hot(actions, self.num_actions)
        act_onehot_seq = jnp.concatenate(
            [a0[:, None, :], act1[:, :-1]], axis=1)
        embed = _mlp(jnp, params["enc"], obs_seq)
        noise = jax.random.normal(key, (T, B, self.z_dim))

        def scan_step(state, inputs):
            emb_t, act_t, reset_t, eps_t = inputs
            state = jnp.where(reset_t[:, None], 0.0, state)
            # a fresh episode has no previous action either
            act_t = jnp.where(reset_t[:, None], 0.0, act_t)
            h = state[..., :self.h_dim]
            z = state[..., self.h_dim:]
            h = _gru_step(jnp, params["gru"],
                          jnp.concatenate([z, act_t], -1), h)
            prior_stats = _mlp(jnp, params["prior"], h)
            post_stats = _mlp(jnp, params["post"],
                              jnp.concatenate([h, emb_t], -1))
            mean, log_std = self._split_stats(jnp, post_stats)
            z = mean + jnp.exp(log_std) * eps_t
            nxt = jnp.concatenate([h, z], -1)
            return nxt, (nxt, prior_stats, post_stats)

        xs = (jnp.swapaxes(embed, 0, 1), jnp.swapaxes(act_onehot_seq, 0, 1),
              jnp.swapaxes(resets, 0, 1), noise)
        _, (states, prior_stats, post_stats) = jax.lax.scan(
            scan_step, state0, xs)
        swap = lambda x: jnp.swapaxes(x, 0, 1)  # noqa: E731
        return swap(states), swap(prior_stats), swap(post_stats)

    def imagine(self, params, start_states, horizon: int, key):
        """Actor-driven PRIOR rollout from [N, H+Z] starts. Returns
        (pre_states, rewards, conts, logps, entropies) each [N, horizon]
        (+state dim) — rewards/continues scored from the (state, action)
        heads exactly as trained."""
        import jax
        import jax.numpy as jnp

        N = start_states.shape[0]
        keys = jax.random.split(key, horizon)

        def scan_step(state, k):
            logits = _mlp(jnp, params["actor"], state)
            ka, kz = jax.random.split(k)
            action = jax.random.categorical(ka, logits)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, action[:, None], axis=-1)[:, 0]
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, -1)
            a1 = jax.nn.one_hot(action, self.num_actions)
            sa = jnp.concatenate([state, a1], -1)
            rew = _mlp(jnp, params["rew"], sa)[..., 0]
            cont = jax.nn.sigmoid(_mlp(jnp, params["cont"], sa)[..., 0])
            h = state[..., :self.h_dim]
            z = state[..., self.h_dim:]
            h = _gru_step(jnp, params["gru"],
                          jnp.concatenate([z, a1], -1), h)
            stats = _mlp(jnp, params["prior"], h)
            mean, log_std = self._split_stats(jnp, stats)
            z = mean + jnp.exp(log_std) * jax.random.normal(
                kz, (N, self.z_dim))
            nxt = jnp.concatenate([h, z], -1)
            return nxt, (state, rew, cont, logp, entropy)

        _, (pre_states, rews, conts, logps, ents) = jax.lax.scan(
            scan_step, start_states, keys)
        swap = lambda x: jnp.swapaxes(x, 0, 1)  # noqa: E731
        return (swap(pre_states), swap(rews), swap(conts),
                swap(logps), swap(ents))


def world_model_loss(module, params, batch, config):
    """Reconstruction + reward + continue + KL(post || prior) with free
    bits (Hafner et al. 2023 eq. 4-5, gaussian instantiation).

    Alignment: the transition into state t+1 consumes action a_t, so
    reward r_t and the continue flag are predicted from states[t+1] —
    the same post-transition convention the imagination rollout scores
    with. Pairs that cross an episode boundary are masked out."""
    import jax
    import jax.numpy as jnp

    B, T = batch["rewards"].shape
    states, prior_stats, post_stats = module.observe(
        params, batch["obs"], batch["actions"], batch["resets"],
        batch["state_in"], batch["key"])
    recon = _mlp(jnp, params["dec"], states)
    recon_loss = jnp.mean(jnp.sum((recon - batch["obs"]) ** 2, -1))
    # (state_t, a_t) -> r_t and continue: every pair lies inside one
    # episode (auto-reset boundaries need no masking)
    a_now = jax.nn.one_hot(batch["actions"], module.num_actions)
    sa = jnp.concatenate([states, a_now], -1)
    rew = _mlp(jnp, params["rew"], sa)[..., 0]
    reward_loss = jnp.mean((rew - batch["rewards"]) ** 2)
    cont_logit = _mlp(jnp, params["cont"], sa)[..., 0]
    cont_target = 1.0 - batch["terminateds"].astype(jnp.float32)
    cont_loss = jnp.mean(
        jnp.maximum(cont_logit, 0) - cont_logit * cont_target
        + jnp.log1p(jnp.exp(-jnp.abs(cont_logit))))
    pm, pls = module._split_stats(jnp, prior_stats)
    qm, qls = module._split_stats(jnp, post_stats)
    kl = (pls - qls + (jnp.exp(2 * qls) + (qm - pm) ** 2)
          / (2 * jnp.exp(2 * pls)) - 0.5)
    kl = jnp.maximum(jnp.sum(kl, -1), config["free_bits"])
    kl_loss = jnp.mean(kl)
    loss = recon_loss + reward_loss + cont_loss + config["kl_coeff"] * kl_loss
    return loss, {
        "recon_loss": recon_loss, "reward_loss": reward_loss,
        "kl": kl_loss, "cont_loss": cont_loss,
        # flat posterior states ride out for the behavior phase
        "_states": jax.lax.stop_gradient(states.reshape(B * T, -1)),
    }


def behavior_loss(module, params, batch, config):
    """Imagination-phase actor-critic: lambda-return REINFORCE + value
    regression, entirely in latent space (dreamer_v3.py training_step's
    second phase). The world model is frozen here — `wm_params` ride in
    the batch; only actor/critic entries of `params` receive gradients
    (the loss touches nothing else)."""
    import jax
    import jax.numpy as jnp

    wm = batch["wm_params"]
    live = {k: v for k, v in wm.items() if k not in ("actor", "critic")}
    live["actor"] = params["actor"]
    live["critic"] = params["critic"]
    pre_states, rew, cont, logps, ents = module.imagine(
        live, batch["starts"], config["horizon"], batch["key"])
    # values of the PRE-decision states v(s_i); bootstrap with the value
    # of the final post-transition state approximated by the last pre
    # state (one-step tail truncation, horizon is short)
    value = _mlp(jnp, params["critic"], pre_states)[..., 0]   # [N, Hrz]
    gamma, lam = config["gamma"], config["lambda"]
    disc = gamma * cont

    def lam_ret(carry, xs):
        r_t, d_t, v_next = xs
        ret = r_t + d_t * ((1 - lam) * v_next + lam * carry)
        return ret, ret

    # v_{i+1}: shift values left; tail bootstraps from its own value
    v_next = jnp.concatenate([value[:, 1:], value[:, -1:]], axis=1)
    _, rets = jax.lax.scan(
        lam_ret, value[:, -1],
        (rew.T[::-1], disc.T[::-1], v_next.T[::-1]))
    returns = rets[::-1].T                                   # [N, Hrz]
    adv = jax.lax.stop_gradient(returns - value)
    # normalize by return scale (the V3 trick, percentile-lite)
    scale = jnp.maximum(1.0, jnp.std(returns) + 1e-6)
    actor_loss = -jnp.mean(logps * adv / scale
                           + config["entropy"] * ents)
    critic_loss = jnp.mean(
        (value - jax.lax.stop_gradient(returns)) ** 2)
    loss = actor_loss + critic_loss
    return loss, {"actor_loss": actor_loss, "critic_loss": critic_loss,
                  "imagined_return": jnp.mean(returns)}


class DreamerConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.rollout_length = 16
        self.buffer_capacity = 2_000   # sequences
        self.learning_starts = 32
        self.wm_updates = 16
        self.behavior_updates = 16
        self.seq_minibatch = 16
        self.horizon = 10
        self.kl_coeff = 0.5
        self.free_bits = 1.0
        self.entropy = 3e-3
        self.lambda_ = 0.95
        self.lr = 8e-4
        self.h_dim = 64
        self.z_dim = 16
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 3_000
        self.algo_class = Dreamer


class Dreamer(Algorithm):
    runner_mode = "epsilon_greedy"  # actor logits argmax + annealed random

    def _runner_factory(self):
        cfg = self.config
        h, z, n = cfg.h_dim, cfg.z_dim, cfg.hidden
        hid = n[0] if isinstance(n, (tuple, list)) else n
        return lambda obs_dim, n_act: DreamerModule(
            obs_dim, n_act, h_dim=h, z_dim=z, hidden=hid)

    def _build_learner(self) -> None:
        cfg = self.config
        hid = (cfg.hidden[0] if isinstance(cfg.hidden, (tuple, list))
               else cfg.hidden)
        self.module = DreamerModule(self.obs_dim, self.num_actions,
                                    h_dim=cfg.h_dim, z_dim=cfg.z_dim,
                                    hidden=hid)
        self.wm_learner = Learner(
            self.module, world_model_loss,
            config={"kl_coeff": cfg.kl_coeff, "free_bits": cfg.free_bits},
            learning_rate=cfg.lr, max_grad_norm=cfg.max_grad_norm,
            seed=cfg.seed)
        self.ac_learner = Learner(
            self.module, behavior_loss,
            config={"horizon": cfg.horizon, "gamma": cfg.gamma,
                    "lambda": cfg.lambda_, "entropy": cfg.entropy},
            learning_rate=cfg.lr, max_grad_norm=cfg.max_grad_norm,
            seed=cfg.seed + 1)
        self.learner = self.wm_learner  # primary (save_state adds the AC)
        self.buffer = SequenceReplayBuffer(
            cfg.buffer_capacity, cfg.rollout_length, self.obs_dim,
            state_dim=cfg.h_dim + cfg.z_dim + self.num_actions,
            seed=cfg.seed)
        self._key = 0
        self._broadcast()

    def _sync_actor_into_wm(self) -> dict:
        """One combined tree: world model + freshest actor/critic."""
        wm = self.wm_learner.get_weights_np()
        ac = self.ac_learner.get_weights_np()
        wm["actor"] = ac["actor"]
        wm["critic"] = ac["critic"]
        return wm

    def _broadcast(self) -> None:
        self._broadcast_weights(self._sync_actor_into_wm(), self._epsilon())

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._total_env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def training_step(self) -> dict:
        import jax

        cfg = self.config
        for b in self._sample_all():
            self.buffer.add_rollout(b)
        metrics_acc: dict[str, list[float]] = {}

        def record(m: dict, prefix: str = "") -> None:
            for k, v in m.items():
                metrics_acc.setdefault(prefix + k, []).append(v)

        states = None
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.wm_updates):
                mb = self.buffer.sample(cfg.seq_minibatch)
                self._key += 1
                mb["key"] = jax.random.PRNGKey(self._key)
                m = self.wm_learner.update(mb)
                states = m.pop("_states")
                record(m)
            # behavior phase: its own update count, on the freshest
            # posterior states, with the world model's DEVICE params (no
            # per-update device<->host round trips)
            for _ in range(cfg.behavior_updates if states is not None else 0):
                self._key += 1
                m2 = self.ac_learner.update({
                    "starts": states,
                    "wm_params": self.wm_learner.params,
                    "key": jax.random.PRNGKey(self._key),
                })
                record(m2, prefix="ac_")
        self._broadcast()
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        out["epsilon"] = self._epsilon()
        out["replay_sequences"] = len(self.buffer)
        return out

    def save_state(self) -> dict:
        state = super().save_state()
        state["ac_learner"] = self.ac_learner.state()
        return state

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        self.ac_learner.load_state(state["ac_learner"])
        self._broadcast()
