"""TD3 / DDPG — deterministic-policy continuous control.

Equivalent of the reference's TD3 and DDPG (reference:
rllib/algorithms/td3/td3.py — DDPG plus twin critics, delayed policy
updates, and target-policy smoothing, Fujimoto et al. 2018; ddpg/ddpg.py).
Relationship inverted deliberately: the general machinery (twin critics +
delay + smoothing) is implemented once, and DDPG is the exact reduction
(single critic, no delay, no smoothing) — the math is identical to
Lillicrap et al. 2016.

TPU mapping: critic step, actor step, and the Polyak target update are
three jitted functions over one param pytree; the actor step differentiates
only the "pi" subtree while the critics ride along frozen.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rl_module import DeterministicPolicyModule


class _TD3Learner:
    """Jitted critic/actor/target updates for deterministic policies."""

    def __init__(self, module: DeterministicPolicyModule, config: dict,
                 actor_lr: float, critic_lr: float, seed: int):
        import jax
        import optax

        self.module = module
        self.config = dict(config)
        self.params = jax.tree_util.tree_map(
            lambda x: jax.numpy.asarray(x), module.init(seed)
        )
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)
        self._critic_tx = optax.adam(critic_lr)
        self._actor_tx = optax.adam(actor_lr)
        self._critic_opt = self._critic_tx.init(self._critic_of(self.params))
        self._actor_opt = self._actor_tx.init({"pi": self.params["pi"]})
        self._critic_step = jax.jit(self._critic_step_impl)
        self._actor_step = jax.jit(self._actor_step_impl)
        self._key = jax.random.PRNGKey(seed + 99)

    @staticmethod
    def _critic_of(params: dict) -> dict:
        return {k: v for k, v in params.items() if k != "pi"}

    def _critic_step_impl(self, params, target_params, opt_state, batch, key):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        m = self.module
        # target-policy smoothing: noisy target action, clipped
        noise = jax.random.normal(key, batch["actions"].shape) * cfg["target_noise"]
        noise = jnp.clip(noise, -cfg["noise_clip"], cfg["noise_clip"])
        a_next = jnp.clip(
            m.policy(target_params, batch["next_obs"]) + noise,
            -m.action_bound, m.action_bound,
        )
        q1_t = m.q_value(target_params, batch["next_obs"], a_next, "q1")
        if m.twin_q:
            q2_t = m.q_value(target_params, batch["next_obs"], a_next, "q2")
            q_t = jnp.minimum(q1_t, q2_t)  # clipped double-Q
        else:
            q_t = q1_t
        not_term = 1.0 - batch["terminateds"].astype(jnp.float32)
        target = jax.lax.stop_gradient(
            batch["rewards"] + cfg["gamma"] * not_term * q_t
        )

        def loss_fn(critic_params):
            full = dict(params, **critic_params)
            q1 = m.q_value(full, batch["obs"], batch["actions"], "q1")
            loss = jnp.mean(jnp.square(q1 - target))
            if m.twin_q:
                q2 = m.q_value(full, batch["obs"], batch["actions"], "q2")
                loss = loss + jnp.mean(jnp.square(q2 - target))
            return loss, jnp.mean(q1)

        critic_params = self._critic_of(params)
        (loss, q_mean), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            critic_params)
        updates, opt_state = self._critic_tx.update(grads, opt_state,
                                                    critic_params)
        critic_params = optax.apply_updates(critic_params, updates)
        return dict(params, **critic_params), opt_state, loss, q_mean

    def _actor_step_impl(self, params, target_params, opt_state, batch):
        import jax
        import jax.numpy as jnp
        import optax

        m = self.module
        tau = self.config["tau"]

        def loss_fn(pi_only):
            full = dict(params, pi=pi_only["pi"])
            a = m.policy(full, batch["obs"])
            return -jnp.mean(m.q_value(full, batch["obs"], a, "q1"))

        pi_only = {"pi": params["pi"]}
        loss, grads = jax.value_and_grad(loss_fn)(pi_only)
        updates, opt_state = self._actor_tx.update(grads, opt_state, pi_only)
        pi_only = optax.apply_updates(pi_only, updates)
        new_params = dict(params, pi=pi_only["pi"])
        # Polyak-averaged targets, in-graph
        new_targets = jax.tree_util.tree_map(
            lambda t, p: (1.0 - tau) * t + tau * p, target_params, new_params
        )
        return new_params, new_targets, opt_state, loss

    def critic_update(self, batch: dict) -> dict:
        import jax

        self._key, sub = jax.random.split(self._key)
        self.params, self._critic_opt, loss, q_mean = self._critic_step(
            self.params, self.target_params, self._critic_opt, batch, sub
        )
        return {"critic_loss": float(loss), "q_mean": float(q_mean)}

    def actor_update(self, batch: dict) -> dict:
        self.params, self.target_params, self._actor_opt, loss = (
            self._actor_step(self.params, self.target_params,
                             self._actor_opt, batch)
        )
        return {"actor_loss": float(loss)}

    def get_weights_np(self) -> dict:
        import jax

        return jax.tree_util.tree_map(lambda x: np.asarray(x), self.params)

    def state(self) -> dict:
        import jax

        return {
            "params": self.get_weights_np(),
            "target_params": jax.tree_util.tree_map(
                lambda x: np.asarray(x), self.target_params),
        }

    def load_state(self, state: dict) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree_util.tree_map(jnp.asarray, state["params"])
        self.target_params = jax.tree_util.tree_map(
            jnp.asarray, state["target_params"])


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.tau = 0.005
        self.twin_q = True
        self.policy_delay = 2
        self.target_noise = 0.2
        self.noise_clip = 0.5
        self.explore_noise = 0.1  # stddev as a fraction of action_bound
        self.buffer_capacity = 100_000
        self.learning_starts = 1_000
        # ~one gradient step per sampled env step (TD3's standard regime;
        # default rollout 64 x 4 envs = 256 steps/iteration)
        self.updates_per_iteration = 256
        self.minibatch_size = 128
        self.algo_class = TD3


class DDPGConfig(TD3Config):
    """DDPG = TD3 without the three addenda (reference: ddpg/ddpg.py)."""

    def __init__(self):
        super().__init__()
        self.twin_q = False
        self.policy_delay = 1
        self.target_noise = 0.0
        self.noise_clip = 0.0
        self.algo_class = DDPG


class TD3(Algorithm):
    runner_mode = "continuous"

    def _setup(self) -> None:
        # continuous runners need action metadata at module build time, so
        # the factory closes over the env's action space probed here
        from ray_tpu.rllib.env import make_env

        probe = make_env(self.config.env_spec)
        if not probe.continuous:
            probe.close()
            raise ValueError("TD3/DDPG require a continuous-action env")
        action_dim, action_bound = probe.action_dim, probe.action_bound
        probe.close()
        hidden = tuple(self.config.hidden)
        twin = self.config.twin_q

        self._module_factory = (
            lambda obs_dim, n_act: DeterministicPolicyModule(
                obs_dim, action_dim, action_bound, hidden, twin_q=twin)
        )
        super()._setup()

    def _runner_factory(self):
        return self._module_factory

    def _build_learner(self) -> None:
        cfg = self.config
        module = DeterministicPolicyModule(
            self.obs_dim, self.action_dim, self.action_bound,
            tuple(cfg.hidden), twin_q=cfg.twin_q,
        )
        self.learner = _TD3Learner(
            module,
            config={
                "gamma": cfg.gamma,
                "tau": cfg.tau,
                "target_noise": cfg.target_noise * self.action_bound,
                "noise_clip": cfg.noise_clip * self.action_bound,
            },
            actor_lr=cfg.actor_lr,
            critic_lr=cfg.critic_lr,
            seed=cfg.seed,
        )
        self.buffer = ReplayBuffer(
            cfg.buffer_capacity, self.obs_dim, seed=cfg.seed,
            action_dim=self.action_dim,
        )
        self._grad_steps = 0
        self._broadcast_weights(self.learner.get_weights_np(),
                                cfg.explore_noise)

    def training_step(self) -> dict:
        cfg = self.config
        for b in self._sample_all():
            T, E = b["rewards"].shape
            self.buffer.add_batch(
                b["obs"].reshape(T * E, -1),
                b["actions"].reshape(T * E, -1),
                b["rewards"].reshape(-1),
                b["next_obs"].reshape(T * E, -1),
                b["terminateds"].reshape(-1),
            )
        metrics_acc: dict[str, list[float]] = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                mb = self.buffer.sample(cfg.minibatch_size)
                m = self.learner.critic_update(mb)
                self._grad_steps += 1
                if self._grad_steps % cfg.policy_delay == 0:
                    m.update(self.learner.actor_update(mb))
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
        self._broadcast_weights(self.learner.get_weights_np(),
                                cfg.explore_noise)
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        out["replay_size"] = len(self.buffer)
        return out


class DDPG(TD3):
    pass
