"""Ape-X DQN — distributed prioritized replay (Horgan et al. 2018).

Equivalent of the reference's ApexDQN (reference:
rllib/algorithms/apex_dqn/apex_dqn.py — replay buffers as ACTORS sharded
across the cluster, rollout workers push experiences to shards, the learner
pulls sampled minibatches asynchronously and pushes priority updates back).
This is the architecture exercise disguised as an algorithm: replay shards
are ordinary ray_tpu actors (so they schedule across nodes), sampling
futures are prefetched so the learner update overlaps the next shard
sample, and priority refreshes ride back asynchronously.

Differences from the reference, by design: workers send rollouts through
the driver (which n-step-collapses once) instead of worker-side replay
pushes — at the CartPole-to-Atari scales this build benches, the driver
hop costs less than duplicating the n-step machinery in every worker; the
object-plane still carries the arrays, so bytes move worker→store→shard.
"""
from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer


class ReplayShard:
    """One prioritized replay shard, hosted as an actor. Methods mirror the
    in-process PrioritizedReplayBuffer; `sample` returns None until warm."""

    def __init__(self, capacity: int, obs_dim: int, seed: int,
                 alpha: float, beta: float, min_size: int,
                 action_dim: int | None = None):
        self._buf = PrioritizedReplayBuffer(
            capacity, obs_dim, seed=seed, alpha=alpha, beta=beta,
            action_dim=action_dim,
        )
        self._min_size = min_size

    def add_batch(self, obs, actions, rewards, next_obs, terminated,
                  discounts) -> int:
        self._buf.add_batch(obs, actions, rewards, next_obs, terminated,
                            discounts)
        return len(self._buf)

    def sample(self, n: int):
        if len(self._buf) < max(self._min_size, n):
            return None
        return self._buf.sample(n)

    def update_priorities(self, indices, priorities) -> None:
        self._buf.update_priorities(np.asarray(indices),
                                    np.asarray(priorities))

    def size(self) -> int:
        return len(self._buf)


class ApexDQNConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.prioritized_replay = True  # definitional for Ape-X
        self.num_replay_shards = 2
        self.replay_shard_num_cpus = 0.25
        # sample futures kept in flight per shard so the learner never
        # waits on a shard round-trip (reference: apex learner thread +
        # replay prefetch)
        self.prefetch_per_shard = 2
        self.algo_class = ApexDQN


class ApexDQN(DQN):
    """DQN whose replay lives in sharded actors. Everything else (n-step,
    double-Q loss, target sync, epsilon runners) is inherited."""

    def _build_learner(self) -> None:
        super()._build_learner()
        cfg = self.config
        self.buffer = None  # replaced by shard actors
        Shard = ray_tpu.remote(num_cpus=cfg.replay_shard_num_cpus)(ReplayShard)
        per_shard = max(1, cfg.buffer_capacity // cfg.num_replay_shards)
        self._shards = [
            Shard.remote(per_shard, self.obs_dim, cfg.seed + i,
                         cfg.per_alpha, cfg.per_beta,
                         max(cfg.minibatch_size, cfg.learning_starts
                             // cfg.num_replay_shards))
            for i in range(cfg.num_replay_shards)
        ]
        self._rr = 0  # round-robin add cursor
        self._sample_futures: list = []  # (shard, ref) prefetch queue
        self._size_refs: list = []

    def _prefetch(self) -> None:
        cfg = self.config
        while len(self._sample_futures) < (
                cfg.prefetch_per_shard * len(self._shards)):
            shard = self._shards[self._rr % len(self._shards)]
            self._rr += 1
            self._sample_futures.append(
                (shard, shard.sample.remote(cfg.minibatch_size)))

    def training_step(self) -> dict:
        cfg = self.config
        # 1. rollouts -> n-step transitions -> round-robin shard pushes
        #    (async; the adds and the updates below overlap)
        add_refs = []
        for b in self._sample_all():
            data = self._nstep(b)
            shard = self._shards[self._rr % len(self._shards)]
            self._rr += 1
            add_refs.append(shard.add_batch.remote(*data))
        # 2. async learner: drain prefetched samples, update, push
        #    priorities back without waiting on them
        self._prefetch()
        metrics_acc: dict[str, list[float]] = {}
        updates_done = 0
        attempts = 0
        while updates_done < cfg.updates_per_iteration and attempts < (
                cfg.updates_per_iteration * 3):
            attempts += 1
            shard, ref = self._sample_futures.pop(0)
            mb = ray_tpu.get(ref, timeout=120)
            self._prefetch()
            if mb is None:
                continue  # shard still warming up
            indices = mb.pop("indices", None)
            mb["target_params"] = self._target_params
            m = self.learner.update(mb)
            td_abs = m.pop("_td_abs", None)
            updates_done += 1
            self._grad_steps += 1
            if self._grad_steps % cfg.target_update_freq == 0:
                self._target_params = self.learner.get_weights_np()
            if indices is not None and td_abs is not None:
                # fire-and-forget: priority freshness is best-effort
                shard.update_priorities.remote(
                    np.asarray(indices), np.asarray(td_abs))
            for k, v in m.items():
                metrics_acc.setdefault(k, []).append(v)
        # 3. weights out to the epsilon-greedy runners
        self._broadcast_weights(self.learner.get_weights_np(), self._epsilon())
        for r in add_refs:  # surface shard failures instead of hiding them
            ray_tpu.get(r, timeout=120)
        sizes = ray_tpu.get([s.size.remote() for s in self._shards],
                            timeout=120)
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        out["epsilon"] = self._epsilon()
        out["replay_size"] = int(sum(sizes))
        out["replay_shards"] = len(self._shards)
        out["updates_done"] = updates_done
        return out

    def stop(self) -> None:
        for s in getattr(self, "_shards", ()):
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
        super().stop()
