"""A2C — synchronous advantage actor-critic.

Equivalent of the reference's A2C (reference: rllib/algorithms/a2c/a2c.py —
one synchronous gradient step per rollout batch over the vanilla
policy-gradient loss; deprecated upstream in favor of PPO but part of the
algorithm surface). Unlike PPO there is no surrogate ratio and no minibatch
epochs: advantages are GAE, the update is a single whole-batch step of
-logp * A, jitted in the Learner.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import compute_gae
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import ActorCriticModule


def a2c_loss(module, params, batch, config):
    """Vanilla policy gradient + value loss + entropy bonus (pure jax)."""
    import jax
    import jax.numpy as jnp

    logits, values = module.forward(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=-1)[:, 0]
    policy_loss = -jnp.mean(logp * batch["advantages"])
    value_loss = jnp.mean(jnp.square(values - batch["value_targets"]))
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    total = (
        policy_loss
        + config["vf_loss_coeff"] * value_loss
        - config["entropy_coeff"] * entropy
    )
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": value_loss,
        "entropy": entropy,
    }


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.gae_lambda = 1.0  # classic A2C: plain n-step returns
        self.algo_class = A2C


class A2C(Algorithm):
    runner_mode = "actor_critic"

    def _runner_factory(self):
        hidden = tuple(self.config.hidden)
        return lambda obs_dim, n_act: ActorCriticModule(obs_dim, n_act, hidden)

    def _build_learner(self) -> None:
        cfg = self.config
        module = ActorCriticModule(self.obs_dim, self.num_actions, cfg.hidden)
        self.learner = Learner(
            module,
            a2c_loss,
            config={
                "vf_loss_coeff": cfg.vf_loss_coeff,
                "entropy_coeff": cfg.entropy_coeff,
            },
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self._broadcast_weights(self.learner.get_weights_np())

    def training_step(self) -> dict:
        cfg = self.config
        batches = self._sample_all()
        flat = {"obs": [], "actions": [], "advantages": [], "value_targets": []}
        for b in batches:
            adv, ret = compute_gae(b, cfg.gamma, cfg.gae_lambda)
            T, E = b["rewards"].shape
            flat["obs"].append(b["obs"].reshape(T * E, -1))
            flat["actions"].append(b["actions"].reshape(-1).astype(np.int32))
            flat["advantages"].append(adv.reshape(-1))
            flat["value_targets"].append(ret.reshape(-1))
        train = {k: np.concatenate(v) for k, v in flat.items()}
        adv = train["advantages"]
        train["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        metrics = self.learner.update(train)  # ONE whole-batch step
        self._broadcast_weights(self.learner.get_weights_np())
        return metrics
