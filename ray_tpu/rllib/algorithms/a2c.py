"""A2C — synchronous advantage actor-critic.

Equivalent of the reference's A2C (reference: rllib/algorithms/a2c/a2c.py —
one synchronous gradient step per rollout batch; deprecated upstream in
favor of PPO but part of the algorithm surface). Implemented as PPO with a
single whole-batch update: on the first (only) pass the importance ratio is
exactly 1, so the clipped surrogate reduces to the vanilla policy gradient
-logp * advantage.
"""
from __future__ import annotations

from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig


class A2CConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        self.num_epochs = 1
        self.minibatch_size = 1 << 30  # whole batch, clamped per rollout
        self.clip_param = 1e9  # never clips at ratio == 1
        self.algo_class = A2C


class A2C(PPO):
    pass
