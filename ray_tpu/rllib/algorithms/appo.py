"""APPO — asynchronous PPO: IMPALA's architecture, PPO's surrogate.

Equivalent of the reference's APPO (reference: rllib/algorithms/appo/appo.py
— IMPALA-style continuous async sampling, with the policy update swapped for
the PPO clipped surrogate over V-trace-corrected advantages, plus a slowly
refreshed target policy the surrogate is anchored to). TPU mapping is
IMPALA's: the V-trace recursion runs in-graph as a reverse lax.scan inside
the jitted learner step; runners are never blocked on the learner.
"""
from __future__ import annotations

from ray_tpu.rllib.algorithms.impala import (
    IMPALA,
    ImpalaConfig,
    vtrace_ingraph,
)
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import ActorCriticModule


def appo_loss(module, params, batch, config):
    """Clipped surrogate on V-trace advantages (pure jax).

    The ratio is target-policy/behavior-policy — the behavior logp recorded
    by the (stale-weighted) sampler stands in for PPO's logp_old, which is
    exactly the reference APPO formulation: off-policyness is both clipped
    (surrogate) and corrected (V-trace targets).
    """
    import jax
    import jax.numpy as jnp

    T, E = batch["rewards"].shape
    obs = batch["obs"].reshape(T * E, -1)
    logits, values = module.forward(params, obs)
    logits = logits.reshape(T, E, -1)
    values = values.reshape(T, E)
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(logp_all, batch["actions"][..., None], axis=-1)[..., 0]

    vs, pg_adv, rhos_raw = vtrace_ingraph(logp, values, batch, config)
    adv = (pg_adv - jnp.mean(pg_adv)) / (jnp.std(pg_adv) + 1e-8)

    ratio = jnp.exp(logp - batch["behavior_logp"])
    clip = config["clip_param"]
    surrogate = jnp.minimum(
        ratio * adv, jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv
    )
    policy_loss = -jnp.mean(surrogate)
    value_loss = jnp.mean(jnp.square(values - vs))
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    total = (
        policy_loss
        + config["vf_loss_coeff"] * value_loss
        - config["entropy_coeff"] * entropy
    )
    metrics = {
        "policy_loss": policy_loss,
        "vf_loss": value_loss,
        "entropy": entropy,
        "mean_rho": jnp.mean(rhos_raw),
    }
    return total, metrics


class APPOConfig(ImpalaConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.num_epochs = 2  # small reuse of each async batch
        self.algo_class = APPO


class APPO(IMPALA):
    def _build_learner(self) -> None:
        cfg = self.config
        module = ActorCriticModule(self.obs_dim, self.num_actions, cfg.hidden)
        self.learner = Learner(
            module,
            appo_loss,
            config={
                "gamma": cfg.gamma,
                "rho_max": cfg.vtrace_rho_clip,
                "c_max": cfg.vtrace_c_clip,
                "clip_param": cfg.clip_param,
                "vf_loss_coeff": cfg.vf_loss_coeff,
                "entropy_coeff": cfg.entropy_coeff,
            },
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self._inflight = {}
        self._broadcast_weights(self.learner.get_weights_np())
    # training_step is inherited from IMPALA: same async collection and
    # broadcast; num_epochs=2 reuses each batch through the clipped loss
