"""PPO — clipped-surrogate policy optimization with GAE.

Equivalent of the reference's PPO new-stack implementation
(reference: rllib/algorithms/ppo/ppo.py:420 training_step —
sample → learner update → weight broadcast; loss in
rllib/algorithms/ppo/torch/ppo_torch_learner.py). The loss is a pure jax
function jitted inside the Learner; minibatch epochs run as repeated jit
calls on fixed shapes.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import ActorCriticModule


def compute_gae(batch: dict, gamma: float, lam: float):
    """Generalized advantage estimation over a [T, E] rollout (host-side
    numpy — sequential scan over T is cheap and stays off the device)."""
    rewards, values = batch["rewards"], batch["values"]
    terms, dones = batch["terminateds"], batch["dones"]
    boot = batch.get("bootstrap_values")
    T, E = rewards.shape
    adv = np.zeros((T, E), np.float32)
    last_adv = np.zeros(E, np.float32)
    next_values = batch["last_values"]
    for t in range(T - 1, -1, -1):
        # truncated (done but not terminated) episodes still bootstrap — from
        # V(true final obs) recorded at the boundary, not the auto-reset obs
        not_term = 1.0 - terms[t].astype(np.float32)
        not_done = 1.0 - dones[t].astype(np.float32)
        nv = next_values
        if boot is not None:
            nv = np.where(dones[t], boot[t], next_values)
        delta = rewards[t] + gamma * nv * not_term - values[t]
        last_adv = delta + gamma * lam * not_done * last_adv
        adv[t] = last_adv
        next_values = values[t]
    returns = adv + values
    return adv, returns


def ppo_loss(module, params, batch, config):
    """Clipped surrogate + value loss + entropy bonus (pure jax)."""
    import jax.numpy as jnp

    import jax

    logits, values = module.forward(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=-1)[:, 0]
    ratio = jnp.exp(logp - batch["logp_old"])
    clip = config["clip_param"]
    adv = batch["advantages"]
    surrogate = jnp.minimum(
        ratio * adv, jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv
    )
    policy_loss = -jnp.mean(surrogate)
    value_loss = jnp.mean(jnp.square(values - batch["value_targets"]))
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    total = (
        policy_loss
        + config["vf_loss_coeff"] * value_loss
        - config["entropy_coeff"] * entropy
    )
    metrics = {
        "policy_loss": policy_loss,
        "vf_loss": value_loss,
        "entropy": entropy,
        "mean_kl": jnp.mean(batch["logp_old"] - logp),
    }
    return total, metrics


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.gae_lambda = 0.95
        # frame_shape=(H, W, C) switches the policy/value net to the conv
        # trunk (ConvActorCriticModule) — the Atari-class configuration
        # (reference: VisionNetwork selection for image observation spaces)
        self.frame_shape = None
        self.algo_class = PPO


def _ac_module_factory(hidden, frame_shape):
    """Module factory shared by runner actors and the learner: conv trunk
    for frame observations (config.hidden's LAST width sizes the dense
    layer after the convs), MLP otherwise."""
    if frame_shape is not None:
        from ray_tpu.rllib.rl_module import ConvActorCriticModule

        dense = int(hidden[-1]) if hidden else 128
        return lambda obs_dim, n_act: ConvActorCriticModule(
            obs_dim, n_act, frame_shape, hidden=dense)
    return lambda obs_dim, n_act: ActorCriticModule(obs_dim, n_act, hidden)


class PPO(Algorithm):
    runner_mode = "actor_critic"

    def _runner_factory(self):
        return _ac_module_factory(tuple(self.config.hidden),
                                  self.config.frame_shape)

    def _build_learner(self) -> None:
        cfg = self.config
        module = _ac_module_factory(tuple(cfg.hidden), cfg.frame_shape)(
            self.obs_dim, self.num_actions)
        self.learner = Learner(
            module,
            ppo_loss,
            config={
                "clip_param": cfg.clip_param,
                "vf_loss_coeff": cfg.vf_loss_coeff,
                "entropy_coeff": cfg.entropy_coeff,
            },
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self._rng = np.random.default_rng(cfg.seed + 7)
        self._broadcast_weights(self.learner.get_weights_np())

    def training_step(self) -> dict:
        cfg = self.config
        batches = self._sample_all()
        # flatten [T, E] rollouts into one training batch
        flat = {"obs": [], "actions": [], "logp_old": [], "advantages": [],
                "value_targets": []}
        for b in batches:
            adv, ret = compute_gae(b, cfg.gamma, cfg.gae_lambda)
            T, E = b["rewards"].shape
            flat["obs"].append(b["obs"].reshape(T * E, -1))
            flat["actions"].append(b["actions"].reshape(-1))
            flat["logp_old"].append(b["logp"].reshape(-1))
            flat["advantages"].append(adv.reshape(-1))
            flat["value_targets"].append(ret.reshape(-1))
        train = {k: np.concatenate(v) for k, v in flat.items()}
        adv = train["advantages"]
        train["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)

        n = len(train["actions"])
        mb = min(cfg.minibatch_size, n)
        metrics_acc: dict[str, list[float]] = {}
        for _ in range(cfg.num_epochs):
            perm = self._rng.permutation(n)
            for start in range(0, n - mb + 1, mb):
                idx = perm[start : start + mb]  # fixed mb => stable jit shapes
                minibatch = {k: v[idx] for k, v in train.items()}
                m = self.learner.update(minibatch)
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
        self._broadcast_weights(self.learner.get_weights_np())
        return {k: float(np.mean(v)) for k, v in metrics_acc.items()}
