"""Contextual bandits — LinUCB and Linear Thompson Sampling.

Equivalent of the reference's bandit algorithms (reference:
rllib/algorithms/bandit/bandit.py — BanditLinUCB, BanditLinTS over
rllib/algorithms/bandit/bandit_torch_model.py's linear posteriors).
Closed-form linear posteriors per arm (A = I*lambda + sum x x^T,
b = sum r x): no gradient learner, no replay — the "training" is a
rank-1 posterior update per observed (context, arm, reward), so these run
entirely on the driver against a bandit-style env (reset -> context,
step(arm) -> reward; episodes are length-1 by convention).
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env


class _LinearPosterior:
    """Per-arm ridge posterior: A^-1 kept incrementally (Sherman-Morrison)."""

    def __init__(self, dim: int, lam: float):
        self.A_inv = np.eye(dim) / lam
        self.b = np.zeros(dim)

    def update(self, x: np.ndarray, r: float) -> None:
        Ax = self.A_inv @ x
        self.A_inv -= np.outer(Ax, Ax) / (1.0 + x @ Ax)
        self.b += r * x

    @property
    def theta(self) -> np.ndarray:
        return self.A_inv @ self.b


class BanditConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.exploration = "ucb"  # "ucb" (LinUCB) | "ts" (Thompson)
        self.ucb_alpha = 1.0
        self.ts_scale = 1.0
        self.ridge_lambda = 1.0
        self.steps_per_iteration = 64
        self.algo_class = Bandit


class BanditLinUCBConfig(BanditConfig):
    pass


class BanditLinTSConfig(BanditConfig):
    def __init__(self):
        super().__init__()
        self.exploration = "ts"


class Bandit(Algorithm):
    """Driver-side bandit loop (no EnvRunner actors: arms are evaluated
    per-context and the posterior update is O(d^2) — actor round-trips
    would dominate)."""

    def _setup(self) -> None:
        cfg = self.config
        self.env = make_env(cfg.env_spec)
        obs0 = np.asarray(self.env.reset(seed=cfg.seed or 0), np.float32)
        self.obs_dim = int(obs0.shape[0])
        self.num_actions = int(getattr(self.env, "num_actions", 2))
        self._posteriors = [
            _LinearPosterior(self.obs_dim, cfg.ridge_lambda)
            for _ in range(self.num_actions)
        ]
        self._rng = np.random.default_rng(cfg.seed or 0)
        self._ctx = obs0
        self._lifetime_reward = 0.0
        self._lifetime_steps = 0

    def _build_learner(self) -> None:  # pragma: no cover — closed-form
        pass

    def _score_arms(self, x: np.ndarray) -> np.ndarray:
        cfg = self.config
        scores = np.empty(self.num_actions)
        for a, post in enumerate(self._posteriors):
            if cfg.exploration == "ts":
                # sample theta ~ N(theta_hat, scale^2 * A^-1)
                theta = self._rng.multivariate_normal(
                    post.theta, cfg.ts_scale**2 * post.A_inv)
                scores[a] = theta @ x
            else:
                var = float(x @ post.A_inv @ x)
                scores[a] = post.theta @ x + cfg.ucb_alpha * np.sqrt(var)
        return scores

    def compute_action(self, obs: np.ndarray) -> int:
        """Greedy (exploitation-only) arm for evaluation."""
        x = np.asarray(obs, np.float32)
        return int(np.argmax([p.theta @ x for p in self._posteriors]))

    def training_step(self) -> dict:
        cfg = self.config
        total = 0.0
        for _ in range(cfg.steps_per_iteration):
            x = self._ctx
            arm = int(np.argmax(self._score_arms(x)))
            _obs, r, term, trunc = self.env.step(arm)
            self._posteriors[arm].update(x, float(r))
            total += float(r)
            self._ctx = np.asarray(
                self.env.reset() if (term or trunc) else _obs, np.float32)
        self._lifetime_reward += total
        self._lifetime_steps += cfg.steps_per_iteration
        return {
            "mean_reward": total / cfg.steps_per_iteration,
            "lifetime_mean_reward":
                self._lifetime_reward / self._lifetime_steps,
        }

    def train(self) -> dict:
        metrics = self.training_step()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        return metrics

    def stop(self) -> None:
        try:
            self.env.close()
        except Exception:
            pass
