"""MADDPG — Multi-Agent DDPG with centralized critics (Lowe et al. 2017).

Equivalent of the reference's MADDPG (reference: rllib_contrib/maddpg —
per-agent deterministic actors trained against CENTRALIZED critics that see
the joint observation and joint action; execution stays decentralized).
This closes the multi-agent continuous-control family: QMIX covers
cooperative discrete agents via value mixing, MADDPG covers continuous
agents via centralized Q. Ships with `ParticleMeet`, a cooperative
continuous multi-agent env in the simple_spread mold (agents steer to
cover a landmark; reward = -sum of distances), so the algorithm is
testable without external simulators.

Self-contained like Dreamer/AlphaZero: in-process vectorized rollouts
with Gaussian exploration noise, a joint-transition replay buffer, and
jitted per-agent actor/critic updates with Polyak-averaged targets.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rl_module import ActorCriticModule, _init_linear


class ParticleMeet:
    """N agents on the 2D unit plane steer (velocity actions in [-1,1]^2)
    toward a shared landmark. obs_i = [own_pos, landmark - own_pos,
    other agents' relative pos]; cooperative reward = -mean distance."""

    def __init__(self, n_agents: int = 2, episode_len: int = 25,
                 seed: int = 0):
        self.n = n_agents
        self.episode_len = episode_len
        self.obs_dim = 4 + 2 * (n_agents - 1)
        self.action_dim = 2
        self._rng = np.random.default_rng(seed)
        self._t = 0

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.pos = self._rng.uniform(-1, 1, (self.n, 2)).astype(np.float32)
        self.landmark = self._rng.uniform(-1, 1, 2).astype(np.float32)
        self._t = 0
        return self._obs()

    def _obs(self) -> np.ndarray:
        obs = []
        for i in range(self.n):
            rel_others = [self.pos[j] - self.pos[i]
                          for j in range(self.n) if j != i]
            obs.append(np.concatenate(
                [self.pos[i], self.landmark - self.pos[i], *rel_others]))
        return np.asarray(obs, np.float32)          # [n, obs_dim]

    def step(self, actions: np.ndarray):
        """actions [n, 2] in [-1, 1] -> (obs, reward, terminated, truncated).
        Reward is SHARED (cooperative)."""
        self.pos = np.clip(self.pos + 0.1 * np.clip(actions, -1, 1), -2, 2)
        self._t += 1
        dist = np.linalg.norm(self.pos - self.landmark, axis=-1)
        reward = -float(dist.mean())
        return self._obs(), reward, False, self._t >= self.episode_len


def _mlp_init(rng, dims, out_scale=0.01):
    layers = [_init_linear(rng, dims[i], dims[i + 1], np.sqrt(2))
              for i in range(len(dims) - 2)]
    layers.append(_init_linear(rng, dims[-2], dims[-1], out_scale))
    return layers


class MADDPGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.n_agents = 2
        self.episode_len = 25
        self.buffer_capacity = 50_000
        self.learning_starts = 512
        self.rollout_episodes = 8       # per training_step
        self.updates_per_iteration = 32
        self.exploration_noise = 0.3
        self.noise_decay_steps = 20_000
        self.tau = 0.01                 # Polyak target averaging
        self.lr = 1e-3
        self.algo_class = MADDPG


class MADDPG(Algorithm):
    """Per-agent actors mu_i(o_i); centralized critics
    Q_i(o_1..o_n, a_1..a_n) trained by joint TD; actor i ascends
    Q_i(o, mu_i(o_i), a_{-i}) with the other agents' dataset actions."""

    def _setup(self) -> None:
        import jax

        cfg = self.config
        self.env = ParticleMeet(cfg.n_agents, cfg.episode_len,
                                seed=cfg.seed or 0)
        n, od, ad = cfg.n_agents, self.env.obs_dim, self.env.action_dim
        self.n_agents, self.obs_dim, self.action_dim = n, od, ad
        rng = np.random.default_rng(cfg.seed or 0)
        hidden = tuple(cfg.hidden)
        self.params = []
        for _ in range(n):
            self.params.append({
                "pi": _mlp_init(rng, [od, *hidden, ad]),
                "q": _mlp_init(rng, [n * (od + ad), *hidden, 1],
                               out_scale=1.0),
            })
        self.target_params = jax.tree.map(np.copy, self.params)
        import optax

        self._tx = optax.adam(cfg.lr)
        self._opt = [self._tx.init(p) for p in self.params]
        # the shared preallocated ring buffer, joint rows flattened to
        # [n*od] / [n*ad] — O(1) vectorized add/sample like the rest of
        # the off-policy family
        self._buf = ReplayBuffer(cfg.buffer_capacity, n * od,
                                 seed=cfg.seed or 0, action_dim=n * ad)
        self._rng = rng
        self._env_steps = 0
        self._jit_update = jax.jit(self._update_impl)

    def _build_learner(self) -> None:  # pragma: no cover — self-contained
        pass

    # -- numpy policies (decentralized execution) --

    def _act(self, obs: np.ndarray, noise: float) -> np.ndarray:
        acts = []
        for i in range(self.n_agents):
            raw = ActorCriticModule._mlp_np(self.params[i]["pi"], obs[i][None])
            a = np.tanh(raw[0]) + noise * self._rng.standard_normal(
                self.action_dim)
            acts.append(np.clip(a, -1, 1))
        return np.asarray(acts, np.float32)

    def _noise(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps / max(1, cfg.noise_decay_steps))
        return cfg.exploration_noise * (1.0 - frac) + 0.02 * frac

    # -- jitted joint update --

    @staticmethod
    def _mlp(layers, x):
        # rl_module's shared forward (tanh trunk, linear head) — the numpy
        # twin is what _act uses, so rollout and learner stay in lockstep
        from ray_tpu.rllib.rl_module import _mlp_jax

        return _mlp_jax(layers, x)

    def _update_impl(self, params, target_params, opt_states, batch):
        """One TD + policy-gradient step for EVERY agent (jitted whole)."""
        import jax
        import jax.numpy as jnp
        import optax

        obs, acts, rew, next_obs, done = (
            batch["obs"], batch["actions"], batch["rewards"],
            batch["next_obs"], batch["dones"],
        )                                           # [B,n,od],[B,n,ad],[B]...
        B = obs.shape[0]
        gamma = self.config.gamma
        joint_next_act = jnp.concatenate(
            [jnp.tanh(self._mlp(target_params[i]["pi"], next_obs[:, i]))
             for i in range(self.n_agents)], axis=-1)
        joint_next = jnp.concatenate(
            [next_obs.reshape(B, -1), joint_next_act], axis=-1)
        joint_obs_flat = obs.reshape(B, -1)
        joint_act_flat = acts.reshape(B, -1)

        new_params, new_opts, metrics = [], [], {}
        for i in range(self.n_agents):
            q_next = self._mlp(target_params[i]["q"], joint_next)[:, 0]
            target = rew + gamma * (1.0 - done) * q_next
            target = jax.lax.stop_gradient(target)

            def critic_loss(q_layers):
                q = self._mlp(
                    q_layers,
                    jnp.concatenate([joint_obs_flat, joint_act_flat], -1),
                )[:, 0]
                return jnp.mean(jnp.square(q - target))

            def actor_loss(pi_layers, q_layers):
                my_act = jnp.tanh(self._mlp(pi_layers, obs[:, i]))
                joint = acts.at[:, i].set(my_act).reshape(B, -1)
                q = self._mlp(
                    q_layers,
                    jnp.concatenate([joint_obs_flat, joint], -1))[:, 0]
                return -jnp.mean(q)

            p = params[i]
            c_loss, c_grad = jax.value_and_grad(critic_loss)(p["q"])
            a_loss, a_grad = jax.value_and_grad(actor_loss)(p["pi"], p["q"])
            grads = {"pi": a_grad, "q": c_grad}
            updates, opt = self._tx.update(grads, opt_states[i], p)
            new_params.append(optax.apply_updates(p, updates))
            new_opts.append(opt)
            metrics[f"critic_loss_{i}"] = c_loss
            metrics[f"actor_loss_{i}"] = a_loss

        tau = self.config.tau
        new_targets = jax.tree.map(
            lambda t, p: (1 - tau) * t + tau * p, target_params, new_params)
        return new_params, new_targets, new_opts, metrics

    def training_step(self) -> dict:
        cfg = self.config
        n, od, ad = self.n_agents, self.obs_dim, self.action_dim
        returns = []
        for _ in range(cfg.rollout_episodes):
            obs = self.env.reset()
            ep = {"obs": [], "acts": [], "rew": [], "next": [], "term": []}
            ep_ret = 0.0
            for _t in range(cfg.episode_len):
                acts = self._act(obs, self._noise())
                next_obs, rew, term, trunc = self.env.step(acts)
                ep["obs"].append(obs.reshape(-1))
                ep["acts"].append(acts.reshape(-1))
                ep["rew"].append(rew)
                ep["next"].append(next_obs.reshape(-1))
                ep["term"].append(term)
                obs = next_obs
                ep_ret += rew
                self._env_steps += 1
                if term or trunc:
                    break
            self._buf.add_batch(
                np.asarray(ep["obs"], np.float32),
                np.asarray(ep["acts"], np.float32),
                np.asarray(ep["rew"], np.float32),
                np.asarray(ep["next"], np.float32),
                np.asarray(ep["term"], np.bool_),
            )
            returns.append(ep_ret)

        metrics_acc: dict[str, list[float]] = {}
        if len(self._buf) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                mb = self._buf.sample(cfg.minibatch_size)
                B = len(mb["rewards"])
                batch = {
                    "obs": mb["obs"].reshape(B, n, od),
                    "actions": mb["actions"].reshape(B, n, ad),
                    "rewards": mb["rewards"],
                    "next_obs": mb["next_obs"].reshape(B, n, od),
                    "dones": mb["terminateds"].astype(np.float32),
                }
                self.params, self.target_params, self._opt, m = (
                    self._jit_update(self.params, self.target_params,
                                     self._opt, batch))
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(float(v))
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        out["episode_return_mean"] = float(np.mean(returns))
        out["exploration_noise"] = self._noise()
        out["env_steps"] = self._env_steps
        return out

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        """Greedy joint action [n_agents, action_dim] (no noise)."""
        return self._act(np.asarray(obs, np.float32), 0.0)

    def train(self) -> dict:
        metrics = self.training_step()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        return metrics

    # -- checkpointing (self-contained: no Learner) --

    def save_state(self) -> dict:
        import jax

        return {
            "iteration": self.iteration,
            "params": jax.tree.map(np.asarray, self.params),
            "target_params": jax.tree.map(np.asarray, self.target_params),
            # per-agent Adam moments — without them a resumed run silently
            # restarts optimization from zeroed first/second moments
            "opt": jax.tree.map(np.asarray, self._opt),
            "env_steps": self._env_steps,
        }

    def load_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        self.params = state["params"]
        self.target_params = state["target_params"]
        if "opt" in state:
            self._opt = state["opt"]
        self._env_steps = state["env_steps"]
