"""SAC — soft actor-critic (discrete-action variant).

Equivalent of the reference's SAC (reference: rllib/algorithms/sac/sac.py,
losses in sac/sac_torch_policy.py; discrete support per the public
SAC-Discrete formulation). Off-policy: replay buffer, twin soft Q networks
with polyak targets, entropy-regularized policy, optional automatic
temperature tuning toward a target entropy.

One Learner/optimizer over {pi, q1, q2, log_alpha}: the loss terms isolate
their gradients with stop_gradient, so a single optax chain updates all
groups in one jitted step (TPU-friendly — one compiled program per update).
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rl_module import ActorCriticModule, QModule, _mlp_jax


class SACModule:
    """Policy + twin Q over the same obs space (discrete actions)."""

    def __init__(self, obs_dim: int, num_actions: int, hidden=(64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.pi = ActorCriticModule(obs_dim, num_actions, hidden)
        self.q = QModule(obs_dim, num_actions, hidden)

    def init(self, seed: int = 0) -> dict:
        return {
            "pi": self.pi.init(seed)["pi"],
            "q1": self.q.init(seed + 1)["q"],
            "q2": self.q.init(seed + 2)["q"],
            # start cool (alpha = 0.1): alpha = 1 lets the entropy bonus
            # drown small task rewards before temperature tuning catches up
            "log_alpha": np.float32(np.log(0.1)),
        }

    # numpy rollout path: sample from the softmax policy
    def sample_actions_np(self, params, obs, rng):
        logits = ActorCriticModule._mlp_np(params["pi"], obs)
        z = logits - logits.max(axis=-1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=-1, keepdims=True)
        cum = np.cumsum(p, axis=-1)
        r = rng.uniform(size=(len(obs), 1))
        # float32 cumsum can top out below 1.0 — clamp so r in (cum[-1], 1)
        # never yields the out-of-range index num_actions
        actions = np.minimum(
            (cum < r).sum(axis=-1), self.num_actions - 1
        ).astype(np.int32)
        return actions

    def forward_np(self, params, obs):
        # epsilon_greedy runner mode calls this; SAC uses its own sampling
        return ActorCriticModule._mlp_np(params["pi"], obs)


def sac_loss(module, params, batch, config):
    import jax
    import jax.numpy as jnp

    alpha = jnp.exp(params["log_alpha"])
    gamma = config["gamma"]
    target_entropy = config["target_entropy"]

    def policy_dist(pi_params, obs):
        logits = _mlp_jax(pi_params, obs)
        logp = jax.nn.log_softmax(logits)
        return jnp.exp(logp), logp

    # --- Q losses (TD toward soft target) ---
    probs_next, logp_next = policy_dist(params["pi"], batch["next_obs"])
    q1_t = _mlp_jax(batch["target_q1"], batch["next_obs"])
    q2_t = _mlp_jax(batch["target_q2"], batch["next_obs"])
    q_t = jnp.minimum(q1_t, q2_t)
    # exact expectation over discrete actions
    v_next = jnp.sum(
        probs_next * (q_t - jax.lax.stop_gradient(alpha) * logp_next), axis=-1
    )
    not_term = 1.0 - batch["terminateds"].astype(jnp.float32)
    target = jax.lax.stop_gradient(batch["rewards"] + gamma * not_term * v_next)

    q1 = _mlp_jax(params["q1"], batch["obs"])
    q2 = _mlp_jax(params["q2"], batch["obs"])
    a = batch["actions"][:, None]
    q1_a = jnp.take_along_axis(q1, a, axis=-1)[:, 0]
    q2_a = jnp.take_along_axis(q2, a, axis=-1)[:, 0]
    q_loss = jnp.mean(jnp.square(q1_a - target)) + jnp.mean(
        jnp.square(q2_a - target)
    )

    # --- policy loss: E_a[alpha*logp - minQ] with Q frozen ---
    probs, logp = policy_dist(params["pi"], batch["obs"])
    q_min = jax.lax.stop_gradient(jnp.minimum(q1, q2))
    pi_loss = jnp.mean(
        jnp.sum(probs * (jax.lax.stop_gradient(alpha) * logp - q_min), axis=-1)
    )

    # --- temperature loss toward target entropy ---
    entropy = -jnp.sum(jax.lax.stop_gradient(probs * logp), axis=-1)
    alpha_loss = jnp.mean(alpha * (entropy - target_entropy))

    total = q_loss + pi_loss + config["alpha_lr_scale"] * alpha_loss
    return total, {
        "q_loss": q_loss,
        "pi_loss": pi_loss,
        "alpha": alpha,
        "entropy_mean": jnp.mean(entropy),
    }


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.buffer_capacity = 50_000
        self.learning_starts = 500
        self.updates_per_iteration = 32
        self.tau = 0.01  # polyak factor for target Q nets
        self.target_entropy_scale = 0.3  # fraction of max entropy ln(A)
        self.alpha_lr_scale = 1.0
        self.lr = 3e-4
        self.algo_class = SAC


class SAC(Algorithm):
    runner_mode = "softmax"  # stochastic policy is the exploration

    def _runner_factory(self):
        hidden = tuple(self.config.hidden)
        return lambda obs_dim, n_act: SACModule(obs_dim, n_act, hidden)

    def _build_learner(self) -> None:
        cfg = self.config
        import math

        module = SACModule(self.obs_dim, self.num_actions, cfg.hidden)
        self.learner = Learner(
            module,
            sac_loss,
            config={
                "gamma": cfg.gamma,
                "target_entropy": cfg.target_entropy_scale
                * math.log(self.num_actions),
                "alpha_lr_scale": cfg.alpha_lr_scale,
            },
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self.buffer = ReplayBuffer(cfg.buffer_capacity, self.obs_dim, seed=cfg.seed)
        w = self.learner.get_weights_np()
        self._target_q1 = w["q1"]
        self._target_q2 = w["q2"]
        self._broadcast_weights(w, epsilon=0.0)  # stochastic policy explores

    def _polyak(self) -> None:
        import jax

        tau = self.config.tau
        w = self.learner.get_weights_np()
        self._target_q1 = jax.tree_util.tree_map(
            lambda t, o: (1 - tau) * t + tau * o, self._target_q1, w["q1"]
        )
        self._target_q2 = jax.tree_util.tree_map(
            lambda t, o: (1 - tau) * t + tau * o, self._target_q2, w["q2"]
        )

    def training_step(self) -> dict:
        cfg = self.config
        for b in self._sample_all():
            T, E = b["rewards"].shape
            self.buffer.add_batch(
                b["obs"].reshape(T * E, -1),
                b["actions"].reshape(-1),
                b["rewards"].reshape(-1),
                b["next_obs"].reshape(T * E, -1),
                b["terminateds"].reshape(-1),
            )
        metrics_acc: dict[str, list[float]] = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                mb = self.buffer.sample(cfg.minibatch_size)
                mb["target_q1"] = self._target_q1
                mb["target_q2"] = self._target_q2
                m = self.learner.update(mb)
                self._polyak()
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
        self._broadcast_weights(self.learner.get_weights_np(), epsilon=0.0)
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        out["replay_size"] = len(self.buffer)
        return out
