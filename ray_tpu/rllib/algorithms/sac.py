"""SAC — soft actor-critic, continuous and discrete.

Equivalent of the reference's SAC (reference: rllib/algorithms/sac/sac.py,
losses in sac/sac_torch_policy.py — canonical continuous squashed-Gaussian
form per Haarnoja et al. 2018, plus discrete support per the public
SAC-Discrete formulation). Off-policy: replay buffer, twin soft Q networks
with polyak targets, entropy-regularized policy, automatic temperature
tuning toward a target entropy. The env's action space selects the variant
at build time.

One Learner/optimizer over {pi, q1, q2, log_alpha}: the loss terms isolate
their gradients with stop_gradient, so a single optax chain updates all
groups in one jitted step (TPU-friendly — one compiled program per update).
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rl_module import (
    ActorCriticModule,
    DeterministicPolicyModule,
    QModule,
    _mlp_jax,
)


class SACModule:
    """Policy + twin Q over the same obs space (discrete actions)."""

    def __init__(self, obs_dim: int, num_actions: int, hidden=(64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.pi = ActorCriticModule(obs_dim, num_actions, hidden)
        self.q = QModule(obs_dim, num_actions, hidden)

    def init(self, seed: int = 0) -> dict:
        return {
            "pi": self.pi.init(seed)["pi"],
            "q1": self.q.init(seed + 1)["q"],
            "q2": self.q.init(seed + 2)["q"],
            # start cool (alpha = 0.1): alpha = 1 lets the entropy bonus
            # drown small task rewards before temperature tuning catches up
            "log_alpha": np.float32(np.log(0.1)),
        }

    # numpy rollout path: sample from the softmax policy
    def sample_actions_np(self, params, obs, rng):
        logits = ActorCriticModule._mlp_np(params["pi"], obs)
        z = logits - logits.max(axis=-1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=-1, keepdims=True)
        cum = np.cumsum(p, axis=-1)
        r = rng.uniform(size=(len(obs), 1))
        # float32 cumsum can top out below 1.0 — clamp so r in (cum[-1], 1)
        # never yields the out-of-range index num_actions
        actions = np.minimum(
            (cum < r).sum(axis=-1), self.num_actions - 1
        ).astype(np.int32)
        return actions

    def forward_np(self, params, obs):
        # epsilon_greedy runner mode calls this; SAC uses its own sampling
        return ActorCriticModule._mlp_np(params["pi"], obs)


def sac_loss(module, params, batch, config):
    import jax
    import jax.numpy as jnp

    alpha = jnp.exp(params["log_alpha"])
    gamma = config["gamma"]
    target_entropy = config["target_entropy"]

    def policy_dist(pi_params, obs):
        logits = _mlp_jax(pi_params, obs)
        logp = jax.nn.log_softmax(logits)
        return jnp.exp(logp), logp

    # --- Q losses (TD toward soft target) ---
    probs_next, logp_next = policy_dist(params["pi"], batch["next_obs"])
    q1_t = _mlp_jax(batch["target_q1"], batch["next_obs"])
    q2_t = _mlp_jax(batch["target_q2"], batch["next_obs"])
    q_t = jnp.minimum(q1_t, q2_t)
    # exact expectation over discrete actions
    v_next = jnp.sum(
        probs_next * (q_t - jax.lax.stop_gradient(alpha) * logp_next), axis=-1
    )
    not_term = 1.0 - batch["terminateds"].astype(jnp.float32)
    target = jax.lax.stop_gradient(batch["rewards"] + gamma * not_term * v_next)

    q1 = _mlp_jax(params["q1"], batch["obs"])
    q2 = _mlp_jax(params["q2"], batch["obs"])
    a = batch["actions"][:, None]
    q1_a = jnp.take_along_axis(q1, a, axis=-1)[:, 0]
    q2_a = jnp.take_along_axis(q2, a, axis=-1)[:, 0]
    q_loss = jnp.mean(jnp.square(q1_a - target)) + jnp.mean(
        jnp.square(q2_a - target)
    )

    # --- policy loss: E_a[alpha*logp - minQ] with Q frozen ---
    probs, logp = policy_dist(params["pi"], batch["obs"])
    q_min = jax.lax.stop_gradient(jnp.minimum(q1, q2))
    pi_loss = jnp.mean(
        jnp.sum(probs * (jax.lax.stop_gradient(alpha) * logp - q_min), axis=-1)
    )

    # --- temperature loss toward target entropy ---
    entropy = -jnp.sum(jax.lax.stop_gradient(probs * logp), axis=-1)
    alpha_loss = jnp.mean(alpha * (entropy - target_entropy))

    total = q_loss + pi_loss + config["alpha_lr_scale"] * alpha_loss
    return total, {
        "q_loss": q_loss,
        "pi_loss": pi_loss,
        "alpha": alpha,
        "entropy_mean": jnp.mean(entropy),
    }


class ContinuousSACModule:
    """Squashed-Gaussian policy + twin Q(s, a) (reference: SAC's canonical
    continuous form, sac_torch_model.py — Haarnoja et al. 2018; the
    discrete SACModule above is the derived variant)."""

    LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0

    def __init__(self, obs_dim: int, action_dim: int, action_bound: float,
                 hidden=(64, 64)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.action_bound = float(action_bound)
        self.hidden = tuple(hidden)
        self._det = DeterministicPolicyModule(
            obs_dim, action_dim, action_bound, hidden, twin_q=True
        )

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        from ray_tpu.rllib.rl_module import _init_linear

        dims = [self.obs_dim, *self.hidden]
        layers = [
            _init_linear(rng, dims[i], dims[i + 1], np.sqrt(2))
            for i in range(len(dims) - 1)
        ]
        # one head emitting [mu, log_std]
        layers.append(_init_linear(rng, dims[-1], 2 * self.action_dim, 0.01))
        base = self._det.init(seed + 1)
        return {
            "pi": layers,
            "q1": base["q1"],
            "q2": base["q2"],
            "log_alpha": np.float32(np.log(0.1)),
        }

    def _dist_np(self, params, obs):
        out = ActorCriticModule._mlp_np(params["pi"], obs)
        mu, log_std = np.split(out, 2, axis=-1)
        log_std = np.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)
        return mu, log_std

    def sample_actions_np(self, params, obs, rng):
        mu, log_std = self._dist_np(params, obs)
        eps = rng.standard_normal(mu.shape)
        return np.tanh(mu + np.exp(log_std) * eps) * self.action_bound

    # -- jax path --

    def dist(self, params, obs):
        import jax.numpy as jnp

        out = _mlp_jax(params["pi"], obs)
        mu, log_std = jnp.split(out, 2, axis=-1)
        return mu, jnp.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)

    def sample_and_logp(self, params, obs, key):
        """Reparameterized squashed-Gaussian sample + its log-prob (with
        the tanh change-of-variables correction)."""
        import jax
        import jax.numpy as jnp

        mu, log_std = self.dist(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mu.shape)
        pre = mu + std * eps
        logp_gauss = jnp.sum(
            -0.5 * (eps**2 + 2 * log_std + jnp.log(2 * jnp.pi)), axis=-1
        )
        tanh_pre = jnp.tanh(pre)
        # d tanh correction (numerically stable form)
        logp = logp_gauss - jnp.sum(
            2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre)), axis=-1
        )
        return tanh_pre * self.action_bound, logp

    def q_value(self, params, obs, actions, head: str = "q1"):
        return self._det.q_value(params, obs, actions, head)


def sac_continuous_loss(module, params, batch, config):
    """Twin-Q soft TD + reparameterized policy + temperature (pure jax;
    sampling keys ride the batch so the jitted signature stays fixed)."""
    import jax
    import jax.numpy as jnp

    alpha = jnp.exp(params["log_alpha"])
    gamma = config["gamma"]
    k1, k2 = jax.random.split(batch["rng"]["key"])

    a_next, logp_next = module.sample_and_logp(params, batch["next_obs"], k1)
    tgt = {"q1": batch["target_q1"], "q2": batch["target_q2"]}
    q_t = jnp.minimum(
        module.q_value(tgt, batch["next_obs"], a_next, "q1"),
        module.q_value(tgt, batch["next_obs"], a_next, "q2"),
    )
    not_term = 1.0 - batch["terminateds"].astype(jnp.float32)
    target = jax.lax.stop_gradient(
        batch["rewards"]
        + gamma * not_term * (q_t - jax.lax.stop_gradient(alpha) * logp_next)
    )
    q1 = module.q_value(params, batch["obs"], batch["actions"], "q1")
    q2 = module.q_value(params, batch["obs"], batch["actions"], "q2")
    q_loss = jnp.mean(jnp.square(q1 - target)) + jnp.mean(jnp.square(q2 - target))

    # policy: gradients flow through the ACTION into frozen-critic weights
    a_new, logp_new = module.sample_and_logp(params, batch["obs"], k2)
    frozen = {
        "q1": jax.lax.stop_gradient(params["q1"]),
        "q2": jax.lax.stop_gradient(params["q2"]),
    }
    q_pi = jnp.minimum(
        module.q_value(frozen, batch["obs"], a_new, "q1"),
        module.q_value(frozen, batch["obs"], a_new, "q2"),
    )
    pi_loss = jnp.mean(jax.lax.stop_gradient(alpha) * logp_new - q_pi)

    # temperature toward target entropy = -action_dim (standard heuristic)
    alpha_loss = -jnp.mean(
        params["log_alpha"]
        * jax.lax.stop_gradient(logp_new + config["target_entropy"])
    )
    total = q_loss + pi_loss + config["alpha_lr_scale"] * alpha_loss
    return total, {
        "q_loss": q_loss,
        "pi_loss": pi_loss,
        "alpha": alpha,
        "entropy_mean": -jnp.mean(logp_new),
    }


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.buffer_capacity = 50_000
        self.learning_starts = 500
        self.updates_per_iteration = 32
        self.tau = 0.01  # polyak factor for target Q nets
        self.target_entropy_scale = 0.3  # fraction of max entropy ln(A)
        self.alpha_lr_scale = 1.0
        self.lr = 3e-4
        self.algo_class = SAC


class SAC(Algorithm):
    runner_mode = "softmax"  # stochastic policy is the exploration

    def _setup(self) -> None:
        # action space selects the variant BEFORE runners are built
        from ray_tpu.rllib.env import make_env

        probe = make_env(self.config.env_spec)
        self._continuous = probe.continuous
        if self._continuous:
            self.runner_mode = "continuous"
            self._probe_action_dim = probe.action_dim
            self._probe_action_bound = probe.action_bound
        probe.close()
        super()._setup()

    def _runner_factory(self):
        hidden = tuple(self.config.hidden)
        if self._continuous:
            action_dim = self._probe_action_dim
            bound = self._probe_action_bound
            return lambda obs_dim, n_act: ContinuousSACModule(
                obs_dim, action_dim, bound, hidden)
        return lambda obs_dim, n_act: SACModule(obs_dim, n_act, hidden)

    def _build_learner(self) -> None:
        cfg = self.config
        import math

        if self._continuous:
            module = ContinuousSACModule(
                self.obs_dim, self.action_dim, self.action_bound, cfg.hidden
            )
            loss = sac_continuous_loss
            target_entropy = -float(self.action_dim)
            action_dim = self.action_dim
        else:
            module = SACModule(self.obs_dim, self.num_actions, cfg.hidden)
            loss = sac_loss
            target_entropy = cfg.target_entropy_scale * math.log(
                self.num_actions)
            action_dim = None
        self.learner = Learner(
            module,
            loss,
            config={
                "gamma": cfg.gamma,
                "target_entropy": target_entropy,
                "alpha_lr_scale": cfg.alpha_lr_scale,
            },
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self.buffer = ReplayBuffer(cfg.buffer_capacity, self.obs_dim,
                                   seed=cfg.seed, action_dim=action_dim)
        self._rng_step = 0
        w = self.learner.get_weights_np()
        self._target_q1 = w["q1"]
        self._target_q2 = w["q2"]
        self._broadcast_weights(w, epsilon=0.0)  # stochastic policy explores

    def _polyak(self) -> None:
        import jax

        tau = self.config.tau
        w = self.learner.get_weights_np()
        self._target_q1 = jax.tree_util.tree_map(
            lambda t, o: (1 - tau) * t + tau * o, self._target_q1, w["q1"]
        )
        self._target_q2 = jax.tree_util.tree_map(
            lambda t, o: (1 - tau) * t + tau * o, self._target_q2, w["q2"]
        )

    def training_step(self) -> dict:
        cfg = self.config
        for b in self._sample_all():
            T, E = b["rewards"].shape
            self.buffer.add_batch(
                b["obs"].reshape(T * E, -1),
                (b["actions"].reshape(T * E, -1) if self._continuous
                 else b["actions"].reshape(-1)),
                b["rewards"].reshape(-1),
                b["next_obs"].reshape(T * E, -1),
                b["terminateds"].reshape(-1),
            )
        metrics_acc: dict[str, list[float]] = {}
        if len(self.buffer) >= cfg.learning_starts:
            import jax

            for _ in range(cfg.updates_per_iteration):
                mb = self.buffer.sample(cfg.minibatch_size)
                mb["target_q1"] = self._target_q1
                mb["target_q2"] = self._target_q2
                if self._continuous:
                    # fresh sampling key each update, riding the batch so
                    # the jitted loss signature stays fixed. Nested in a
                    # dict: Learner's mesh path data-shards TOP-LEVEL
                    # ndarrays, and a shape-(2,) key must replicate
                    self._rng_step += 1
                    mb["rng"] = {"key": np.asarray(
                        jax.random.PRNGKey(cfg.seed * 100003 + self._rng_step))}
                m = self.learner.update(mb)
                self._polyak()
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
        self._broadcast_weights(self.learner.get_weights_np(), epsilon=0.0)
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        out["replay_size"] = len(self.buffer)
        return out
