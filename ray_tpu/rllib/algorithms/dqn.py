"""DQN — Q-learning with replay buffer and target network.

Equivalent of the reference's DQN
(reference: rllib/algorithms/dqn/dqn.py training_step — sample, store to
replay, update from replay, periodic target sync; loss in
dqn/torch/dqn_torch_learner, double-Q per Hasselt). Double-DQN targets by
default; epsilon-greedy exploration annealed per env step.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rl_module import QModule


def dqn_loss(module, params, batch, config):
    """Double-DQN TD loss (pure jax). target_params ride inside the batch
    so the jitted signature stays (params, opt_state, batch)."""
    import jax
    import jax.numpy as jnp

    q = module.forward(params, batch["obs"])
    q_taken = jnp.take_along_axis(q, batch["actions"][:, None], axis=-1)[:, 0]
    q_next_online = module.forward(params, batch["next_obs"])
    q_next_target = module.forward(batch["target_params"], batch["next_obs"])
    best = jnp.argmax(q_next_online, axis=-1)
    q_next = jnp.take_along_axis(q_next_target, best[:, None], axis=-1)[:, 0]
    not_term = 1.0 - batch["terminateds"].astype(q.dtype)
    # per-sample bootstrap discount gamma**k: n-step windows truncated at
    # episode/rollout boundaries carry k < n_step
    target = batch["rewards"] + batch["discounts"] * not_term * q_next
    td = q_taken - jax.lax.stop_gradient(target)
    weights = batch.get("weights")  # PER importance-sampling weights
    if weights is None:
        loss = jnp.mean(jnp.square(td))
    else:
        loss = jnp.mean(weights * jnp.square(td))
    return loss, {
        "q_mean": jnp.mean(q_taken),
        "td_abs": jnp.mean(jnp.abs(td)),
        # per-sample magnitudes for PER priority refresh (underscore
        # prefix: Learner returns these as arrays, not scalar metrics)
        "_td_abs": jnp.abs(td),
    }


def c51_loss(module, params, batch, config):
    """C51 categorical TD loss (Bellemare et al. 2017): project the
    Bellman-shifted target distribution onto the fixed support, minimize
    cross-entropy. Double-DQN action selection on the EXPECTED online Q;
    per-sample cross-entropy doubles as the PER priority signal."""
    import jax
    import jax.numpy as jnp

    z = jnp.asarray(module.support)                       # [K]
    K = module.n_atoms
    dz = (module.v_max - module.v_min) / (K - 1)

    logits = module.logits(params, batch["obs"])          # [B, A, K]
    logp_taken = jax.nn.log_softmax(
        jnp.take_along_axis(
            logits, batch["actions"][:, None, None].repeat(K, -1), axis=1
        )[:, 0], axis=-1)                                 # [B, K]

    q_next_online = module.forward(params, batch["next_obs"])
    best = jnp.argmax(q_next_online, axis=-1)             # [B]
    t_logits = module.logits(batch["target_params"], batch["next_obs"])
    p_next = jax.nn.softmax(
        jnp.take_along_axis(
            t_logits, best[:, None, None].repeat(K, -1), axis=1
        )[:, 0], axis=-1)                                 # [B, K]
    p_next = jax.lax.stop_gradient(p_next)

    not_term = 1.0 - batch["terminateds"].astype(jnp.float32)
    tz = jnp.clip(
        batch["rewards"][:, None]
        + batch["discounts"][:, None] * not_term[:, None] * z[None, :],
        module.v_min, module.v_max)                       # [B, K]
    bj = (tz - module.v_min) / dz
    lo = jnp.floor(bj)
    hi = jnp.ceil(bj)
    # integer bj (lo == hi) would lose its mass to two zero weights;
    # route it entirely to lo
    w_lo = jnp.where(hi == lo, 1.0, hi - bj)
    w_hi = bj - lo
    # scatter via one-hot contraction: m[b, k] = sum_j p*(w at k)
    m = (jnp.einsum("bj,bjk->bk", p_next * w_lo,
                    jax.nn.one_hot(lo.astype(jnp.int32), K))
         + jnp.einsum("bj,bjk->bk", p_next * w_hi,
                      jax.nn.one_hot(hi.astype(jnp.int32), K)))
    ce = -jnp.sum(m * logp_taken, axis=-1)                # [B]
    weights = batch.get("weights")
    loss = jnp.mean(ce if weights is None else weights * ce)
    q_taken = jnp.sum(jnp.exp(logp_taken) * z, axis=-1)
    return loss, {
        "q_mean": jnp.mean(q_taken),
        "td_abs": jnp.mean(ce),
        "_td_abs": ce,  # PER priorities = categorical cross-entropy
    }


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.buffer_capacity = 50_000
        self.learning_starts = 500
        self.target_update_freq = 200  # in gradient steps
        self.updates_per_iteration = 32
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 5_000
        self.lr = 1e-3
        # rainbow-style extensions (each independently toggleable;
        # reference: dqn.py config dueling/n_step/prioritized_replay)
        self.dueling = False
        self.n_step = 1
        self.prioritized_replay = False
        self.per_alpha = 0.6
        self.per_beta = 0.4
        # C51 distributional head (reference: dqn config num_atoms,
        # v_min/v_max; Bellemare et al. 2017)
        self.distributional = False
        self.n_atoms = 51
        self.v_min = -10.0
        self.v_max = 10.0
        self.algo_class = DQN


def _make_q_module(obs_dim: int, n_act: int, hidden: tuple, dueling: bool,
                   distributional: bool, n_atoms: int, v_min: float,
                   v_max: float):
    """The ONE place learner and EnvRunner modules are constructed from —
    a structural mismatch between the two breaks weight broadcast."""
    if distributional:
        if dueling:
            raise ValueError(
                "dueling + distributional are not combined in this build; "
                "pick one (reference supports both only on the torch "
                "model path)")
        from ray_tpu.rllib.rl_module import DistributionalQModule

        return DistributionalQModule(obs_dim, n_act, hidden,
                                     n_atoms=n_atoms, v_min=v_min,
                                     v_max=v_max)
    return QModule(obs_dim, n_act, hidden, dueling=dueling)


class DQN(Algorithm):
    runner_mode = "epsilon_greedy"

    def _module_args(self) -> tuple:
        cfg = self.config
        return (tuple(cfg.hidden), cfg.dueling, cfg.distributional,
                cfg.n_atoms, cfg.v_min, cfg.v_max)

    def _runner_factory(self):
        # close over config SCALARS only — the factory ships to EnvRunner
        # actors and must not drag the whole Algorithm along
        args = self._module_args()
        return lambda obs_dim, n_act: _make_q_module(obs_dim, n_act, *args)

    def _build_learner(self) -> None:
        cfg = self.config
        module = _make_q_module(self.obs_dim, self.num_actions,
                                *self._module_args())
        self.learner = Learner(
            module,
            c51_loss if cfg.distributional else dqn_loss,
            config={"gamma": cfg.gamma},  # discounts ride per-sample in batch
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        if cfg.prioritized_replay:
            from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer

            self.buffer = PrioritizedReplayBuffer(
                cfg.buffer_capacity, self.obs_dim, seed=cfg.seed,
                alpha=cfg.per_alpha, beta=cfg.per_beta,
            )
        else:
            self.buffer = ReplayBuffer(cfg.buffer_capacity, self.obs_dim,
                                       seed=cfg.seed)
        self._target_params = self.learner.get_weights_np()
        self._grad_steps = 0
        self._broadcast_weights(self.learner.get_weights_np(), self._epsilon())

    def _nstep(self, b: dict) -> tuple:
        """Collapse a [T, E] rollout into n-step transitions: returns
        (obs_t, a_t, sum_{k<n} gamma^k r_{t+k}, next_obs_{t+n}, term) with
        the lookahead truncated at episode boundaries (reference:
        rllib/utils/replay_buffers n-step postprocessing)."""
        cfg = self.config
        n = cfg.n_step
        T, E = b["rewards"].shape
        if n <= 1:
            return (
                b["obs"].reshape(T * E, -1),
                b["actions"].reshape(-1),
                b["rewards"].reshape(-1),
                b["next_obs"].reshape(T * E, -1),
                b["terminateds"].reshape(-1),
                np.full(T * E, cfg.gamma, np.float32),
            )
        obs, actions, rewards, next_obs, term, disc = [], [], [], [], [], []
        for t in range(T):
            ret = np.zeros(E, np.float32)
            done_mask = np.zeros(E, np.bool_)
            term_mask = np.zeros(E, np.bool_)
            last = np.full(E, t, np.int64)
            for k in range(n):
                tk = t + k
                if tk >= T:
                    break
                ret = ret + np.where(done_mask, 0.0,
                                     cfg.gamma ** k * b["rewards"][tk])
                last = np.where(done_mask, last, tk)
                term_mask = term_mask | (~done_mask & b["terminateds"][tk])
                done_mask = done_mask | b["dones"][tk]
            obs.append(b["obs"][t])
            actions.append(b["actions"][t])
            rewards.append(ret)
            next_obs.append(b["next_obs"][last, np.arange(E)])
            term.append(term_mask)
            # bootstrap discount matches the ACTUAL window: gamma**steps,
            # where steps = last_included - t + 1 (< n at boundaries)
            disc.append((cfg.gamma ** (last - t + 1)).astype(np.float32))
        return (
            np.concatenate(obs),
            np.concatenate(actions),
            np.concatenate(rewards),
            np.concatenate(next_obs),
            np.concatenate(term),
            np.concatenate(disc),
        )

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._total_env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def training_step(self) -> dict:
        cfg = self.config
        for b in self._sample_all():
            self.buffer.add_batch(*self._nstep(b))
        metrics_acc: dict[str, list[float]] = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                mb = self.buffer.sample(cfg.minibatch_size)
                indices = mb.pop("indices", None)
                mb["target_params"] = self._target_params
                m = self.learner.update(mb)
                td_abs = m.pop("_td_abs", None)
                self._grad_steps += 1
                if self._grad_steps % cfg.target_update_freq == 0:
                    self._target_params = self.learner.get_weights_np()
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
                if indices is not None and td_abs is not None:
                    # priorities refresh straight from the jitted update's
                    # per-sample |td| — no host-side recompute
                    self.buffer.update_priorities(indices, td_abs)
        self._broadcast_weights(self.learner.get_weights_np(), self._epsilon())
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        out["epsilon"] = self._epsilon()
        out["replay_size"] = len(self.buffer)
        return out
