"""DQN — Q-learning with replay buffer and target network.

Equivalent of the reference's DQN
(reference: rllib/algorithms/dqn/dqn.py training_step — sample, store to
replay, update from replay, periodic target sync; loss in
dqn/torch/dqn_torch_learner, double-Q per Hasselt). Double-DQN targets by
default; epsilon-greedy exploration annealed per env step.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rl_module import QModule


def dqn_loss(module, params, batch, config):
    """Double-DQN TD loss (pure jax). target_params ride inside the batch
    so the jitted signature stays (params, opt_state, batch)."""
    import jax
    import jax.numpy as jnp

    q = module.forward(params, batch["obs"])
    q_taken = jnp.take_along_axis(q, batch["actions"][:, None], axis=-1)[:, 0]
    q_next_online = module.forward(params, batch["next_obs"])
    q_next_target = module.forward(batch["target_params"], batch["next_obs"])
    best = jnp.argmax(q_next_online, axis=-1)
    q_next = jnp.take_along_axis(q_next_target, best[:, None], axis=-1)[:, 0]
    not_term = 1.0 - batch["terminateds"].astype(q.dtype)
    target = batch["rewards"] + config["gamma"] * not_term * q_next
    td = q_taken - jax.lax.stop_gradient(target)
    loss = jnp.mean(jnp.square(td))
    return loss, {"q_mean": jnp.mean(q_taken), "td_abs": jnp.mean(jnp.abs(td))}


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.buffer_capacity = 50_000
        self.learning_starts = 500
        self.target_update_freq = 200  # in gradient steps
        self.updates_per_iteration = 32
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 5_000
        self.lr = 1e-3
        self.algo_class = DQN


class DQN(Algorithm):
    runner_mode = "epsilon_greedy"

    def _runner_factory(self):
        hidden = tuple(self.config.hidden)
        return lambda obs_dim, n_act: QModule(obs_dim, n_act, hidden)

    def _build_learner(self) -> None:
        cfg = self.config
        module = QModule(self.obs_dim, self.num_actions, cfg.hidden)
        self.learner = Learner(
            module,
            dqn_loss,
            config={"gamma": cfg.gamma},
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self.buffer = ReplayBuffer(cfg.buffer_capacity, self.obs_dim, seed=cfg.seed)
        self._target_params = self.learner.get_weights_np()
        self._grad_steps = 0
        self._broadcast_weights(self.learner.get_weights_np(), self._epsilon())

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._total_env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def training_step(self) -> dict:
        cfg = self.config
        for b in self._sample_all():
            T, E = b["rewards"].shape
            self.buffer.add_batch(
                b["obs"].reshape(T * E, -1),
                b["actions"].reshape(-1),
                b["rewards"].reshape(-1),
                b["next_obs"].reshape(T * E, -1),
                b["terminateds"].reshape(-1),
            )
        metrics_acc: dict[str, list[float]] = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                mb = self.buffer.sample(cfg.minibatch_size)
                mb["target_params"] = self._target_params
                m = self.learner.update(mb)
                self._grad_steps += 1
                if self._grad_steps % cfg.target_update_freq == 0:
                    self._target_params = self.learner.get_weights_np()
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
        self._broadcast_weights(self.learner.get_weights_np(), self._epsilon())
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        out["epsilon"] = self._epsilon()
        out["replay_size"] = len(self.buffer)
        return out
