from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig

__all__ = ["DQN", "DQNConfig", "PPO", "PPOConfig"]
from ray_tpu.rllib.algorithms.a2c import A2C, A2CConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig

__all__ += ["A2C", "A2CConfig", "SAC", "SACConfig"]

from ray_tpu.rllib.algorithms.impala import IMPALA, ImpalaConfig

__all__ += ["IMPALA", "ImpalaConfig"]

from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig

__all__ += ["APPO", "APPOConfig"]

from ray_tpu.rllib.algorithms.td3 import DDPG, DDPGConfig, TD3, TD3Config

__all__ += ["DDPG", "DDPGConfig", "TD3", "TD3Config"]

from ray_tpu.rllib.algorithms.apex import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.algorithms.es import ES, ESConfig

__all__ += ["ApexDQN", "ApexDQNConfig", "ES", "ESConfig"]

from ray_tpu.rllib.algorithms.bandit import (
    Bandit,
    BanditConfig,
    BanditLinTSConfig,
    BanditLinUCBConfig,
)
from ray_tpu.rllib.algorithms.qmix import QMIX, QMIXConfig

__all__ += ["Bandit", "BanditConfig", "BanditLinTSConfig",
            "BanditLinUCBConfig", "QMIX", "QMIXConfig"]

from ray_tpu.rllib.algorithms.r2d2 import R2D2, R2D2Config

__all__ += ["R2D2", "R2D2Config"]

from ray_tpu.rllib.algorithms.alphazero import (
    AlphaZero,
    AlphaZeroConfig,
    TicTacToe,
)

__all__ += ["AlphaZero", "AlphaZeroConfig", "TicTacToe"]

from ray_tpu.rllib.algorithms.dreamer import Dreamer, DreamerConfig

__all__ += ["Dreamer", "DreamerConfig"]

from ray_tpu.rllib.algorithms.slateq import (
    RecSysEnv,
    SlateQ,
    SlateQConfig,
)

__all__ += ["RecSysEnv", "SlateQ", "SlateQConfig"]

from ray_tpu.rllib.algorithms.ars import ARS, ARSConfig

__all__ += ["ARS", "ARSConfig"]

from ray_tpu.rllib.algorithms.maddpg import (
    MADDPG,
    MADDPGConfig,
    ParticleMeet,
)

__all__ += ["MADDPG", "MADDPGConfig", "ParticleMeet"]
