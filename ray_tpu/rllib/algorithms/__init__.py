from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig

__all__ = ["DQN", "DQNConfig", "PPO", "PPOConfig"]
