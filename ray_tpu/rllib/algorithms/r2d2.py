"""R2D2 — recurrent replay distributed DQN.

Equivalent of the reference's R2D2 (reference: rllib_contrib/r2d2/src/
rllib_r2d2/r2d2.py — DQN over an LSTM wrapper with `replay_sequence_length`
windows, stored recurrent states, and burn-in; Kapturowski et al. 2019).
TPU-first shape: the learner consumes fixed-length [B, T] sequence
minibatches through ONE jitted update whose recurrence is a `lax.scan`
(static shapes, compiler-unrolled burn-in prefix); rollout workers thread
GRU state in numpy and store it per-sequence ('stored state', not
zero-init, so replayed hidden states match collection).
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.replay_buffer import SequenceReplayBuffer
from ray_tpu.rllib.rl_module import RecurrentQModule


def r2d2_loss(module, params, batch, config):
    """Sequence double-Q TD loss with burn-in (pure jax).

    Burn-in: the first `burn_in` steps of each sequence warm the hidden
    state from the stored `state_in` under stop_gradient (both nets), and
    contribute no loss. Truncation boundaries (done without terminated)
    are masked out — their successor state is a different episode whose
    value must not bootstrap through. The final step of every sequence has
    no in-sequence successor and is likewise excluded.
    """
    import jax
    import jax.numpy as jnp

    burn = int(config["burn_in"])
    gamma = config["gamma"]
    tgt = batch["target_params"]
    obs, resets = batch["obs"], batch["resets"]
    h0_online = h0_target = batch["state_in"]
    if burn > 0:
        _, h0_online = module.forward_seq(
            params, obs[:, :burn], batch["state_in"], resets[:, :burn])
        _, h0_target = module.forward_seq(
            tgt, obs[:, :burn], batch["state_in"], resets[:, :burn])
        h0_online = jax.lax.stop_gradient(h0_online)
    obs_t, resets_t = obs[:, burn:], resets[:, burn:]
    q_online, _ = module.forward_seq(params, obs_t, h0_online, resets_t)
    q_target, _ = module.forward_seq(tgt, obs_t, h0_target, resets_t)

    actions = batch["actions"][:, burn:]
    rewards = batch["rewards"][:, burn:]
    dones = batch["dones"][:, burn:]
    terms = batch["terminateds"][:, burn:]

    q_taken = jnp.take_along_axis(q_online, actions[..., None], axis=-1)[..., 0]
    best_next = jnp.argmax(q_online[:, 1:], axis=-1)
    q_next = jnp.take_along_axis(
        q_target[:, 1:], best_next[..., None], axis=-1)[..., 0]
    not_term = 1.0 - terms[:, :-1].astype(q_next.dtype)
    target = rewards[:, :-1] + gamma * not_term * q_next
    td = q_taken[:, :-1] - jax.lax.stop_gradient(target)
    # truncated boundary: no valid in-sequence successor value
    valid = 1.0 - (dones[:, :-1] & ~terms[:, :-1]).astype(td.dtype)
    denom = jnp.maximum(jnp.sum(valid), 1.0)
    loss = jnp.sum(valid * jnp.square(td)) / denom
    return loss, {
        "q_mean": jnp.sum(valid * q_taken[:, :-1]) / denom,
        "td_abs": jnp.sum(valid * jnp.abs(td)) / denom,
    }


class R2D2Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.rollout_length = 16      # stored sequence length
        self.burn_in = 4              # warm-up prefix inside each sequence
        self.rnn_hidden = 64
        self.buffer_capacity = 4_000  # in sequences
        self.learning_starts = 64     # in sequences
        self.target_update_freq = 200
        self.updates_per_iteration = 32
        self.seq_minibatch = 32       # sequences per gradient step
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 8_000
        self.lr = 1e-3
        self.algo_class = R2D2


class R2D2(Algorithm):
    runner_mode = "epsilon_greedy"

    def _runner_factory(self):
        hidden = tuple(self.config.hidden)
        rnn_hidden = self.config.rnn_hidden
        return lambda obs_dim, n_act: RecurrentQModule(
            obs_dim, n_act, hidden, rnn_hidden=rnn_hidden)

    def _build_learner(self) -> None:
        cfg = self.config
        if not 0 <= cfg.burn_in < cfg.rollout_length:
            raise ValueError(
                f"burn_in ({cfg.burn_in}) must be < rollout_length "
                f"({cfg.rollout_length})")
        module = RecurrentQModule(self.obs_dim, self.num_actions,
                                  cfg.hidden, rnn_hidden=cfg.rnn_hidden)
        self.learner = Learner(
            module,
            r2d2_loss,
            config={"gamma": cfg.gamma, "burn_in": cfg.burn_in},
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self.buffer = SequenceReplayBuffer(
            cfg.buffer_capacity, cfg.rollout_length, self.obs_dim,
            state_dim=cfg.rnn_hidden, seed=cfg.seed)
        self._target_params = self.learner.get_weights_np()
        self._grad_steps = 0
        self._broadcast_weights(self.learner.get_weights_np(), self._epsilon())

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._total_env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def training_step(self) -> dict:
        cfg = self.config
        for b in self._sample_all():
            self.buffer.add_rollout(b)
        metrics_acc: dict[str, list[float]] = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                mb = self.buffer.sample(cfg.seq_minibatch)
                mb["target_params"] = self._target_params
                m = self.learner.update(mb)
                self._grad_steps += 1
                if self._grad_steps % cfg.target_update_freq == 0:
                    self._target_params = self.learner.get_weights_np()
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
        self._broadcast_weights(self.learner.get_weights_np(), self._epsilon())
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        out["epsilon"] = self._epsilon()
        out["replay_sequences"] = len(self.buffer)
        return out
