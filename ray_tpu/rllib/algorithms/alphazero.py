"""AlphaZero — self-play MCTS with a policy/value network.

Equivalent of the reference's AlphaZero (reference:
rllib_contrib/alpha_zero/src/rllib_alpha_zero/ — PUCT tree search guided
by a policy/value net, self-play targets = visit distributions + game
outcome; Silver et al. 2018). TPU-first split, same as the rest of
rllib here: the tree search runs in numpy on the host (it is pointer
chasing, not linear algebra), while training is one jitted update over
(board, visit-dist, outcome) minibatches.

Games implement the two-player zero-sum canonical-form protocol below
(board always from the player-to-move's perspective); TicTacToe ships
in-tree as the smoke-test game.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import ActorCriticModule, _init_linear


class TicTacToe:
    """Canonical-form tic-tac-toe: board [9] with +1 = player to move,
    -1 = opponent. `step` returns the NEXT canonical board (flipped)."""

    num_actions = 9
    obs_dim = 9

    def initial(self) -> np.ndarray:
        return np.zeros(9, np.float32)

    def legal_actions(self, board: np.ndarray) -> np.ndarray:
        return np.flatnonzero(board == 0)

    def step(self, board: np.ndarray, action: int) -> np.ndarray:
        nxt = board.copy()
        nxt[action] = 1.0
        return -nxt  # perspective flip: the other player moves next

    _LINES = [(0, 1, 2), (3, 4, 5), (6, 7, 8), (0, 3, 6), (1, 4, 7),
              (2, 5, 8), (0, 4, 8), (2, 4, 6)]

    def terminal(self, board: np.ndarray) -> tuple[bool, float]:
        """(done, outcome for the player to move). The PREVIOUS mover's
        stones are -1 after the flip, so a completed line of -1 means the
        player to move has LOST."""
        for a, b, c in self._LINES:
            if board[a] == board[b] == board[c] == -1.0:
                return True, -1.0
        if not (board == 0).any():
            return True, 0.0
        return False, 0.0


class AlphaZeroModule(ActorCriticModule):
    """Policy/value net over the canonical board: shared tanh trunk, a
    masked-softmax policy head and a tanh value head in [-1, 1]."""

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        dims = [self.obs_dim, *self.hidden]
        trunk = [_init_linear(rng, dims[i], dims[i + 1], np.sqrt(2))
                 for i in range(len(dims) - 1)]
        return {
            "trunk": trunk,
            "pi": [_init_linear(rng, dims[-1], self.num_actions, 0.01)],
            "vf": [_init_linear(rng, dims[-1], 1, 1.0)],
        }

    def forward_np(self, params, obs: np.ndarray):
        h = obs
        for layer in params["trunk"]:
            h = np.tanh(h @ layer["w"] + layer["b"])
        pi, vf = params["pi"][0], params["vf"][0]
        logits = h @ pi["w"] + pi["b"]
        value = np.tanh((h @ vf["w"] + vf["b"])[:, 0])
        return logits, value

    def forward(self, params, obs):
        import jax.numpy as jnp

        h = obs
        for layer in params["trunk"]:
            h = jnp.tanh(h @ layer["w"] + layer["b"])
        pi, vf = params["pi"][0], params["vf"][0]
        logits = h @ pi["w"] + pi["b"]
        value = jnp.tanh((h @ vf["w"] + vf["b"])[:, 0])
        return logits, value


def alphazero_loss(module, params, batch, config):
    """CE to the MCTS visit distribution + MSE to the game outcome
    (Silver et al. 2018 eq. 1; L2 comes from the optimizer's weight
    decay upstream — here adam + max_grad_norm)."""
    import jax.numpy as jnp

    logits, value = module.forward(params, batch["obs"])
    logp = jnp.where(batch["legal"], logits, -1e9)
    logp = logp - jnp.max(logp, axis=-1, keepdims=True)
    logp = logp - jnp.log(
        jnp.sum(jnp.exp(logp), axis=-1, keepdims=True))
    policy_loss = -jnp.mean(jnp.sum(batch["pi"] * logp, axis=-1))
    value_loss = jnp.mean((value - batch["z"]) ** 2)
    loss = policy_loss + value_loss
    return loss, {"policy_loss": policy_loss, "value_loss": value_loss}


class _MCTS:
    """PUCT search over canonical states (Silver et al. 2018 fig. 2)."""

    def __init__(self, game, module, params, c_puct: float = 1.5,
                 dirichlet_alpha: float = 0.6, noise_frac: float = 0.25,
                 rng: np.random.Generator | None = None):
        self.game = game
        self.module = module
        self.params = params
        self.c_puct = c_puct
        self.dirichlet_alpha = dirichlet_alpha
        self.noise_frac = noise_frac
        self.rng = rng or np.random.default_rng(0)
        # state key -> {P, N, W, legal}
        self.nodes: dict[bytes, dict] = {}

    def _expand(self, board: np.ndarray) -> float:
        """Create a leaf node from the net; returns its value estimate
        (player-to-move perspective)."""
        logits, value = self.module.forward_np(self.params, board[None, :])
        legal = self.game.legal_actions(board)
        mask = np.zeros(len(logits[0]), bool)
        mask[legal] = True
        z = logits[0] - logits[0].max()
        p = np.exp(z) * mask
        p = p / max(p.sum(), 1e-9)
        self.nodes[board.tobytes()] = {
            "P": p,
            "N": np.zeros(len(p), np.float64),
            "W": np.zeros(len(p), np.float64),
            "legal": mask,
        }
        return float(value[0])

    def _simulate(self, board: np.ndarray) -> float:
        """One descent; returns the subtree value for the player to move
        at `board`."""
        done, outcome = self.game.terminal(board)
        if done:
            return outcome
        key = board.tobytes()
        node = self.nodes.get(key)
        if node is None:
            return self._expand(board)
        n_total = node["N"].sum()
        q = np.where(node["N"] > 0, node["W"] / np.maximum(node["N"], 1), 0.0)
        u = (self.c_puct * node["P"] * np.sqrt(n_total + 1e-8)
             / (1.0 + node["N"]))
        score = np.where(node["legal"], q + u, -np.inf)
        action = int(np.argmax(score))
        # opponent's value negates on the way back up (zero-sum)
        value = -self._simulate(self.game.step(board, action))
        node["N"][action] += 1
        node["W"][action] += value
        return value

    def search(self, board: np.ndarray, n_sims: int,
               root_noise: bool = True) -> np.ndarray:
        """Visit distribution over actions after n_sims descents."""
        if board.tobytes() not in self.nodes:
            self._expand(board)
        root = self.nodes[board.tobytes()]
        if root_noise:
            legal = np.flatnonzero(root["legal"])
            noise = self.rng.dirichlet(
                [self.dirichlet_alpha] * len(legal))
            p = root["P"].copy()
            p[legal] = ((1 - self.noise_frac) * p[legal]
                        + self.noise_frac * noise)
            root["P"] = p
        for _ in range(n_sims):
            self._simulate(board)
        pi = root["N"] / max(root["N"].sum(), 1e-9)
        return pi


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.game = TicTacToe
        self.n_simulations = 48
        self.games_per_iteration = 24
        self.temperature_moves = 4  # sample proportionally early, then argmax
        self.buffer_capacity = 20_000
        self.updates_per_iteration = 24
        self.lr = 3e-3
        self.hidden = (64, 64)
        self.algo_class = AlphaZero


class AlphaZero(Algorithm):
    """Driver-side self-play + jitted policy/value updates."""

    def _setup(self) -> None:
        cfg = self.config
        self.game = cfg.game() if isinstance(cfg.game, type) else cfg.game
        self.module = AlphaZeroModule(
            self.game.obs_dim, self.game.num_actions, tuple(cfg.hidden))
        self.learner = Learner(
            self.module, alphazero_loss, config={},
            learning_rate=cfg.lr, max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh, seed=cfg.seed,
        )
        self._rng = np.random.default_rng(cfg.seed)
        self._buf: list[tuple] = []
        self._buf_head = 0

    def _build_learner(self) -> None:  # pragma: no cover — done in _setup
        pass

    def _store(self, row: tuple) -> None:
        if len(self._buf) < self.config.buffer_capacity:
            self._buf.append(row)
        else:
            self._buf[self._buf_head] = row
            self._buf_head = (self._buf_head + 1) % self.config.buffer_capacity

    def _self_play_game(self, params) -> float:
        cfg = self.config
        mcts = _MCTS(self.game, self.module, params, rng=self._rng)
        board = self.game.initial()
        history: list[tuple] = []  # (board, pi, legal)
        move = 0
        while True:
            done, outcome = self.game.terminal(board)
            if done:
                break
            pi = mcts.search(board, cfg.n_simulations)
            legal_mask = np.zeros(self.game.num_actions, bool)
            legal_mask[self.game.legal_actions(board)] = True
            history.append((board.copy(), pi.copy(), legal_mask))
            if move < cfg.temperature_moves:
                action = int(self._rng.choice(len(pi), p=pi))
            else:
                action = int(np.argmax(pi))
            board = self.game.step(board, action)
            move += 1
        # outcome is from the FINAL player-to-move's perspective; walk
        # back alternating signs
        z = outcome
        for board_t, pi_t, legal_t in reversed(history):
            z = -z
            self._store((board_t, pi_t, float(z), legal_t))
        return outcome

    def training_step(self) -> dict:
        cfg = self.config
        params = self.learner.get_weights_np()
        outcomes = [self._self_play_game(params)
                    for _ in range(cfg.games_per_iteration)]
        metrics_acc: dict[str, list[float]] = {}
        if len(self._buf) >= cfg.minibatch_size:
            for _ in range(cfg.updates_per_iteration):
                idx = self._rng.integers(0, len(self._buf),
                                         cfg.minibatch_size)
                rows = [self._buf[i] for i in idx]
                batch = {
                    "obs": np.stack([r[0] for r in rows]),
                    "pi": np.stack([r[1] for r in rows]).astype(np.float32),
                    "z": np.asarray([r[2] for r in rows], np.float32),
                    "legal": np.stack([r[3] for r in rows]),
                }
                for k, v in self.learner.update(batch).items():
                    metrics_acc.setdefault(k, []).append(v)
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        # draws are the optimal self-play fixed point for tic-tac-toe
        out["draw_rate"] = float(np.mean([o == 0.0 for o in outcomes]))
        out["replay_size"] = len(self._buf)
        return out

    def compute_action(self, board: np.ndarray, n_simulations: int | None = None) -> int:
        """Strongest move (no root noise, argmax visits)."""
        mcts = _MCTS(self.game, self.module, self.learner.get_weights_np(),
                     noise_frac=0.0, rng=self._rng)
        pi = mcts.search(board, n_simulations or self.config.n_simulations,
                         root_noise=False)
        return int(np.argmax(pi))

    def train(self) -> dict:
        metrics = self.training_step()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        return metrics

    def stop(self) -> None:
        pass
