"""SlateQ — slate recommendation RL via per-item Q decomposition.

Equivalent of the reference's SlateQ (reference:
rllib_contrib/slate_q/src/rllib_slateq/ — Ie et al. 2019: the value of a
SLATE decomposes as Q(s, A) = sum_{i in A} P(click i | s, A) * Q̄(s, i)
under a conditional-logit user choice model, so a combinatorial action
space trains through per-item values). Both learned pieces — the choice
model v(s, i) (MLE on logged click outcomes, null included) and the
item value Q̄(s, i) (SARSA on the decomposed next-slate value) — are
single jitted updates; slates are built greedily by choice-weighted
item value (the paper's top-k variant).

The in-tree `RecSysEnv` is the synthetic interest-evolution workload
(reference uses RecSim's interest evolution env): user interest drifts
toward clicked items, a null click costs patience, and myopic slates
(pure click-bait) underperform value-aware ones.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import _init_linear, _mlp


class RecSysEnv:
    """Synthetic slate-recommendation env.

    State (observable): user interest vector [d] + patience scalar.
    Action: a slate of `slate_size` item indices from a fixed catalog.
    The user clicks item i with conditional-logit probability
    P(i) ∝ exp(interest · features_i); the no-click option has constant
    logit. A click pays that item's engagement value and drifts interest
    toward the item; no-click drains patience; the episode ends when
    patience runs out or after max_episode_steps.
    """

    def __init__(self, n_items: int = 30, d: int = 6, slate_size: int = 3,
                 seed: int = 0, max_episode_steps: int = 40):
        rng = np.random.default_rng(seed)
        self.n_items = n_items
        self.d = d
        self.slate_size = slate_size
        self.max_episode_steps = max_episode_steps
        feats = rng.standard_normal((n_items, d))
        self.item_features = (feats / np.linalg.norm(feats, axis=1,
                                                     keepdims=True)
                              ).astype(np.float32)
        # engagement (reward) is DECORRELATED from clickability: items a
        # user is likely to click are not necessarily valuable, which is
        # exactly what separates SlateQ from a myopic click-rate ranker
        self.engagement = rng.uniform(0.1, 1.0, n_items).astype(np.float32)
        self._rng = rng
        self.obs_dim = d + 1

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        u = self._rng.standard_normal(self.d)
        self._interest = (u / np.linalg.norm(u)).astype(np.float32)
        self._patience = 1.0
        self._steps = 0
        return self._obs()

    def _obs(self) -> np.ndarray:
        return np.concatenate(
            [self._interest, [self._patience]]).astype(np.float32)

    def choice_probs(self, slate: np.ndarray) -> np.ndarray:
        """[slate_size + 1] — last entry is the null (no-click) option."""
        logits = self.item_features[slate] @ self._interest
        logits = np.concatenate([logits, [0.0]])  # null logit = 0
        z = np.exp(logits - logits.max())
        return z / z.sum()

    def step(self, slate: np.ndarray):
        self._steps += 1
        p = self.choice_probs(slate)
        pick = int(self._rng.choice(len(p), p=p))
        if pick == len(slate):  # null click
            reward = 0.0
            self._patience -= 0.25
            clicked = -1
        else:
            clicked = int(slate[pick])
            reward = float(self.engagement[clicked])
            self._patience = min(1.0, self._patience + 0.05)
            drift = 0.3 * self.item_features[clicked]
            v = self._interest + drift
            self._interest = (v / np.linalg.norm(v)).astype(np.float32)
        terminated = self._patience <= 0
        truncated = self._steps >= self.max_episode_steps
        return self._obs(), reward, terminated, truncated, clicked


class SlateQModule:
    """Two heads over (state, item_features): choice score v and item
    value Q̄, trained jointly in one param tree."""

    def __init__(self, obs_dim: int, item_dim: int, hidden: int = 64):
        self.obs_dim = obs_dim
        self.item_dim = item_dim
        self.hidden = hidden

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        n_in = self.obs_dim + self.item_dim
        h = self.hidden
        return {
            "choice": [
                _init_linear(rng, n_in, h, np.sqrt(2)),
                _init_linear(rng, h, 1, 0.1),
            ],
            "qbar": [
                _init_linear(rng, n_in, h, np.sqrt(2)),
                _init_linear(rng, h, 1, 0.1),
            ],
        }

    def scores_np(self, params, obs: np.ndarray, item_feats: np.ndarray):
        """(choice logits v [N], item values q [N]) for one state against
        all N candidate items (numpy; slate building on the driver)."""
        x = np.concatenate(
            [np.repeat(obs[None, :], len(item_feats), 0), item_feats], -1)
        v = _mlp(np, params["choice"], x)[:, 0]
        q = _mlp(np, params["qbar"], x)[:, 0]
        return v, q


def slateq_loss(module, params, batch, config):
    """Joint jitted update (pure jax).

    Choice model: conditional-logit MLE over (slate + null) with the
    observed pick. Q̄: SARSA — for transitions with a click, the target
    is r + gamma * sum_j P(j | s', A') Q̄_target(s', j) over the NEXT
    slate (null contributes 0), masked at terminals.
    """
    import jax
    import jax.numpy as jnp

    K = batch["slate_feats"].shape[1]

    def scores(p, head, obs, feats):
        B, k, D = feats.shape
        x = jnp.concatenate(
            [jnp.repeat(obs[:, None, :], k, 1), feats], -1)
        return _mlp(jnp, p[head], x.reshape(B * k, -1)).reshape(B, k)

    # -- choice MLE over slate + null (null logit fixed at 0) --
    v = scores(params, "choice", batch["obs"], batch["slate_feats"])
    v_full = jnp.concatenate([v, jnp.zeros((v.shape[0], 1))], -1)
    logp = jax.nn.log_softmax(v_full)
    choice_nll = -jnp.mean(
        jnp.take_along_axis(logp, batch["pick"][:, None], axis=-1)[:, 0])

    # -- decomposed SARSA for Q̄ on clicked transitions --
    q = scores(params, "qbar", batch["obs"], batch["slate_feats"])
    q_clicked = jnp.take_along_axis(
        q, jnp.minimum(batch["pick"], K - 1)[:, None], axis=-1)[:, 0]
    tgt = batch["target_params"]
    v_next = scores(tgt, "choice", batch["next_obs"], batch["next_feats"])
    q_next = scores(tgt, "qbar", batch["next_obs"], batch["next_feats"])
    v_next_full = jnp.concatenate(
        [v_next, jnp.zeros((v_next.shape[0], 1))], -1)
    p_next = jax.nn.softmax(v_next_full)[:, :K]      # drop null: Q̄_null = 0
    slate_value = jnp.sum(p_next * q_next, -1)
    not_term = 1.0 - batch["terminateds"].astype(jnp.float32)
    target = batch["rewards"] + config["gamma"] * not_term * slate_value
    clicked_mask = (batch["pick"] < K).astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(clicked_mask), 1.0)
    td = (q_clicked - jax.lax.stop_gradient(target)) * clicked_mask
    q_loss = jnp.sum(jnp.square(td)) / denom
    loss = choice_nll + q_loss
    return loss, {"choice_nll": choice_nll, "q_loss": q_loss,
                  "q_mean": jnp.sum(q_clicked * clicked_mask) / denom}


class SlateQConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.n_items = 30
        self.slate_size = 3
        self.item_dim = 6
        self.episodes_per_iteration = 16
        self.buffer_capacity = 20_000
        self.learning_starts = 256
        self.updates_per_iteration = 32
        self.target_update_freq = 100
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 4_000
        self.lr = 1e-3
        self.hidden = 64
        self.env_seed = 0
        self.algo_class = SlateQ


class SlateQ(Algorithm):
    """Driver-side slate rollouts (combinatorial actions don't fit the
    int-action EnvRunner protocol) + jitted joint choice/Q̄ updates."""

    def _setup(self) -> None:
        cfg = self.config
        env_spec = cfg.env_spec
        if env_spec is None:
            env_spec = lambda: RecSysEnv(  # noqa: E731
                n_items=cfg.n_items, d=cfg.item_dim,
                slate_size=cfg.slate_size, seed=cfg.env_seed)
        self.env = env_spec() if callable(env_spec) else env_spec
        hid = (cfg.hidden[0] if isinstance(cfg.hidden, (tuple, list))
               else cfg.hidden)
        self.module = SlateQModule(self.env.obs_dim,
                                   self.env.item_features.shape[1], hid)
        self.learner = Learner(
            self.module, slateq_loss, config={"gamma": cfg.gamma},
            learning_rate=cfg.lr, max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh, seed=cfg.seed)
        self._target_params = self.learner.get_weights_np()
        self._rng = np.random.default_rng(cfg.seed)
        self._buf: list[tuple] = []
        self._buf_head = 0
        self._grad_steps = 0
        self._env_steps = 0

    def _build_learner(self) -> None:  # pragma: no cover — done in _setup
        pass

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def build_slate(self, params, obs: np.ndarray) -> np.ndarray:
        """Greedy top-k by choice-weighted item value (the paper's top-k
        slate optimizer): rank items by sigmoid-ish weight exp(v) * Q̄."""
        v, q = self.module.scores_np(params, obs, self.env.item_features)
        score = np.exp(v - v.max()) * q
        return np.argsort(-score)[: self.env.slate_size].astype(np.int64)

    def _store(self, row: tuple) -> None:
        if len(self._buf) < self.config.buffer_capacity:
            self._buf.append(row)
        else:
            self._buf[self._buf_head] = row
            self._buf_head = (self._buf_head + 1) % self.config.buffer_capacity

    def _play_episode(self, params, greedy: bool = False) -> float:
        env, cfg = self.env, self.config
        obs = env.reset()
        total, done = 0.0, False
        prev = None  # (obs, slate, pick, reward, terminated)
        while not done:
            if not greedy and self._rng.random() < self._epsilon():
                slate = self._rng.choice(env.n_items, env.slate_size,
                                         replace=False).astype(np.int64)
            else:
                slate = self.build_slate(params, obs)
            nxt, reward, term, trunc, clicked = env.step(slate)
            self._env_steps += 0 if greedy else 1
            total += reward
            pick = (int(np.where(slate == clicked)[0][0])
                    if clicked >= 0 else env.slate_size)
            if not greedy:
                if prev is not None:
                    # SARSA: the previous transition's target needs THIS
                    # step's slate as the next action
                    self._store((*prev, obs, slate))
                prev = (obs, slate, pick, reward, term)
            obs = nxt
            done = term or trunc
        if not greedy and prev is not None:
            # terminal/truncated tail: next slate unused when terminal;
            # for truncation the bootstrap uses the LAST built slate
            self._store((*prev, obs, self.build_slate(params, obs)))
        return total

    def training_step(self) -> dict:
        cfg = self.config
        params = self.learner.get_weights_np()
        returns = [self._play_episode(params)
                   for _ in range(cfg.episodes_per_iteration)]
        metrics_acc: dict[str, list[float]] = {}
        feats = self.env.item_features
        if len(self._buf) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                idx = self._rng.integers(0, len(self._buf),
                                         cfg.minibatch_size)
                rows = [self._buf[i] for i in idx]
                batch = {
                    "obs": np.stack([r[0] for r in rows]),
                    "slate_feats": np.stack([feats[r[1]] for r in rows]),
                    "pick": np.asarray([r[2] for r in rows], np.int32),
                    "rewards": np.asarray([r[3] for r in rows], np.float32),
                    "terminateds": np.asarray([r[4] for r in rows], bool),
                    "next_obs": np.stack([r[5] for r in rows]),
                    "next_feats": np.stack([feats[r[6]] for r in rows]),
                    "target_params": self._target_params,
                }
                m = self.learner.update(batch)
                self._grad_steps += 1
                if self._grad_steps % cfg.target_update_freq == 0:
                    self._target_params = self.learner.get_weights_np()
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
        out = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        out["episode_return_mean"] = float(np.mean(returns))
        out["epsilon"] = self._epsilon()
        return out

    def evaluate(self, episodes: int = 10) -> float:
        params = self.learner.get_weights_np()
        return float(np.mean(
            [self._play_episode(params, greedy=True)
             for _ in range(episodes)]))

    def train(self) -> dict:
        metrics = self.training_step()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        return metrics

    def stop(self) -> None:
        pass
