"""ARS — Augmented Random Search (Mania et al. 2018).

Equivalent of the reference's ARS (reference: rllib_contrib/ars/src/..../
ars.py — the V2 variant: antithetic perturbation rollouts like ES, plus
the three augmentations that define ARS: (1) only the top-k directions by
max(r+, r-) contribute to the update, (2) the step is normalized by the
standard deviation of the selected returns, (3) observations are
normalized by a running mean/std filter synchronized across workers each
iteration). Shares the ES worker geometry: only integer noise seeds and
the filter's summary statistics cross the wire.
"""
from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.es import _flatten, _unflatten
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.rl_module import ActorCriticModule


class _RunningStat:
    """Welford-mergeable mean/var (the reference's MeanStdFilter core)."""

    def __init__(self, dim: int):
        self.count = 0.0
        self.mean = np.zeros(dim, np.float64)
        self.m2 = np.zeros(dim, np.float64)

    def push_batch(self, xs: np.ndarray) -> None:
        for x in np.asarray(xs, np.float64):
            self.count += 1.0
            delta = x - self.mean
            self.mean += delta / self.count
            self.m2 += delta * (x - self.mean)

    def merge(self, count, mean, m2) -> None:
        if count <= 0:
            return
        total = self.count + count
        delta = mean - self.mean
        self.mean = (self.count * self.mean + count * mean) / total
        self.m2 = self.m2 + m2 + delta * delta * self.count * count / total
        self.count = total

    @property
    def std(self) -> np.ndarray:
        if self.count < 2:
            return np.ones_like(self.mean)
        return np.sqrt(np.maximum(self.m2 / (self.count - 1), 1e-8))


class ARSWorker:
    """Antithetic-rollout actor with a local observation filter; returns
    per-seed (r+, r-) pairs plus the filter's batch statistics so the
    driver can merge and re-broadcast a consistent normalization."""

    def __init__(self, env_spec, hidden, sigma: float, seed: int,
                 episode_limit: int = 500):
        self.env = make_env(env_spec)
        obs0 = self.env.reset(seed=seed)
        self.obs_dim = int(np.asarray(obs0).shape[0])
        self.num_actions = int(getattr(self.env, "num_actions", 2))
        self.module = ActorCriticModule(self.obs_dim, self.num_actions,
                                        tuple(hidden))
        self.sigma = sigma
        self.episode_limit = episode_limit

    def _episode_return(self, theta, spec, seed, mean, std, stat):
        params = _unflatten(theta, spec)
        obs = self.env.reset(seed=seed)
        total = 0.0
        for _ in range(self.episode_limit):
            o = np.asarray(obs, np.float32)
            stat.append(o)
            norm = (o - mean) / std
            logits = ActorCriticModule._mlp_np(params["policy"], norm[None])
            action = int(np.argmax(logits[0]))
            obs, r, term, trunc = self.env.step(action)
            total += float(r)
            if term or trunc:
                break
        return total

    def evaluate(self, theta: np.ndarray, spec, seeds: list, eval_seed: int,
                 mean: np.ndarray, std: np.ndarray):
        pairs, seen = [], []
        for s in seeds:
            noise = np.random.default_rng(s).standard_normal(
                theta.shape[0]).astype(np.float32)
            pairs.append((
                self._episode_return(theta + self.sigma * noise, spec,
                                     eval_seed, mean, std, seen),
                self._episode_return(theta - self.sigma * noise, spec,
                                     eval_seed, mean, std, seen),
            ))
        stat = _RunningStat(self.obs_dim)
        if seen:
            stat.push_batch(np.asarray(seen, np.float64))
        return pairs, (stat.count, stat.mean, stat.m2)


class ARSConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_workers = 2
        self.num_directions = 16      # perturbation pairs per iteration
        self.num_top_directions = 8   # k directions kept for the update
        self.sigma = 0.1
        self.ars_lr = 0.05
        self.episode_limit = 500
        self.algo_class = ARS


class ARS(Algorithm):
    """Driver holds theta + the merged observation filter."""

    def _setup(self) -> None:
        cfg = self.config
        env = make_env(cfg.env_spec)
        obs0 = env.reset(seed=cfg.seed or 0)
        obs_dim = int(np.asarray(obs0).shape[0])
        num_actions = int(getattr(env, "num_actions", 2))
        env.close()
        self.module = ActorCriticModule(obs_dim, num_actions,
                                        tuple(cfg.hidden))
        p = self.module.init(cfg.seed or 0)
        self.theta, self._spec = _flatten({"policy": p["pi"]})
        self._filter = _RunningStat(obs_dim)
        Worker = ray_tpu.remote(num_cpus=1)(ARSWorker)
        self._workers = [
            Worker.remote(cfg.env_spec, tuple(cfg.hidden), cfg.sigma,
                          (cfg.seed or 0) + i, cfg.episode_limit)
            for i in range(cfg.num_workers)
        ]
        self._rng = np.random.default_rng(cfg.seed or 0)
        self._iter = 0

    def _build_learner(self) -> None:  # pragma: no cover — gradient-free
        pass

    def training_step(self) -> dict:
        cfg = self.config
        self._iter += 1
        seeds = self._rng.integers(0, 2**31, cfg.num_directions)
        chunks = np.array_split(seeds, len(self._workers))
        eval_seed = int(self._rng.integers(0, 2**31))
        mean = self._filter.mean.astype(np.float32)
        std = self._filter.std.astype(np.float32)
        refs = [
            w.evaluate.remote(self.theta, self._spec, [int(s) for s in c],
                              eval_seed, mean, std)
            for w, c in zip(self._workers, chunks) if len(c)
        ]
        pairs, used_seeds = [], []
        for r, c in zip(refs, [c for c in chunks if len(c)]):
            p, (cnt, m, m2) = ray_tpu.get(r, timeout=300)
            pairs.extend(p)
            used_seeds.extend(int(s) for s in c[: len(p)])
            self._filter.merge(cnt, m, m2)
        rets = np.asarray(pairs, np.float32)          # [n, 2] (+, -)
        # augmentation 1: keep only the top-k directions by max(r+, r-)
        k = min(cfg.num_top_directions, len(pairs))
        order = np.argsort(-rets.max(axis=1))[:k]
        # augmentation 2: normalize the step by the selected returns' std
        sigma_r = float(np.std(rets[order])) or 1.0
        grad = np.zeros_like(self.theta)
        for i in order:
            noise = np.random.default_rng(used_seeds[i]).standard_normal(
                self.theta.shape[0]).astype(np.float32)
            grad += (rets[i, 0] - rets[i, 1]) * noise
        self.theta = self.theta + cfg.ars_lr / (k * sigma_r) * grad
        return {
            "episode_return_mean": float(rets.mean()),
            "episode_return_max": float(rets.max()),
            "filter_count": float(self._filter.count),
            "training_iteration": self._iter,
        }

    def compute_action(self, obs: np.ndarray) -> int:
        params = _unflatten(self.theta, self._spec)
        norm = ((np.asarray(obs, np.float32) - self._filter.mean)
                / self._filter.std).astype(np.float32)
        logits = ActorCriticModule._mlp_np(params["policy"], norm[None])
        return int(np.argmax(logits[0]))

    def stop(self) -> None:
        for w in getattr(self, "_workers", ()):
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        super().stop()

    def train(self) -> dict:
        # ES-family: owns its return metrics (no EnvRunner tracker)
        metrics = self.training_step()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        return metrics
