"""ARS — Augmented Random Search (Mania et al. 2018).

Equivalent of the reference's ARS (reference: rllib_contrib/ars — the V2
variant). Extends ES (same antithetic-perturbation worker geometry, only
integer noise seeds cross the wire) with the three augmentations that
define ARS: (1) only the top-k directions by max(r+, r-) contribute to
the update, (2) the step is normalized by the standard deviation of the
selected returns, (3) observations are normalized by a running mean/std
filter whose per-worker statistics are Welford-merged on the driver and
re-broadcast each iteration.
"""
from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import AlgorithmConfig
from ray_tpu.rllib.algorithms.es import ES, ESWorker, _unflatten
from ray_tpu.rllib.rl_module import ActorCriticModule


class _RunningStat:
    """Welford-mergeable mean/var (the reference's MeanStdFilter core)."""

    def __init__(self, dim: int):
        self.count = 0.0
        self.mean = np.zeros(dim, np.float64)
        self.m2 = np.zeros(dim, np.float64)

    def push_batch(self, xs: np.ndarray) -> None:
        xs = np.asarray(xs, np.float64)
        if len(xs) == 0:
            return
        mean = xs.mean(axis=0)
        self.merge(float(len(xs)), mean, ((xs - mean) ** 2).sum(axis=0))

    def merge(self, count, mean, m2) -> None:
        if count <= 0:
            return
        total = self.count + count
        delta = mean - self.mean
        self.mean = (self.count * self.mean + count * mean) / total
        self.m2 = self.m2 + m2 + delta * delta * self.count * count / total
        self.count = total

    @property
    def std(self) -> np.ndarray:
        if self.count < 2:
            return np.ones_like(self.mean)
        return np.sqrt(np.maximum(self.m2 / (self.count - 1), 1e-8))


class ARSWorker(ESWorker):
    """ESWorker + observation normalization: rollouts normalize with the
    driver-broadcast filter and return their own batch statistics."""

    def _episode_return(self, theta, spec, seed, mean=None, std=None,
                        seen=None):
        if mean is None:
            return super()._episode_return(theta, spec, seed)
        params = _unflatten(theta, spec)
        obs = self.env.reset(seed=seed)
        total = 0.0
        for _ in range(self.episode_limit):
            o = np.asarray(obs, np.float32)
            seen.append(o)
            norm = (o - mean) / std
            logits = ActorCriticModule._mlp_np(params["policy"], norm[None])
            obs, r, term, trunc = self.env.step(int(np.argmax(logits[0])))
            total += float(r)
            if term or trunc:
                break
        return total

    def evaluate(self, theta, spec, seeds, eval_seed, mean=None, std=None):
        if mean is None:  # ES-compatible call shape
            return super().evaluate(theta, spec, seeds, eval_seed)
        pairs, seen = [], []
        for s in seeds:
            noise = np.random.default_rng(s).standard_normal(
                theta.shape[0]).astype(np.float32)
            pairs.append((
                self._episode_return(theta + self.sigma * noise, spec,
                                     eval_seed, mean, std, seen),
                self._episode_return(theta - self.sigma * noise, spec,
                                     eval_seed, mean, std, seen),
            ))
        stat = _RunningStat(self.obs_dim)
        stat.push_batch(np.asarray(seen, np.float64) if seen
                        else np.zeros((0, self.obs_dim)))
        return pairs, (stat.count, stat.mean, stat.m2)


class ARSConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_workers = 2
        self.num_directions = 16      # perturbation pairs per iteration
        self.num_top_directions = 8   # k directions kept for the update
        self.sigma = 0.1
        self.ars_lr = 0.05
        self.episode_limit = 500
        self.algo_class = ARS


class ARS(ES):
    """ES driver with the augmented update + merged observation filter.
    _setup/stop/train are inherited; only the worker class, the filter,
    and the update rule differ."""

    _worker_cls = ARSWorker

    def _setup(self) -> None:
        super()._setup()
        self._filter = _RunningStat(self.obs_dim)

    def training_step(self) -> dict:
        cfg = self.config
        self._iter += 1
        seeds = self._rng.integers(0, 2**31, cfg.num_directions)
        chunks = [c for c in np.array_split(seeds, len(self._workers))
                  if len(c)]
        eval_seed = int(self._rng.integers(0, 2**31))
        mean = self._filter.mean.astype(np.float32)
        std = self._filter.std.astype(np.float32)
        refs = [
            w.evaluate.remote(self.theta, self._spec, [int(s) for s in c],
                              eval_seed, mean, std)
            for w, c in zip(self._workers, chunks)
        ]
        pairs, used_seeds = [], []
        for r, c in zip(refs, chunks):
            p, (cnt, m, m2) = ray_tpu.get(r, timeout=300)
            pairs.extend(p)
            used_seeds.extend(int(s) for s in c[: len(p)])
            self._filter.merge(cnt, m, m2)
        rets = np.asarray(pairs, np.float32)          # [n, 2] (+, -)
        # augmentation 1: keep only the top-k directions by max(r+, r-)
        k = min(cfg.num_top_directions, len(pairs))
        order = np.argsort(-rets.max(axis=1))[:k]
        # augmentation 2: normalize the step by the selected returns' std
        sigma_r = float(np.std(rets[order])) or 1.0
        grad = np.zeros_like(self.theta)
        for i in order:
            noise = np.random.default_rng(used_seeds[i]).standard_normal(
                self.theta.shape[0]).astype(np.float32)
            grad += (rets[i, 0] - rets[i, 1]) * noise
        self.theta = self.theta + cfg.ars_lr / (k * sigma_r) * grad
        return {
            "episode_return_mean": float(rets.mean()),
            "episode_return_max": float(rets.max()),
            "filter_count": float(self._filter.count),
            "training_iteration": self._iter,
        }

    def compute_action(self, obs: np.ndarray) -> int:
        params = _unflatten(self.theta, self._spec)
        norm = ((np.asarray(obs, np.float32) - self._filter.mean)
                / self._filter.std).astype(np.float32)
        logits = ActorCriticModule._mlp_np(params["policy"], norm[None])
        return int(np.argmax(logits[0]))
