"""Replay buffers for off-policy algorithms.

Equivalent of the reference's replay buffers
(reference: rllib/utils/replay_buffers/replay_buffer.py uniform storage;
prioritized_replay_buffer.py proportional PER per Schaul et al. 2016).
Stores flat transition arrays; samples fixed-size minibatches (static
shapes for the jitted learner). Discrete actions are int32 scalars;
continuous actions are float32 [action_dim] vectors (action_dim=None
selects discrete storage).

The prioritized variant uses numpy cumulative sums over the priority
array instead of the reference's segment tree — O(n) per sampled batch,
which at the 1e5-transition scale these buffers run at is a few hundred
microseconds and keeps the implementation 40 lines instead of 200.
"""
from __future__ import annotations

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, obs_dim: int, seed: int = 0,
                 action_dim: int | None = None):
        self.capacity = capacity
        self._rng = np.random.default_rng(seed)
        self._obs = np.empty((capacity, obs_dim), np.float32)
        if action_dim is None:
            self._actions = np.empty(capacity, np.int32)
        else:
            self._actions = np.empty((capacity, action_dim), np.float32)
        self._rewards = np.empty(capacity, np.float32)
        self._next_obs = np.empty((capacity, obs_dim), np.float32)
        self._terminated = np.empty(capacity, np.bool_)
        # bootstrap discount per transition: gamma**k where k is the
        # ACTUAL lookahead (n-step windows truncate at episode/rollout
        # boundaries, so k varies per sample)
        self._discounts = np.empty(capacity, np.float32)
        self._size = 0
        self._head = 0

    def __len__(self) -> int:
        return self._size

    def add_batch(self, obs, actions, rewards, next_obs, terminated,
                  discounts=None):
        """Returns the storage indices written (PER subclass re-uses them
        to seed priorities)."""
        n = len(actions)
        idx = (self._head + np.arange(n)) % self.capacity
        self._obs[idx] = obs
        self._actions[idx] = actions
        self._rewards[idx] = rewards
        self._next_obs[idx] = next_obs
        self._terminated[idx] = terminated
        self._discounts[idx] = 1.0 if discounts is None else discounts
        self._head = int((self._head + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        return idx

    def _rows(self, idx: np.ndarray) -> dict:
        return {
            "obs": self._obs[idx],
            "actions": self._actions[idx],
            "rewards": self._rewards[idx],
            "next_obs": self._next_obs[idx],
            "terminateds": self._terminated[idx],
            "discounts": self._discounts[idx],
        }

    def sample(self, batch_size: int) -> dict:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return self._rows(idx)


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized experience replay (reference:
    prioritized_replay_buffer.py): P(i) ∝ p_i^alpha, importance-sampling
    weights w_i = (N * P(i))^-beta normalized by max, priorities updated
    to |td| after each learn step."""

    def __init__(self, capacity: int, obs_dim: int, seed: int = 0,
                 action_dim: int | None = None, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6):
        super().__init__(capacity, obs_dim, seed=seed, action_dim=action_dim)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._priorities = np.zeros(capacity, np.float64)
        self._max_priority = 1.0

    def add_batch(self, obs, actions, rewards, next_obs, terminated,
                  discounts=None):
        idx = super().add_batch(obs, actions, rewards, next_obs, terminated,
                                discounts)
        # new transitions enter at max priority so they are seen at least
        # once before their TD error is known
        self._priorities[idx] = self._max_priority
        return idx

    def sample(self, batch_size: int) -> dict:
        p = self._priorities[: self._size] ** self.alpha
        p = p / p.sum()
        idx = self._rng.choice(self._size, size=batch_size, p=p)
        batch = self._rows(idx)
        weights = (self._size * p[idx]) ** (-self.beta)
        batch["weights"] = (weights / weights.max()).astype(np.float32)
        batch["indices"] = idx
        return batch

    def update_priorities(self, indices: np.ndarray, td_abs: np.ndarray):
        pr = np.abs(td_abs) + self.eps
        self._priorities[indices] = pr
        self._max_priority = max(self._max_priority, float(pr.max()))


class SequenceReplayBuffer:
    """Fixed-length sequence storage for recurrent replay (R2D2;
    reference: rllib/utils/replay_buffers — R2D2 stores `replay_sequence
    _length` windows with `replay_zero_init_states=False`, i.e. the
    runner's stored hidden state rides with each sequence, Kapturowski
    et al. 2019 'stored state'). Each row is one env's full rollout window:
    obs [T, D], actions/rewards/dones/terminateds [T], resets [T] (step
    starts a new episode), state_in [H] (hidden state at the window start).
    """

    def __init__(self, capacity: int, seq_len: int, obs_dim: int,
                 state_dim: int, seed: int = 0):
        self.capacity = capacity
        self.seq_len = seq_len
        self._rng = np.random.default_rng(seed)
        self._obs = np.empty((capacity, seq_len, obs_dim), np.float32)
        self._actions = np.empty((capacity, seq_len), np.int32)
        self._rewards = np.empty((capacity, seq_len), np.float32)
        self._dones = np.empty((capacity, seq_len), np.bool_)
        self._terminated = np.empty((capacity, seq_len), np.bool_)
        self._resets = np.empty((capacity, seq_len), np.bool_)
        self._state_in = np.empty((capacity, state_dim), np.float32)
        self._size = 0
        self._head = 0

    def __len__(self) -> int:
        return self._size

    def add_rollout(self, batch: dict) -> None:
        """Store a [T, E] EnvRunner batch as E sequences."""
        T, E = batch["rewards"].shape
        if T != self.seq_len:
            raise ValueError(f"rollout length {T} != buffer seq_len {self.seq_len}")
        for e in range(E):
            i = self._head
            self._obs[i] = batch["obs"][:, e]
            self._actions[i] = batch["actions"][:, e]
            self._rewards[i] = batch["rewards"][:, e]
            self._dones[i] = batch["dones"][:, e]
            self._terminated[i] = batch["terminateds"][:, e]
            self._resets[i] = batch["resets"][:, e]
            self._state_in[i] = batch["state_in"][e]
            self._head = (self._head + 1) % self.capacity
            self._size = min(self._size + 1, self.capacity)

    def sample(self, batch_size: int) -> dict:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {
            "obs": self._obs[idx],
            "actions": self._actions[idx],
            "rewards": self._rewards[idx],
            "dones": self._dones[idx],
            "terminateds": self._terminated[idx],
            "resets": self._resets[idx],
            "state_in": self._state_in[idx],
        }
