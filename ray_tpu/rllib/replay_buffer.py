"""Uniform ring replay buffer for off-policy algorithms.

Equivalent of the reference's replay buffers
(reference: rllib/utils/replay_buffers/replay_buffer.py uniform storage;
prioritized variant not yet ported). Stores flat transition arrays; samples
fixed-size minibatches (static shapes for the jitted learner).
"""
from __future__ import annotations

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, obs_dim: int, seed: int = 0):
        self.capacity = capacity
        self._rng = np.random.default_rng(seed)
        self._obs = np.empty((capacity, obs_dim), np.float32)
        self._actions = np.empty(capacity, np.int32)
        self._rewards = np.empty(capacity, np.float32)
        self._next_obs = np.empty((capacity, obs_dim), np.float32)
        self._terminated = np.empty(capacity, np.bool_)
        self._size = 0
        self._head = 0

    def __len__(self) -> int:
        return self._size

    def add_batch(self, obs, actions, rewards, next_obs, terminated) -> None:
        n = len(actions)
        idx = (self._head + np.arange(n)) % self.capacity
        self._obs[idx] = obs
        self._actions[idx] = actions
        self._rewards[idx] = rewards
        self._next_obs[idx] = next_obs
        self._terminated[idx] = terminated
        self._head = int((self._head + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))

    def sample(self, batch_size: int) -> dict:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {
            "obs": self._obs[idx],
            "actions": self._actions[idx],
            "rewards": self._rewards[idx],
            "next_obs": self._next_obs[idx],
            "terminateds": self._terminated[idx],
        }
