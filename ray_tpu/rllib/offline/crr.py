"""CRR — Critic Regularized Regression from offline experience files.

Equivalent of the reference's CRR (reference: rllib/algorithms/crr/crr.py —
Wang et al. 2020). Discrete-action variant: a single-Q critic trains by
expected-SARSA TD against a target-network copy (the reference uses twin
critics; with the full discrete action set enumerable, the expectation
backup already tempers the max-operator overestimation twin critics exist
to fight); the policy trains by advantage-weighted behavior cloning where
the weight is

    f(A) = 1[A > 0]            (mode="binary", the paper's robust default)
    f(A) = clip(exp(A / beta)) (mode="exp")

with A(s, a) = Q(s, a) - E_{a'~pi} Q(s, a') estimated from the critic and
the CURRENT policy's distribution. Unlike BC the policy only imitates
dataset actions the critic judges better than the policy's average — the
filtering is what lets CRR improve on mixed-quality data where BC merely
averages it. Reads the same JsonReader/DatasetReader experience format as
MARWIL/BC/CQL.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.offline.io import DatasetReader, JsonReader
from ray_tpu.rllib.rl_module import ActorCriticModule, QModule


def crr_critic_loss(module, params, batch, config):
    """TD against the target net, successor action from the CURRENT
    policy's distribution (expected SARSA backup — matches the actor being
    regularized toward the data). The policy's params ride in the batch
    (replicated pytree, the DQN target_params pattern) so the whole step
    stays inside this jit — no host-side forward per minibatch."""
    import jax
    import jax.numpy as jnp

    q = module.forward(params, batch["obs"])
    q_data = jnp.take_along_axis(q, batch["actions"][:, None], axis=-1)[:, 0]
    q_next = module.forward(batch["target_params"], batch["next_obs"])
    next_logits, _ = config["policy_module"].forward(
        batch["policy_params"], batch["next_obs"])
    pi_next = jax.nn.softmax(jax.lax.stop_gradient(next_logits))
    v_next = jnp.sum(pi_next * q_next, axis=-1)
    not_term = 1.0 - batch["terminateds"].astype(q.dtype)
    target = batch["rewards"] + config["gamma"] * not_term * v_next
    td_loss = jnp.mean(jnp.square(q_data - jax.lax.stop_gradient(target)))
    return td_loss, {"td_loss": td_loss, "q_data_mean": jnp.mean(q_data)}


def crr_actor_loss(module, params, batch, config):
    """-logp(a|s) * f(A), advantages from the frozen critic whose params
    ride in the batch (on-device, see crr_critic_loss)."""
    import jax
    import jax.numpy as jnp

    logits, _ = module.forward(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=-1)[:, 0]
    q = jax.lax.stop_gradient(
        config["critic_module"].forward(batch["critic_params"], batch["obs"]))
    pi = jax.nn.softmax(jax.lax.stop_gradient(logits))
    v = jnp.sum(pi * q, axis=-1)
    adv = jnp.take_along_axis(q, batch["actions"][:, None], axis=-1)[:, 0] - v
    if config["mode"] == "binary":
        weight = (adv > 0).astype(logp.dtype)
    else:
        weight = jnp.clip(jnp.exp(adv / config["beta"]), 0.0,
                          config["weight_clip"])
    actor_loss = -jnp.mean(jax.lax.stop_gradient(weight) * logp)
    return actor_loss, {
        "actor_loss": actor_loss,
        "mean_weight": jnp.mean(weight),
        "adv_mean": jnp.mean(adv),
    }


class CRRConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.mode = "binary"          # binary | exp
        self.beta = 1.0               # exp-mode temperature
        self.weight_clip = 20.0
        self.input_ = None
        self.observation_dim = None
        self.num_actions = None
        self.target_update_freq = 50  # critic gradient steps
        self.algo_class = CRR

    def offline_data(self, input_=None, mode=None, beta=None) -> "CRRConfig":
        if input_ is not None:
            self.input_ = input_
        if mode is not None:
            self.mode = mode
        if beta is not None:
            self.beta = beta
        return self

    def environment(self, env=None, *, observation_dim=None,
                    num_actions=None) -> "CRRConfig":
        if env is not None:
            self.env_spec = env
        if observation_dim is not None:
            self.observation_dim = observation_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self


class CRR(Algorithm):
    """Offline-only: transitions from experience files; each training_step
    interleaves critic TD epochs with advantage-filtered policy epochs."""

    def _setup(self) -> None:
        cfg = self.config
        reader = cfg.input_
        if isinstance(reader, str):
            reader = JsonReader(reader)
        elif reader is not None and not hasattr(reader, "episodes"):
            reader = DatasetReader(reader)
        if reader is None:
            raise ValueError("CRR requires config.offline_data(input_=...)")
        obs, actions, rewards, next_obs, term = [], [], [], [], []
        for ep in reader.episodes():
            for i, row in enumerate(ep):
                terminated = bool(row.get("terminated", row["done"]))
                if i + 1 == len(ep) and not terminated:
                    continue  # truncated tail: no successor, don't bootstrap
                obs.append(row["obs"])
                actions.append(row["action"])
                rewards.append(row["reward"])
                next_obs.append(ep[i + 1]["obs"] if i + 1 < len(ep)
                                else row["obs"])
                term.append(terminated)
        if not actions:
            raise ValueError("offline input is empty")
        self._obs = np.asarray(obs, np.float32)
        self._actions = np.asarray(actions)
        if self._actions.ndim != 1 or not np.all(
                self._actions == np.round(self._actions)):
            raise ValueError(
                "discrete CRR requires scalar integer actions; got shape "
                f"{self._actions.shape}")
        self._actions = self._actions.astype(np.int32)
        self._rewards = np.asarray(rewards, np.float32)
        self._next_obs = np.asarray(next_obs, np.float32)
        self._terminateds = np.asarray(term, np.bool_)
        self.obs_dim = cfg.observation_dim or int(self._obs.shape[1])
        self.num_actions = cfg.num_actions or int(self._actions.max()) + 1
        self._rng = np.random.default_rng(cfg.seed)
        self._build_learner()

    def _build_learner(self) -> None:
        cfg = self.config
        critic_module = QModule(self.obs_dim, self.num_actions, cfg.hidden)
        policy_module = ActorCriticModule(self.obs_dim, self.num_actions,
                                          cfg.hidden)
        self.critic = Learner(
            critic_module,
            crr_critic_loss,
            config={"gamma": cfg.gamma, "policy_module": policy_module},
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self.learner = Learner(  # the policy (named learner for checkpoints)
            policy_module,
            crr_actor_loss,
            config={"mode": cfg.mode, "beta": cfg.beta,
                    "weight_clip": cfg.weight_clip,
                    "critic_module": critic_module},
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self._target_params = self.critic.get_weights_np()
        self._grad_steps = 0

    def training_step(self) -> dict:
        cfg = self.config
        n = len(self._actions)
        mb = min(cfg.minibatch_size, n)
        metrics_acc: dict[str, list[float]] = {}
        for _ in range(cfg.num_epochs):
            perm = self._rng.permutation(n)
            for start in range(0, n - mb + 1, mb):
                idx = perm[start:start + mb]
                # the other learner's live device params ride in the batch
                # (replicated pytree) — no device→host copies on this loop
                m = self.critic.update({
                    "obs": self._obs[idx],
                    "actions": self._actions[idx],
                    "rewards": self._rewards[idx],
                    "next_obs": self._next_obs[idx],
                    "terminateds": self._terminateds[idx],
                    "policy_params": self.learner.params,
                    "target_params": self._target_params,
                })
                self._grad_steps += 1
                if self._grad_steps % cfg.target_update_freq == 0:
                    self._target_params = self.critic.get_weights_np()
                ma = self.learner.update({
                    "obs": self._obs[idx],
                    "actions": self._actions[idx],
                    "critic_params": self.critic.params,
                })
                for k, v in {**m, **ma}.items():
                    metrics_acc.setdefault(k, []).append(v)
        return {k: float(np.mean(v)) for k, v in metrics_acc.items()}

    # -- checkpointing: the first two-Learner algorithm — the base class
    # persists self.learner (the policy); the critic must ride along or a
    # restore would filter the actor loss with a random-critic advantage
    def save_state(self) -> dict:
        state = super().save_state()
        state["critic"] = self.critic.state()
        state["grad_steps"] = self._grad_steps
        return state

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        if "critic" in state:
            self.critic.load_state(state["critic"])
            # the target network is derived state — rebuild it from the
            # restored critic, or TD targets bootstrap from a fresh-init
            # network until the next target_update_freq boundary
            self._target_params = self.critic.get_weights_np()
        if "grad_steps" in state:
            self._grad_steps = state["grad_steps"]

    def _sample_all(self):  # pragma: no cover — offline only
        raise RuntimeError("offline algorithm does not sample")

    def compute_action(self, obs: np.ndarray) -> int:
        w = self.learner.get_weights_np()
        logits, _ = self.learner.module.forward_np(
            w, np.asarray(obs, np.float32)[None])
        return int(np.argmax(logits[0]))
