"""MARWIL / BC — offline policy learning from experience files.

Equivalent of the reference's MARWIL and BC (reference:
rllib/algorithms/marwil/marwil.py — advantage-weighted behavior cloning,
Wang et al. 2018; rllib/algorithms/bc/bc.py is MARWIL with beta=0, the same
subclass relationship used here). No environment is stepped: batches come
from a JsonReader / DatasetReader; the loss is a jitted advantage-weighted
cross-entropy plus a Monte-Carlo value regression.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.offline.io import (
    DatasetReader,
    JsonReader,
    compute_returns,
)
from ray_tpu.rllib.rl_module import ActorCriticModule


def marwil_loss(module, params, batch, config):
    """-logp(a|s) * exp(beta * A_norm) + c_vf * (V - R)^2 (pure jax).

    beta=0 reduces exactly to behavior cloning (the exp weight is 1 and the
    value head trains but does not influence the policy term)."""
    import jax
    import jax.numpy as jnp

    logits, values = module.forward(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=-1)[:, 0]
    adv = batch["returns"] - jax.lax.stop_gradient(values)
    adv_norm = adv / (jnp.std(adv) + 1e-8)
    weight = jnp.exp(jnp.clip(config["beta"] * adv_norm, -10.0, 10.0))
    policy_loss = -jnp.mean(jax.lax.stop_gradient(weight) * logp)
    value_loss = jnp.mean(jnp.square(values - batch["returns"]))
    total = policy_loss + config["vf_coeff"] * value_loss
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": value_loss,
        "mean_weight": jnp.mean(weight),
    }


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0
        self.vf_coeff = 1.0
        self.input_ = None  # path / JsonReader / DatasetReader / Dataset
        self.observation_dim = None  # inferred from data when None
        self.num_actions = None
        self.algo_class = MARWIL

    def offline_data(self, input_=None, beta=None) -> "MARWILConfig":
        if input_ is not None:
            self.input_ = input_
        if beta is not None:
            self.beta = beta
        return self

    def environment(self, env=None, *, observation_dim=None,
                    num_actions=None) -> "MARWILConfig":
        if env is not None:
            self.env_spec = env
        if observation_dim is not None:
            self.observation_dim = observation_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self


class MARWIL(Algorithm):
    """Offline-only Algorithm: `_setup` loads the data instead of spawning
    EnvRunners; `train()` runs minibatch epochs over it."""

    def _setup(self) -> None:
        cfg = self.config
        reader = cfg.input_
        if isinstance(reader, str):
            reader = JsonReader(reader)
        elif reader is not None and not hasattr(reader, "episodes"):
            reader = DatasetReader(reader)  # a Dataset
        if reader is None:
            raise ValueError("MARWIL/BC requires config.offline_data(input_=...)")
        episodes = reader.episodes()
        self._obs, self._actions, self._returns = compute_returns(
            episodes, cfg.gamma
        )
        if len(self._actions) == 0:
            raise ValueError("offline input is empty")
        if self._actions.ndim != 1:
            raise ValueError(
                "MARWIL/BC requires discrete (scalar) actions; got "
                f"action shape {self._actions.shape} — continuous-action "
                "datasets (SAC/TD3 output) are not supported by this "
                "discrete behavior-cloning family")
        if not np.issubdtype(self._actions.dtype, np.integer):
            # float-typed but integral-valued actions (e.g. hand-written
            # datasets using 1.0) are fine; genuinely fractional are not
            if not np.all(self._actions == np.round(self._actions)):
                raise ValueError(
                    "MARWIL/BC requires discrete actions; offline data "
                    "contains fractional action values")
            self._actions = self._actions.astype(np.int32)
        self.obs_dim = (cfg.observation_dim
                        or int(self._obs.shape[1]))
        self.num_actions = (cfg.num_actions
                            or int(self._actions.max()) + 1)
        self._rng = np.random.default_rng(cfg.seed)
        self._build_learner()

    def _build_learner(self) -> None:
        cfg = self.config
        module = ActorCriticModule(self.obs_dim, self.num_actions, cfg.hidden)
        self.learner = Learner(
            module,
            marwil_loss,
            config={"beta": cfg.beta, "vf_coeff": cfg.vf_coeff},
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )

    def training_step(self) -> dict:
        cfg = self.config
        n = len(self._actions)
        mb = min(cfg.minibatch_size, n)
        metrics_acc: dict[str, list[float]] = {}
        for _ in range(cfg.num_epochs):
            perm = self._rng.permutation(n)
            for start in range(0, n - mb + 1, mb):
                idx = perm[start:start + mb]
                m = self.learner.update({
                    "obs": self._obs[idx],
                    "actions": self._actions[idx],
                    "returns": self._returns[idx],
                })
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
        return {k: float(np.mean(v)) for k, v in metrics_acc.items()}

    # offline algos sample no env steps
    def _sample_all(self):  # pragma: no cover - not used
        raise RuntimeError("offline algorithm does not sample")

    def compute_action(self, obs: np.ndarray) -> int:
        """Greedy action for evaluation."""
        w = self.learner.get_weights_np()
        logits, _ = self.learner.module.forward_np(
            w, np.asarray(obs, np.float32)[None]
        )
        return int(np.argmax(logits[0]))


class BCConfig(MARWILConfig):
    """Behavior cloning = MARWIL with beta=0 (the reference's exact
    relationship, rllib/algorithms/bc/bc.py)."""

    def __init__(self):
        super().__init__()
        self.beta = 0.0
        self.vf_coeff = 0.0  # pure imitation: value head untouched
        self.algo_class = BC


class BC(MARWIL):
    pass
