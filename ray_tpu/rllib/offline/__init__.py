"""ray_tpu.rllib.offline — experience file IO + offline algorithms.

Equivalent of the reference's offline stack (reference: rllib/offline/ —
json_reader/json_writer/dataset_reader; offline algorithms under
rllib/algorithms/marwil, /bc).
"""
from ray_tpu.rllib.offline.io import (
    DatasetReader,
    JsonReader,
    JsonWriter,
    compute_returns,
)
from ray_tpu.rllib.offline.cql import CQL, CQLConfig
from ray_tpu.rllib.offline.crr import CRR, CRRConfig
from ray_tpu.rllib.offline.dt import DT, DTConfig
from ray_tpu.rllib.offline.marwil import BC, BCConfig, MARWIL, MARWILConfig

__all__ = [
    "BC",
    "BCConfig",
    "CQL",
    "CQLConfig",
    "CRR",
    "CRRConfig",
    "DT",
    "DTConfig",
    "DatasetReader",
    "JsonReader",
    "JsonWriter",
    "MARWIL",
    "MARWILConfig",
    "compute_returns",
]
