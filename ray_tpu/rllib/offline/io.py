"""Offline sample IO — JSONL experience files and dataset readers.

Equivalent of the reference's offline IO (reference: rllib/offline/
json_writer.py, json_reader.py, dataset_reader.py — experiences written as
row-chunk files consumable by offline algorithms and replay seeding). Rows
here are per-TRANSITION dicts carrying an `eps_id` so readers can regroup
episodes and compute returns; `done` marks episode ends.
"""
from __future__ import annotations

import json
import os
from typing import Iterator, List, Optional

import numpy as np


def _encode_action(a):
    """Scalar (discrete) actions → int/float; vector (continuous, SAC/TD3)
    actions → list, mirroring the obs handling."""
    arr = np.asarray(a)
    if arr.ndim == 0:
        return float(arr) if np.issubdtype(arr.dtype, np.floating) else int(arr)
    return arr.tolist()


class JsonWriter:
    """Append rollout batches ([T, E, ...] dicts from EnvRunner.sample) or
    single transitions to a JSONL file."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a")
        # per-env episode counters so eps_ids stay unique across batches
        self._eps_base = 0
        self._eps_cur: dict[int, int] = {}

    def write_batch(self, batch: dict) -> int:
        """Flatten one [T, E] rollout batch into transition rows."""
        T, E = batch["rewards"].shape
        n = 0
        for e in range(E):
            if e not in self._eps_cur:
                self._eps_cur[e] = self._alloc_eps()
            for t in range(T):
                row = {
                    "eps_id": self._eps_cur[e],
                    "obs": batch["obs"][t, e].tolist(),
                    "action": _encode_action(batch["actions"][t, e]),
                    "reward": float(batch["rewards"][t, e]),
                    "done": bool(batch["dones"][t, e]),
                    "terminated": bool(batch["terminateds"][t, e]),
                }
                if "logp" in batch:
                    row["logp"] = float(batch["logp"][t, e])
                self._f.write(json.dumps(row) + "\n")
                n += 1
                if row["done"]:
                    self._eps_cur[e] = self._alloc_eps()
        self._f.flush()
        return n

    def write_transition(self, eps_id: int, obs, action, reward: float,
                         done: bool, terminated: Optional[bool] = None,
                         **extra) -> None:
        row = {
            "eps_id": int(eps_id),
            "obs": np.asarray(obs, np.float32).tolist(),
            "action": _encode_action(action),
            "reward": float(reward),
            "done": bool(done),
            "terminated": bool(done if terminated is None else terminated),
        }
        row.update(extra)
        self._f.write(json.dumps(row) + "\n")

    def _alloc_eps(self) -> int:
        self._eps_base += 1
        return self._eps_base - 1

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JsonReader:
    """Read a JSONL experience file (or a directory of them)."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            self.files = sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.endswith((".json", ".jsonl"))
            )
        else:
            self.files = [path]

    def iter_rows(self) -> Iterator[dict]:
        for f in self.files:
            with open(f) as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        yield json.loads(line)

    def episodes(self) -> List[List[dict]]:
        """Group rows into episodes by eps_id (file order preserved
        within an episode)."""
        by_id: dict[int, List[dict]] = {}
        for row in self.iter_rows():
            by_id.setdefault(row["eps_id"], []).append(row)
        return list(by_id.values())


class DatasetReader:
    """Adapter: a ray_tpu.data.Dataset with the same row schema acts as an
    offline input (reference: rllib/offline/dataset_reader.py)."""

    def __init__(self, dataset):
        self._ds = dataset

    def iter_rows(self) -> Iterator[dict]:
        for row in self._ds.iter_rows():
            row = dict(row)
            obs = row["obs"]
            row["obs"] = (obs.tolist() if isinstance(obs, np.ndarray) else
                          list(obs))
            yield row

    def episodes(self) -> List[List[dict]]:
        by_id: dict[int, List[dict]] = {}
        for row in self.iter_rows():
            by_id.setdefault(int(row["eps_id"]), []).append(row)
        return list(by_id.values())


def compute_returns(episodes: List[List[dict]], gamma: float):
    """Per-transition discounted return-to-go. Episodes whose last row isn't
    `done` (truncated files) get dropped-tail treatment: their rows are kept
    but the return bootstraps from 0 — standard MC treatment of incomplete
    trails (reference MARWIL postprocesses with GAE when a value net exists;
    pure MC here keeps the offline path model-free)."""
    obs, actions, returns = [], [], []
    for ep in episodes:
        g = 0.0
        rets = np.empty(len(ep), np.float32)
        for i in range(len(ep) - 1, -1, -1):
            g = ep[i]["reward"] + gamma * g
            rets[i] = g
        for i, row in enumerate(ep):
            obs.append(row["obs"])
            actions.append(row["action"])
            returns.append(rets[i])
    acts = np.asarray(actions)
    # discrete rows deserialize as python ints → int32; continuous rows
    # (vectors or floats) keep float32
    acts = (acts.astype(np.int32) if np.issubdtype(acts.dtype, np.integer)
            else acts.astype(np.float32))
    return (
        np.asarray(obs, np.float32),
        acts,
        np.asarray(returns, np.float32),
    )
