"""Decision Transformer — return-conditioned sequence modeling for
offline RL.

Equivalent of the reference's DT (reference: rllib/algorithms/dt/dt.py —
a causal transformer over (return-to-go, state, action) token triples
predicts the action at each state token; Chen et al. 2021). TPU-first:
the model IS the hot path here, so unlike the MLP algorithms there is no
numpy twin — training and evaluation both run the jitted forward with a
FIXED context length (left-padded + masked), so XLA compiles exactly one
shape for each.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.offline.io import DatasetReader, JsonReader


def _linear(rng, n_in, n_out, scale=0.02):
    return {
        "w": (rng.standard_normal((n_in, n_out)) * scale).astype(np.float32),
        "b": np.zeros(n_out, np.float32),
    }


class DTModule:
    """Causal transformer over interleaved (R̂, s, a) tokens."""

    def __init__(self, obs_dim: int, num_actions: int, context_len: int = 20,
                 d_model: int = 64, n_layer: int = 2, n_head: int = 2,
                 max_timestep: int = 1024):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.K = context_len
        self.d_model = d_model
        self.n_layer = n_layer
        self.n_head = n_head
        self.max_timestep = max_timestep

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        d = self.d_model
        params = {
            "emb_rtg": _linear(rng, 1, d),
            "emb_obs": _linear(rng, self.obs_dim, d),
            "emb_act": _linear(rng, self.num_actions, d),
            # one positional row per TIMESTEP (shared by its 3 tokens) +
            # a learned modality offset per token kind
            "pos": (rng.standard_normal((self.max_timestep, d)) * 0.02
                    ).astype(np.float32),
            "modality": (rng.standard_normal((3, d)) * 0.02
                         ).astype(np.float32),
            "blocks": [],
            "ln_f": {"g": np.ones(d, np.float32),
                     "b": np.zeros(d, np.float32)},
            "head": _linear(rng, d, self.num_actions),
        }
        for _ in range(self.n_layer):
            params["blocks"].append({
                "ln1": {"g": np.ones(d, np.float32),
                        "b": np.zeros(d, np.float32)},
                "qkv": _linear(rng, d, 3 * d),
                "proj": _linear(rng, d, d),
                "ln2": {"g": np.ones(d, np.float32),
                        "b": np.zeros(d, np.float32)},
                "fc1": _linear(rng, d, 4 * d),
                "fc2": _linear(rng, 4 * d, d),
            })
        return params

    # -- jax forward (training AND eval) --

    def forward(self, params, rtg, obs, actions, timesteps):
        """rtg [B,K], obs [B,K,D], actions [B,K] (int; position t's token
        embeds a_t), timesteps [B,K] -> action logits at each STATE token
        [B,K,A]."""
        import jax
        import jax.numpy as jnp

        B, K = rtg.shape
        d = self.d_model

        def ln(p, x):
            mu = jnp.mean(x, -1, keepdims=True)
            var = jnp.var(x, -1, keepdims=True)
            return (x - mu) / jnp.sqrt(var + 1e-5) * p["g"] + p["b"]

        pos = params["pos"][timesteps]                      # [B,K,d]
        tok_r = (rtg[..., None] @ params["emb_rtg"]["w"]
                 + params["emb_rtg"]["b"]) + pos + params["modality"][0]
        tok_s = (obs @ params["emb_obs"]["w"]
                 + params["emb_obs"]["b"]) + pos + params["modality"][1]
        a_onehot = jax.nn.one_hot(actions, self.num_actions,
                                  dtype=jnp.float32)
        tok_a = (a_onehot @ params["emb_act"]["w"]
                 + params["emb_act"]["b"]) + pos + params["modality"][2]
        # interleave -> [B, 3K, d] in (r_t, s_t, a_t) order
        x = jnp.stack([tok_r, tok_s, tok_a], axis=2).reshape(B, 3 * K, d)
        T = 3 * K
        causal = jnp.tril(jnp.ones((T, T), bool))
        for blk in params["blocks"]:
            h = ln(blk["ln1"], x)
            qkv = h @ blk["qkv"]["w"] + blk["qkv"]["b"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            hd = d // self.n_head

            def heads(t):
                return t.reshape(B, T, self.n_head, hd).transpose(0, 2, 1, 3)

            q, k, v = heads(q), heads(k), heads(v)
            att = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(hd)
            att = jnp.where(causal, att, -1e9)
            att = jax.nn.softmax(att, axis=-1)
            out = (att @ v).transpose(0, 2, 1, 3).reshape(B, T, d)
            x = x + (out @ blk["proj"]["w"] + blk["proj"]["b"])
            h = ln(blk["ln2"], x)
            h = jax.nn.gelu(h @ blk["fc1"]["w"] + blk["fc1"]["b"])
            x = x + (h @ blk["fc2"]["w"] + blk["fc2"]["b"])
        x = ln(params["ln_f"], x)
        state_tokens = x.reshape(B, K, 3, d)[:, :, 1, :]
        return state_tokens @ params["head"]["w"] + params["head"]["b"]


def dt_loss(module, params, batch, config):
    """CE between the state-token predictions and the logged actions,
    masked to valid (non-padding) positions."""
    import jax
    import jax.numpy as jnp

    logits = module.forward(params, batch["rtg"], batch["obs"],
                            batch["actions"], batch["timesteps"])
    logp = jax.nn.log_softmax(logits)
    picked = jnp.take_along_axis(
        logp, batch["actions"][..., None], axis=-1)[..., 0]
    mask = batch["mask"].astype(jnp.float32)
    loss = -jnp.sum(picked * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return loss, {"action_ce": loss}


class DTConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.input_ = None  # path / JsonReader / DatasetReader / Dataset
        self.context_len = 20
        self.d_model = 64
        self.n_layer = 2
        self.n_head = 2
        self.updates_per_iteration = 64
        self.minibatch_size = 64
        self.lr = 1e-3
        self.num_actions = None   # inferred from data when None
        self.observation_dim = None
        self.algo_class = DT

    def offline_data(self, input_=None) -> "DTConfig":
        if input_ is not None:
            self.input_ = input_
        return self


class DT(Algorithm):
    """Offline training over (R̂, s, a) windows + return-conditioned
    evaluation."""

    def _setup(self) -> None:
        cfg = self.config
        reader = cfg.input_
        if isinstance(reader, str):
            reader = JsonReader(reader)
        elif reader is not None and not hasattr(reader, "episodes"):
            reader = DatasetReader(reader)
        if reader is None:
            raise ValueError("DT requires config.offline_data(input_=...)")
        self._episodes = []
        max_len = 1
        for ep in reader.episodes():
            obs = np.asarray([r["obs"] for r in ep], np.float32)
            acts = np.asarray([r["action"] for r in ep], np.int32)
            rews = np.asarray([r["reward"] for r in ep], np.float32)
            rtg = np.cumsum(rews[::-1])[::-1].copy()  # undiscounted, DT-style
            self._episodes.append((obs, acts, rtg))
            max_len = max(max_len, len(ep))
        if not self._episodes:
            raise ValueError("offline input is empty")
        self.obs_dim = (cfg.observation_dim
                        or int(self._episodes[0][0].shape[1]))
        self.num_actions = (cfg.num_actions
                            or int(max(a.max() for _, a, _ in
                                       self._episodes)) + 1)
        self.module = DTModule(
            self.obs_dim, self.num_actions, cfg.context_len,
            cfg.d_model, cfg.n_layer, cfg.n_head,
            max_timestep=max(1024, max_len + cfg.context_len))
        self.learner = Learner(
            self.module, dt_loss, config={},
            learning_rate=cfg.lr, max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh, seed=cfg.seed,
        )
        self._rng = np.random.default_rng(cfg.seed)

    def _build_learner(self) -> None:  # pragma: no cover — done in _setup
        pass

    def _sample_window(self):
        K = self.config.context_len
        obs, acts, rtg = self._episodes[
            self._rng.integers(len(self._episodes))]
        T = len(acts)
        start = int(self._rng.integers(0, max(1, T)))
        end = min(start + K, T)
        n = end - start
        w_obs = np.zeros((K, self.obs_dim), np.float32)
        w_act = np.zeros(K, np.int32)
        w_rtg = np.zeros(K, np.float32)
        w_ts = np.zeros(K, np.int64)
        w_mask = np.zeros(K, bool)
        w_obs[K - n:] = obs[start:end]
        w_act[K - n:] = acts[start:end]
        w_rtg[K - n:] = rtg[start:end]
        w_ts[K - n:] = np.arange(start, end)
        w_mask[K - n:] = True
        return w_obs, w_act, w_rtg, w_ts, w_mask

    def training_step(self) -> dict:
        cfg = self.config
        metrics_acc: dict[str, list[float]] = {}
        for _ in range(cfg.updates_per_iteration):
            rows = [self._sample_window() for _ in range(cfg.minibatch_size)]
            batch = {
                "obs": np.stack([r[0] for r in rows]),
                "actions": np.stack([r[1] for r in rows]),
                "rtg": np.stack([r[2] for r in rows]),
                "timesteps": np.stack([r[3] for r in rows]),
                "mask": np.stack([r[4] for r in rows]),
            }
            for k, v in self.learner.update(batch).items():
                metrics_acc.setdefault(k, []).append(v)
        return {k: float(np.mean(v)) for k, v in metrics_acc.items()}

    def evaluate(self, env_spec, target_return: float,
                 episodes: int = 5) -> float:
        """Roll the env conditioning on `target_return` (Chen et al. 2021
        eval protocol: decrement the return-to-go by observed rewards)."""
        import jax

        from ray_tpu.rllib.env import make_env

        K = self.config.context_len
        fwd = jax.jit(lambda p, r, o, a, t: self.module.forward(p, r, o, a, t))
        params = self.learner.params
        totals = []
        for ep_i in range(episodes):
            env = make_env(env_spec)
            obs = env.reset(seed=1000 + ep_i)
            hist_obs, hist_act, hist_rtg = [], [], []
            rtg, total, done, t = target_return, 0.0, False, 0
            while not done and t < getattr(env, "max_episode_steps", 1000):
                hist_obs.append(np.asarray(obs, np.float32))
                hist_rtg.append(rtg)
                hist_act.append(0)  # placeholder for the current step
                w_obs = np.zeros((1, K, self.obs_dim), np.float32)
                w_act = np.zeros((1, K), np.int32)
                w_rtg = np.zeros((1, K), np.float32)
                w_ts = np.zeros((1, K), np.int64)
                n = min(K, len(hist_obs))
                w_obs[0, K - n:] = np.stack(hist_obs[-n:])
                w_act[0, K - n:] = hist_act[-n:]
                w_rtg[0, K - n:] = hist_rtg[-n:]
                w_ts[0, K - n:] = np.arange(
                    len(hist_obs) - n, len(hist_obs))
                logits = np.asarray(fwd(params, w_rtg, w_obs, w_act, w_ts))
                action = int(np.argmax(logits[0, -1]))
                hist_act[-1] = action
                obs, reward, term, trunc = env.step(action)
                done = term or trunc
                total += reward
                rtg -= reward
                t += 1
            totals.append(total)
        return float(np.mean(totals))

    def train(self) -> dict:
        metrics = self.training_step()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        return metrics

    def stop(self) -> None:
        pass
