"""CQL — Conservative Q-Learning from offline experience files.

Equivalent of the reference's CQL (reference: rllib/algorithms/cql/cql.py —
SAC + conservative regularizer per Kumar et al. 2020). This is the
DISCRETE-action variant (CQL(H) with the logsumexp regularizer over the
full action set), trained from the same MARWIL/BC experience-file format
(JsonReader rows), so a dataset recorded with config.offline_data(output=…)
feeds it directly:

    L = TD(double-Q) + cql_alpha * E[ logsumexp_a Q(s,a) - Q(s, a_data) ]

The regularizer pushes down Q on out-of-distribution actions while holding
up Q on dataset actions — the defining offline-RL correction the pure
TD objective lacks.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.offline.io import DatasetReader, JsonReader
from ray_tpu.rllib.rl_module import QModule


def cql_loss(module, params, batch, config):
    """Double-Q TD loss + conservative logsumexp penalty (pure jax)."""
    import jax
    import jax.numpy as jnp

    q = module.forward(params, batch["obs"])
    q_data = jnp.take_along_axis(q, batch["actions"][:, None], axis=-1)[:, 0]
    q_next_online = module.forward(params, batch["next_obs"])
    q_next_target = module.forward(batch["target_params"], batch["next_obs"])
    best = jnp.argmax(q_next_online, axis=-1)
    q_next = jnp.take_along_axis(q_next_target, best[:, None], axis=-1)[:, 0]
    not_term = 1.0 - batch["terminateds"].astype(q.dtype)
    target = batch["rewards"] + config["gamma"] * not_term * q_next
    td = q_data - jax.lax.stop_gradient(target)
    td_loss = jnp.mean(jnp.square(td))
    # conservative term: logsumexp over ALL actions minus the dataset
    # action's Q — zero iff the policy implied by Q stays on-distribution
    cql_term = jnp.mean(jax.nn.logsumexp(q, axis=-1) - q_data)
    total = td_loss + config["cql_alpha"] * cql_term
    return total, {
        "td_loss": td_loss,
        "cql_gap": cql_term,
        "q_data_mean": jnp.mean(q_data),
    }


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.cql_alpha = 1.0
        self.input_ = None  # path / JsonReader / DatasetReader / Dataset
        self.observation_dim = None
        self.num_actions = None
        self.target_update_freq = 50  # gradient steps
        self.algo_class = CQL

    def offline_data(self, input_=None, cql_alpha=None) -> "CQLConfig":
        if input_ is not None:
            self.input_ = input_
        if cql_alpha is not None:
            self.cql_alpha = cql_alpha
        return self

    def environment(self, env=None, *, observation_dim=None,
                    num_actions=None) -> "CQLConfig":
        if env is not None:
            self.env_spec = env
        if observation_dim is not None:
            self.observation_dim = observation_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self


class CQL(Algorithm):
    """Offline-only: `_setup` builds (s, a, r, s', term) transitions from
    the episode files; `train()` runs minibatch TD + conservative epochs."""

    def _setup(self) -> None:
        cfg = self.config
        reader = cfg.input_
        if isinstance(reader, str):
            reader = JsonReader(reader)
        elif reader is not None and not hasattr(reader, "episodes"):
            reader = DatasetReader(reader)
        if reader is None:
            raise ValueError("CQL requires config.offline_data(input_=...)")
        obs, actions, rewards, next_obs, term = [], [], [], [], []
        for ep in reader.episodes():
            for i, row in enumerate(ep):
                terminated = bool(row.get("terminated", row["done"]))
                if i + 1 == len(ep) and not terminated:
                    # episode-final TRUNCATED row: no successor obs was
                    # logged, and marking it terminal would bias Q-targets
                    # low on time-limited envs (the reference distinguishes
                    # terminated from truncated) — drop the transition
                    continue
                obs.append(row["obs"])
                actions.append(row["action"])
                rewards.append(row["reward"])
                if i + 1 < len(ep):
                    next_obs.append(ep[i + 1]["obs"])
                else:
                    next_obs.append(row["obs"])  # terminal: masked below
                term.append(terminated)
        if not actions:
            raise ValueError("offline input is empty")
        self._obs = np.asarray(obs, np.float32)
        self._actions = np.asarray(actions)
        if self._actions.ndim != 1 or not np.all(
                self._actions == np.round(self._actions)):
            raise ValueError(
                "discrete CQL requires scalar integer actions; got shape "
                f"{self._actions.shape}")
        self._actions = self._actions.astype(np.int32)
        self._rewards = np.asarray(rewards, np.float32)
        self._next_obs = np.asarray(next_obs, np.float32)
        self._terminateds = np.asarray(term, np.bool_)
        self.obs_dim = cfg.observation_dim or int(self._obs.shape[1])
        self.num_actions = (cfg.num_actions
                            or int(self._actions.max()) + 1)
        self._rng = np.random.default_rng(cfg.seed)
        self._build_learner()

    def _build_learner(self) -> None:
        cfg = self.config
        module = QModule(self.obs_dim, self.num_actions, cfg.hidden)
        self.learner = Learner(
            module,
            cql_loss,
            config={"gamma": cfg.gamma, "cql_alpha": cfg.cql_alpha},
            learning_rate=cfg.lr,
            max_grad_norm=cfg.max_grad_norm,
            mesh=cfg.mesh,
            seed=cfg.seed,
        )
        self._target_params = self.learner.get_weights_np()
        self._grad_steps = 0

    def training_step(self) -> dict:
        cfg = self.config
        n = len(self._actions)
        mb = min(cfg.minibatch_size, n)
        metrics_acc: dict[str, list[float]] = {}
        for _ in range(cfg.num_epochs):
            perm = self._rng.permutation(n)
            for start in range(0, n - mb + 1, mb):
                idx = perm[start:start + mb]
                m = self.learner.update({
                    "obs": self._obs[idx],
                    "actions": self._actions[idx],
                    "rewards": self._rewards[idx],
                    "next_obs": self._next_obs[idx],
                    "terminateds": self._terminateds[idx],
                    "target_params": self._target_params,
                })
                self._grad_steps += 1
                if self._grad_steps % cfg.target_update_freq == 0:
                    self._target_params = self.learner.get_weights_np()
                for k, v in m.items():
                    metrics_acc.setdefault(k, []).append(v)
        return {k: float(np.mean(v)) for k, v in metrics_acc.items()}

    def _sample_all(self):  # pragma: no cover — offline only
        raise RuntimeError("offline algorithm does not sample")

    def compute_action(self, obs: np.ndarray) -> int:
        w = self.learner.get_weights_np()
        q = self.learner.module.forward_np(w, np.asarray(obs, np.float32)[None])
        return int(np.argmax(q[0]))
