"""Learner — the jitted gradient step, optionally over a device mesh.

Equivalent of the reference's Learner/TorchLearner
(reference: rllib/core/learner/learner.py:229; torch_learner.py:53 with DDP
wrap at :368). TPU mapping per SURVEY.md §3.5: the Learner IS a jitted train
step; data parallelism is a sharded batch under jit on a mesh 'data' axis
(XLA inserts the gradient psum — no DDP wrapper object).
"""
from __future__ import annotations

from typing import Any, Callable

import numpy as np


class Learner:
    """Owns params + optimizer state on device and applies jitted updates.

    loss_fn(module, params, batch, config) -> (scalar loss, metrics dict) —
    pure, jax-traceable; each algorithm supplies its own.
    """

    def __init__(
        self,
        module,
        loss_fn: Callable,
        config: dict,
        learning_rate: float = 3e-4,
        max_grad_norm: float | None = 0.5,
        mesh=None,
        seed: int = 0,
    ):
        import jax
        import optax

        self.module = module
        self.loss_fn = loss_fn
        self.config = dict(config)
        self.mesh = mesh
        chain = []
        if max_grad_norm is not None:
            chain.append(optax.clip_by_global_norm(max_grad_norm))
        chain.append(optax.adam(learning_rate))
        self._tx = optax.chain(*chain)
        self.params = jax.tree_util.tree_map(
            lambda x: jax.numpy.asarray(x), module.init(seed)
        )
        self.opt_state = self._tx.init(self.params)
        self._update_jit = jax.jit(self._update_impl)
        self._batch_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ray_tpu.parallel.mesh import AxisNames

            # batch sharded over the data axis; params replicated — XLA
            # derives the grad all-reduce (idiomatic dp, no DDP object)
            self._batch_sharding = NamedSharding(mesh, P(AxisNames.DATA))
            self._replicated_sharding = NamedSharding(mesh, P())
            self.params = jax.device_put(self.params, self._replicated_sharding)
            self.opt_state = jax.device_put(self.opt_state, self._replicated_sharding)

    def _update_impl(self, params, opt_state, batch):
        import jax
        import optax

        (loss, metrics), grads = jax.value_and_grad(
            lambda p: self.loss_fn(self.module, p, batch, self.config),
            has_aux=True,
        )(params)
        updates, opt_state = self._tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics)
        metrics["loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, metrics

    def update(self, batch: dict) -> dict:
        """One gradient step on a host batch (dict of arrays, leading dim =
        batch). Returns float metrics."""
        import jax

        if self._batch_sharding is not None:
            # only top-level arrays are per-example data; nested pytrees
            # (e.g. DQN's target_params riding in the batch) replicate
            batch = {
                k: jax.device_put(
                    v,
                    self._batch_sharding
                    if isinstance(v, np.ndarray)
                    else self._replicated_sharding,
                )
                for k, v in batch.items()
            }
        self.params, self.opt_state, metrics = self._update_jit(
            self.params, self.opt_state, batch
        )
        # "_"-prefixed metrics are per-sample arrays (e.g. PER |td|);
        # everything else reduces to a float scalar
        return {
            k: (np.asarray(v) if k.startswith("_") else float(v))
            for k, v in metrics.items()
        }

    def get_weights_np(self) -> dict:
        """Host numpy copy for EnvRunner broadcast (device→host once per
        iteration — SURVEY.md §3.5 'weight sync = device→host once per iter')."""
        import jax

        return jax.tree_util.tree_map(lambda x: np.asarray(x), self.params)

    def set_weights(self, params: Any) -> None:
        import jax

        self.params = jax.tree_util.tree_map(lambda x: jax.numpy.asarray(x), params)

    def state(self) -> dict:
        return {"params": self.get_weights_np()}

    def load_state(self, state: dict) -> None:
        self.set_weights(state["params"])
