"""ray_tpu.rllib — reinforcement learning on the distributed core.

Equivalent of the reference's RLlib (reference: rllib/ — SURVEY.md §2.3 A6,
§3.5 call stack). TPU mapping: EnvRunners are CPU actors running a numpy
policy path; the Learner is a jitted train step on the device (mesh-aware
data parallelism via sharded batches); weights sync device→host once per
iteration.
"""
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms import (
    APPO,
    APPOConfig,
    DDPG,
    DDPGConfig,
    TD3,
    TD3Config,
    DQN,
    DQNConfig,
    IMPALA,
    ImpalaConfig,
    PPO,
    PPOConfig,
    R2D2,
    R2D2Config,
)
from ray_tpu.rllib.connectors import (
    ClipObs,
    Connector,
    ConnectorPipeline,
    FlattenObs,
    FrameStack,
    NormalizeObs,
)
from ray_tpu.rllib.multi_agent import (
    IndependentMultiEnv,
    MultiAgentEnv,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.env import (
    CartPole,
    Corridor,
    Env,
    Pendulum,
    GymEnv,
    VectorEnv,
    make_env,
    register_env,
)
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rl_module import (
    ActorCriticModule,
    DistributionalQModule,
    QModule,
    RecurrentQModule,
)

__all__ = [
    "APPO",
    "APPOConfig",
    "ActorCriticModule",
    "Algorithm",
    "AlgorithmConfig",
    "CartPole",
    "ClipObs",
    "Connector",
    "ConnectorPipeline",
    "FlattenObs",
    "FrameStack",
    "IndependentMultiEnv",
    "MultiAgentEnv",
    "MultiAgentEnvRunner",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "NormalizeObs",
    "Pendulum",
    "DDPG",
    "DDPGConfig",
    "TD3",
    "TD3Config",
    "Corridor",
    "DQN",
    "DQNConfig",
    "Env",
    "EnvRunner",
    "GymEnv",
    "IMPALA",
    "ImpalaConfig",
    "Learner",
    "PPO",
    "PPOConfig",
    "DistributionalQModule",
    "QModule",
    "R2D2",
    "R2D2Config",
    "RecurrentQModule",
    "ReplayBuffer",
    "VectorEnv",
    "make_env",
    "register_env",
]


from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("rllib")
del _rlu
