"""EnvRunner — rollout collection, local or as a CPU actor.

Equivalent of the reference's EnvRunner/RolloutWorker
(reference: rllib/env/env_runner.py:9, rllib/evaluation/rollout_worker.py:159;
fan-out via rollout_ops.py:21 synchronous_parallel_sample). Runs the numpy
policy path only — no jax in rollout processes (SURVEY.md §3.5: env stepping
stays on CPU actors; the learner owns the device).
"""
from __future__ import annotations

import numpy as np


class EnvRunner:
    """Steps a VectorEnv with the current policy; returns fixed-shape
    rollout batches [T, E, ...] (static shapes keep the learner jit-stable).
    """

    def __init__(
        self,
        env_spec,
        module_factory,
        num_envs: int = 1,
        rollout_length: int = 64,
        seed: int = 0,
        # actor_critic: sample policy + record logp/values (PPO family)
        # epsilon_greedy: argmax Q with annealed exploration (DQN family)
        # softmax: sample the module's stochastic policy (SAC family)
        # continuous: deterministic policy + gaussian exploration noise
        #             scaled by `epsilon` (TD3/DDPG family)
        mode: str = "actor_critic",
        connectors: list | None = None,
    ):
        from ray_tpu.rllib.connectors import ConnectorPipeline
        from ray_tpu.rllib.env import VectorEnv

        self.vec = VectorEnv(env_spec, num_envs, base_seed=seed)
        self.pipeline = ConnectorPipeline(connectors)
        # the module (and hence the learner) sees the CONNECTOR-PROCESSED
        # observation space — e.g. FrameStack(k) multiplies the dim by k
        self.obs_dim = self.pipeline.setup(num_envs, self.vec.observation_dim)
        self.module = module_factory(self.obs_dim, self.vec.num_actions)
        self.rollout_length = rollout_length
        self.mode = mode
        self._rng = np.random.default_rng(seed + 1000)
        self._params: dict | None = None
        self.epsilon = 1.0
        # recurrent modules: the runner owns per-env hidden-state rows,
        # persisted ACROSS rollouts (sequences continue mid-episode; the
        # stored state_in makes replayed sequences self-contained — R2D2's
        # stored-state strategy, Kapturowski et al. 2019)
        self._recurrent = bool(getattr(self.module, "is_recurrent", False))
        self._h = (
            self.module.initial_state(self.vec.num_envs)
            if self._recurrent else None
        )

    def set_weights(self, params: dict, epsilon: float | None = None) -> None:
        self._params = params
        if epsilon is not None:
            self.epsilon = epsilon

    def env_info(self) -> dict:
        return {
            "observation_dim": self.obs_dim,
            "num_actions": self.vec.num_actions,
            "continuous": self.vec.continuous,
            "action_dim": self.vec.action_dim,
            "action_bound": self.vec.action_bound,
        }

    def get_state(self) -> dict:
        return {"connectors": self.pipeline.state(), "epsilon": self.epsilon}

    def set_state(self, state: dict) -> None:
        self.pipeline.load_state(state["connectors"])
        self.epsilon = state["epsilon"]

    def sample(self) -> dict:
        """One rollout of T steps across E envs."""
        if self._params is None:
            raise RuntimeError("set_weights must be called before sample()")
        T, E = self.rollout_length, self.vec.num_envs
        obs_dim = self.obs_dim
        batch = {
            "obs": np.empty((T, E, obs_dim), np.float32),
            "actions": (
                np.empty((T, E, self.vec.action_dim), np.float32)
                if self.mode == "continuous"
                else np.empty((T, E), np.int32)
            ),
            "rewards": np.empty((T, E), np.float32),
            "dones": np.empty((T, E), np.bool_),
            "terminateds": np.empty((T, E), np.bool_),
        }
        if self.mode == "actor_critic":
            batch["logp"] = np.empty((T, E), np.float32)
            batch["values"] = np.empty((T, E), np.float32)
            # V(true next obs) at episode boundaries (zeros elsewhere):
            # truncated episodes bootstrap from the REAL successor state,
            # not the auto-reset obs
            batch["bootstrap_values"] = np.zeros((T, E), np.float32)
        else:
            batch["next_obs"] = np.empty((T, E, obs_dim), np.float32)
        if self._recurrent:
            # hidden state at rollout start + whether step t begins a new
            # episode (t=0 rows are continuations unless state_in is zero)
            batch["state_in"] = self._h.copy()
            batch["resets"] = np.zeros((T, E), np.bool_)
        pending_boots: list[tuple] = []  # (t, done_mask, done rows' obs)
        for t in range(T):
            obs = self.pipeline(self.vec.obs)
            batch["obs"][t] = obs
            if self.mode == "actor_critic":
                actions, logp, values = self.module.sample_actions_np(
                    self._params, obs, self._rng
                )
                batch["logp"][t] = logp
                batch["values"][t] = values
            elif self.mode == "softmax":
                actions = self.module.sample_actions_np(
                    self._params, obs, self._rng
                )
            elif self.mode == "continuous":
                if hasattr(self.module, "sample_actions_np"):
                    # stochastic policy (SAC): its own sampling explores
                    actions = self.module.sample_actions_np(
                        self._params, obs, self._rng
                    ).astype(np.float32)
                else:
                    mean = self.module.policy_np(self._params, obs)
                    noise = self._rng.normal(
                        0.0, self.epsilon * self.vec.action_bound, mean.shape
                    )
                    actions = np.clip(
                        mean + noise,
                        -self.vec.action_bound, self.vec.action_bound,
                    ).astype(np.float32)
            else:
                if self._recurrent:
                    q, self._h = self.module.step_np(self._params, obs, self._h)
                else:
                    q = self.module.forward_np(self._params, obs)
                greedy = np.argmax(q, axis=-1)
                random_a = self._rng.integers(0, self.vec.num_actions, size=E)
                explore = self._rng.uniform(size=E) < self.epsilon
                actions = np.where(explore, random_a, greedy).astype(np.int32)
            if self._recurrent and hasattr(self.module, "pack_action"):
                # modules whose filter conditions on the previous action
                # (Dreamer's RSSM) record the CHOSEN action — exploration
                # included — in the carried state
                self._h = self.module.pack_action(self._h, actions)
            true_next_obs, rewards, dones, terms = self.vec.step(actions)
            batch["actions"][t] = actions
            batch["rewards"][t] = rewards
            batch["dones"][t] = dones
            batch["terminateds"][t] = terms
            if self.mode == "actor_critic":
                if dones.any():
                    # peek: processed successor obs WITHOUT advancing
                    # connector state (the real next pipeline step happens
                    # on the auto-reset obs). Deferred: boundary rows are
                    # batched into ONE forward after the loop — per-step
                    # value calls were the conv rollout bottleneck.
                    proc_next = self.pipeline.peek(true_next_obs)
                    pending_boots.append((t, dones.copy(), proc_next[dones]))
            else:
                batch["next_obs"][t] = self.pipeline.peek(true_next_obs)
            if self._recurrent:
                if t + 1 < T:
                    batch["resets"][t + 1] = dones
                if dones.any():
                    # fresh episode -> fresh hidden state
                    self._h = np.where(dones[:, None], 0.0, self._h)
            self.pipeline.on_dones(dones)
        if self.mode == "actor_critic":
            # bootstrap values for the obs after the last step
            _, last_values = self.module.forward_np(
                self._params, self.pipeline.peek(self.vec.obs))
            batch["last_values"] = last_values.astype(np.float32)
            if pending_boots:
                rows = np.concatenate([r for _, _, r in pending_boots])
                n_rows = len(rows)
                # pad to a power-of-two bucket: a jitted forward recompiles
                # per input shape, and the boundary count varies per rollout
                bucket = 1 << (n_rows - 1).bit_length()
                if bucket != n_rows:
                    rows = np.concatenate(
                        [rows, np.zeros((bucket - n_rows, rows.shape[1]),
                                        rows.dtype)])
                _, v_all = self.module.forward_np(self._params, rows)
                v_all = v_all[:n_rows]
                off = 0
                for t, dones, r in pending_boots:
                    n = len(r)
                    batch["bootstrap_values"][t][dones] = v_all[off:off + n]
                    off += n
        returns, lengths = self.vec.pop_episode_stats()
        batch["episode_returns"] = np.asarray(returns, np.float32)
        batch["episode_lengths"] = np.asarray(lengths, np.int64)
        return batch
