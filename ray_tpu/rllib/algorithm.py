"""AlgorithmConfig + Algorithm base — the RL training driver.

Equivalent of the reference's Algorithm(Trainable) and AlgorithmConfig
(reference: rllib/algorithms/algorithm.py:191, step() :815,
training_step() :1402; algorithm_config.py:118 fluent builder). The driver
loop: fan rollout collection out to EnvRunner actors (or a local runner),
aggregate batches, run jitted learner updates, broadcast weights back —
SURVEY.md §3.5's TPU mapping.
"""
from __future__ import annotations

import copy
import time
from typing import Any

import numpy as np


class AlgorithmConfig:
    """Fluent config builder (reference: algorithm_config.py:118)."""

    def __init__(self):
        self.env_spec: Any = None
        self.num_env_runners = 0  # 0 = sample in the driver process
        self.num_envs_per_runner = 4
        self.rollout_length = 64
        self.connectors = None  # list of Connector instances (or None)
        self.lr = 3e-4
        self.gamma = 0.99
        self.train_batch_size = 256
        self.minibatch_size = 128
        self.num_epochs = 4
        self.hidden = (64, 64)
        self.max_grad_norm = 0.5
        self.seed = 0
        self.mesh = None  # optional jax Mesh with a 'data' axis for the learner
        self.output = None  # JSONL experience-output path (offline_data)
        self.external = None  # (host, port, obs_dim, num_actions) policy server
        self.extra: dict = {}

    # -- builder surface (mirrors the reference's groups) --

    def environment(self, env: Any) -> "AlgorithmConfig":
        self.env_spec = env
        return self

    def env_runners(
        self,
        num_env_runners: int | None = None,
        num_envs_per_runner: int | None = None,
        rollout_length: int | None = None,
        connectors: list | None = None,
    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_runner is not None:
            self.num_envs_per_runner = num_envs_per_runner
        if rollout_length is not None:
            self.rollout_length = rollout_length
        if connectors is not None:
            self.connectors = connectors
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def learners(self, mesh=None) -> "AlgorithmConfig":
        self.mesh = mesh
        return self

    def external_env(self, port: int, obs_dim: int, num_actions: int,
                     host: str = "127.0.0.1") -> "AlgorithmConfig":
        """Experience arrives from external PolicyClient processes instead
        of an in-process env: the algorithm starts a PolicyServerInput on
        `port` (0 = ephemeral; read it back from `algo.policy_server.port`).
        The env's spaces cannot be introspected remotely, so declare them
        (reference: policy_server_input.py requires the same)."""
        self.external = (host, int(port), int(obs_dim), int(num_actions))
        return self

    def offline_data(self, output: str | None = None) -> "AlgorithmConfig":
        """Log every sampled rollout batch to a JSONL experience file
        (reference: config.offline_data(output=...) → JsonWriter)."""
        if output is not None:
            self.output = output
        return self

    def debugging(self, seed: int | None = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        return self.algo_class(self)  # set by subclass

    def to_dict(self) -> dict:
        d = {k: v for k, v in self.__dict__.items() if k not in ("mesh",)}
        return d


class Algorithm:
    """Base driver. Subclasses define `_make_runner_factory` and
    `training_step`."""

    runner_mode = "actor_critic"

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._runners = []  # actor handles, or [local EnvRunner]
        self._local_runner = None
        self._recent_returns: list[float] = []
        self._total_env_steps = 0
        self._output_writer = None
        self._setup()

    # -- setup --

    def _setup(self) -> None:
        cfg = self.config
        factory = self._runner_factory()
        if cfg.external is not None:
            from ray_tpu.rllib.external import PolicyServerInput

            host, port, obs_dim, num_actions = cfg.external
            self.policy_server = PolicyServerInput(
                port, obs_dim, num_actions, factory,
                rollout_length=cfg.rollout_length, mode=self.runner_mode,
                host=host, seed=cfg.seed,
            )
            self._local_runner = self.policy_server
            info = self.policy_server.env_info()
        elif cfg.num_env_runners > 0:
            import ray_tpu
            from ray_tpu.rllib.env_runner import EnvRunner

            runner_cls = ray_tpu.remote(num_cpus=1)(EnvRunner)
            self._runners = [
                runner_cls.remote(
                    cfg.env_spec,
                    factory,
                    num_envs=cfg.num_envs_per_runner,
                    rollout_length=cfg.rollout_length,
                    seed=cfg.seed + 1 + i,
                    mode=self.runner_mode,
                    connectors=cfg.connectors,
                )
                for i in range(cfg.num_env_runners)
            ]
            import ray_tpu as rt

            info = rt.get(self._runners[0].env_info.remote(), timeout=120)
        else:
            from ray_tpu.rllib.env_runner import EnvRunner

            self._local_runner = EnvRunner(
                cfg.env_spec,
                factory,
                num_envs=cfg.num_envs_per_runner,
                rollout_length=cfg.rollout_length,
                seed=cfg.seed,
                mode=self.runner_mode,
                connectors=cfg.connectors,
            )
            info = self._local_runner.env_info()
        self.obs_dim = info["observation_dim"]
        self.num_actions = info["num_actions"]
        self.continuous = info.get("continuous", False)
        self.action_dim = info.get("action_dim", 0)
        self.action_bound = info.get("action_bound", 1.0)
        self._build_learner()

    def _runner_factory(self):
        """Callable (obs_dim, num_actions) -> module, cloudpickled to
        runner actors."""
        raise NotImplementedError

    def _build_learner(self) -> None:
        raise NotImplementedError

    def training_step(self) -> dict:
        raise NotImplementedError

    # -- rollout plumbing --

    def _broadcast_weights(self, params_np: dict, epsilon: float | None = None) -> None:
        if self._local_runner is not None:
            self._local_runner.set_weights(params_np, epsilon)
        else:
            import ray_tpu

            ray_tpu.get(
                [r.set_weights.remote(params_np, epsilon) for r in self._runners],
                timeout=120,
            )

    def _record_batch(self, b: dict) -> None:
        """Episode-return window + lifetime step accounting for one batch."""
        self._recent_returns.extend(b["episode_returns"].tolist())
        self._recent_returns = self._recent_returns[-100:]
        self._total_env_steps += b["rewards"].size
        if self.config.output is not None:
            if self._output_writer is None:
                from ray_tpu.rllib.offline import JsonWriter

                self._output_writer = JsonWriter(self.config.output)
            self._output_writer.write_batch(b)

    def _sample_all(self) -> list[dict]:
        """synchronous_parallel_sample (reference: rollout_ops.py:21)."""
        if self._local_runner is not None:
            batches = [self._local_runner.sample()]
        else:
            import ray_tpu

            batches = ray_tpu.get(
                [r.sample.remote() for r in self._runners], timeout=300
            )
        for b in batches:
            self._record_batch(b)
        return batches

    # -- public Trainable surface --

    def train(self) -> dict:
        """One iteration (reference: Trainable.train → step → training_step)."""
        t0 = time.monotonic()
        metrics = self.training_step()
        self.iteration += 1
        metrics.update(
            {
                "training_iteration": self.iteration,
                "num_env_steps_sampled_lifetime": self._total_env_steps,
                "episode_return_mean": (
                    float(np.mean(self._recent_returns))
                    if self._recent_returns
                    else float("nan")
                ),
                "time_this_iter_s": time.monotonic() - t0,
            }
        )
        return metrics

    def stop(self) -> None:
        import ray_tpu

        if getattr(self, "policy_server", None) is not None:
            self.policy_server.close()
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
        self._runners = []

    # -- checkpointing (Trainable save/restore surface) --

    def save_state(self) -> dict:
        """Learner weights + the off-policy bookkeeping subclasses keep by
        convention (_target_params / _grad_steps / _env_steps) — a restore
        must not compute TD targets against a random target net or reset
        exploration annealing. Replay buffers are deliberately NOT
        persisted (matching the reference's default checkpoints)."""
        state = {
            "iteration": self.iteration,
            "total_env_steps": self._total_env_steps,
            "learner": self.learner.state(),
        }
        if getattr(self, "_target_params", None) is not None:
            state["target_params"] = self._target_params
        for attr in ("_grad_steps", "_env_steps"):
            if hasattr(self, attr):
                state[attr.lstrip("_")] = getattr(self, attr)
        return state

    def load_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        self._total_env_steps = state["total_env_steps"]
        self.learner.load_state(state["learner"])
        if "target_params" in state and hasattr(self, "_target_params"):
            self._target_params = state["target_params"]
        for attr in ("_grad_steps", "_env_steps"):
            key = attr.lstrip("_")
            if key in state and hasattr(self, attr):
                setattr(self, attr, state[key])

    @classmethod
    def as_trainable(cls, base_config: AlgorithmConfig, stop_iters: int = 10):
        """Adapter to the tune function-trainable API: hyperparams from the
        tune config dict overlay the base config (reference: Algorithm IS a
        Trainable class; our tune runs function trainables)."""

        def trainable(tune_config: dict):
            from ray_tpu import tune as rt_tune

            cfg = base_config.copy()
            for k, v in tune_config.items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
                else:
                    cfg.extra[k] = v
            algo = cls(cfg)
            try:
                for _ in range(stop_iters):
                    rt_tune.report(algo.train())
            finally:
                algo.stop()

        return trainable
