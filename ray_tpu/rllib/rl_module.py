"""RLModule — policy/value networks as pure param pytrees.

Equivalent of the reference's RLModule (reference: rllib/core/rl_module/
rl_module.py:229; torch/tf models rllib/models/; a jax model dir exists at
rllib/models/jax/). Two forward paths over the SAME params:

  * `forward` — jax, jitted inside the Learner's update on the device mesh.
  * `forward_np` — numpy, used by CPU EnvRunner actors for action sampling
    (no jax runtime in rollout workers: sampling a 2x64 MLP is
    memory-latency-bound, and keeping jax out of the env actors keeps them
    lightweight and off the TPU — SURVEY.md §3.5 TPU mapping).
"""
from __future__ import annotations

from typing import Sequence

import numpy as np


def _init_linear(rng: np.random.Generator, n_in: int, n_out: int, scale: float):
    # orthogonal init (standard for PPO stability)
    a = rng.normal(size=(n_in, n_out))
    q, r = np.linalg.qr(a) if n_in >= n_out else np.linalg.qr(a.T)
    q = q if n_in >= n_out else q.T
    q = q[:n_in, :n_out]
    return {
        "w": (scale * q).astype(np.float32),
        "b": np.zeros(n_out, np.float32),
    }


def _mlp(xp, layers, x):
    """Backend-generic tanh-MLP forward (xp = np | jnp) — the single
    implementation behind both rollout (numpy) and learner (jax) paths."""
    for layer in layers[:-1]:
        x = xp.tanh(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return x @ last["w"] + last["b"]


def _mlp_jax(layers, x):
    import jax.numpy as jnp

    return _mlp(jnp, layers, x)


class ActorCriticModule:
    """Tanh-MLP trunk with separate policy/value heads (discrete actions)."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        params: dict = {"pi": [], "vf": []}
        for head, out_dim, out_scale in (
            ("pi", self.num_actions, 0.01),
            ("vf", 1, 1.0),
        ):
            dims = [self.obs_dim, *self.hidden]
            layers = [
                _init_linear(rng, dims[i], dims[i + 1], np.sqrt(2))
                for i in range(len(dims) - 1)
            ]
            layers.append(_init_linear(rng, dims[-1], out_dim, out_scale))
            params[head] = layers
        return params

    # -- numpy path (EnvRunner) --

    @staticmethod
    def _mlp_np(layers: list[dict], x: np.ndarray) -> np.ndarray:
        for layer in layers[:-1]:
            x = np.tanh(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    def forward_np(self, params: dict, obs: np.ndarray):
        """(logits [B, A], values [B])."""
        logits = self._mlp_np(params["pi"], obs)
        values = self._mlp_np(params["vf"], obs)[:, 0]
        return logits, values

    def sample_actions_np(
        self, params: dict, obs: np.ndarray, rng: np.random.Generator
    ):
        """(actions, logp, values) — categorical sampling via Gumbel trick."""
        logits, values = self.forward_np(params, obs)
        z = logits - logits.max(axis=-1, keepdims=True)
        logp_all = z - np.log(np.exp(z).sum(axis=-1, keepdims=True))
        gumbel = -np.log(-np.log(rng.uniform(1e-10, 1.0, logits.shape)))
        actions = np.argmax(logits + gumbel, axis=-1)
        logp = np.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
        return actions.astype(np.int32), logp.astype(np.float32), values.astype(np.float32)

    # -- jax path (Learner) --

    def forward(self, params, obs):
        """Same math in jax; called inside the jitted learner update."""
        logits = _mlp_jax(params["pi"], obs)
        values = _mlp_jax(params["vf"], obs)[:, 0]
        return logits, values


class QModule:
    """Q-network MLP for value-based algorithms (DQN family). With
    `dueling`, the net splits into value + advantage streams recombined as
    Q = V + A - mean(A) (reference: dqn_torch_model.py dueling heads,
    Wang et al. 2016)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64), dueling: bool = False):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)
        self.dueling = dueling

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        if not self.dueling:
            dims = [self.obs_dim, *self.hidden, self.num_actions]
            return {
                "q": [
                    _init_linear(rng, dims[i], dims[i + 1], np.sqrt(2))
                    for i in range(len(dims) - 1)
                ]
            }
        dims = [self.obs_dim, *self.hidden]
        trunk = [
            _init_linear(rng, dims[i], dims[i + 1], np.sqrt(2))
            for i in range(len(dims) - 1)
        ]
        return {
            "trunk": trunk,
            "v": [_init_linear(rng, dims[-1], 1, 1.0)],
            "a": [_init_linear(rng, dims[-1], self.num_actions, 0.01)],
        }

    def forward_np(self, params: dict, obs: np.ndarray) -> np.ndarray:
        if not self.dueling:
            return ActorCriticModule._mlp_np(params["q"], obs)
        h = obs
        for layer in params["trunk"]:
            h = np.tanh(h @ layer["w"] + layer["b"])
        v = h @ params["v"][0]["w"] + params["v"][0]["b"]
        a = h @ params["a"][0]["w"] + params["a"][0]["b"]
        return v + a - a.mean(axis=-1, keepdims=True)

    def forward(self, params, obs):
        import jax.numpy as jnp

        if not self.dueling:
            return _mlp_jax(params["q"], obs)
        h = obs
        for layer in params["trunk"]:
            h = jnp.tanh(h @ layer["w"] + layer["b"])
        v = h @ params["v"][0]["w"] + params["v"][0]["b"]
        a = h @ params["a"][0]["w"] + params["a"][0]["b"]
        return v + a - jnp.mean(a, axis=-1, keepdims=True)


class DistributionalQModule:
    """C51 categorical value network (Bellemare et al. 2017; reference:
    dqn_torch_model.py num_atoms>1 path). The head emits per-action
    logits over `n_atoms` fixed support points z in [v_min, v_max];
    `forward`/`forward_np` collapse to the expected Q so epsilon-greedy
    EnvRunners and the target-selection code are distribution-agnostic,
    while `logits` exposes the full distribution to the C51 loss."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64), n_atoms: int = 51,
                 v_min: float = -10.0, v_max: float = 10.0):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)
        self.n_atoms = n_atoms
        self.v_min = float(v_min)
        self.v_max = float(v_max)
        self.support = np.linspace(v_min, v_max, n_atoms).astype(np.float32)

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        dims = [self.obs_dim, *self.hidden, self.num_actions * self.n_atoms]
        return {
            "q": [
                _init_linear(rng, dims[i], dims[i + 1],
                             np.sqrt(2) if i < len(dims) - 2 else 0.01)
                for i in range(len(dims) - 1)
            ]
        }

    def logits(self, params, obs):
        """[B, num_actions, n_atoms] (jax)."""
        out = _mlp_jax(params["q"], obs)
        return out.reshape(*out.shape[:-1], self.num_actions, self.n_atoms)

    def forward(self, params, obs):
        import jax
        import jax.numpy as jnp

        probs = jax.nn.softmax(self.logits(params, obs), axis=-1)
        return jnp.sum(probs * jnp.asarray(self.support), axis=-1)

    def forward_np(self, params: dict, obs: np.ndarray) -> np.ndarray:
        out = ActorCriticModule._mlp_np(params["q"], obs)
        out = out.reshape(*out.shape[:-1], self.num_actions, self.n_atoms)
        out = out - out.max(axis=-1, keepdims=True)
        p = np.exp(out)
        p /= p.sum(axis=-1, keepdims=True)
        return (p * self.support).sum(axis=-1)


class DeterministicPolicyModule:
    """Actor-critic pair for continuous control: tanh-bounded deterministic
    actor pi(s) and twin Q(s, a) critics (reference: rllib's DDPG/TD3
    models — ddpg/ddpg_torch_model.py actor + twin critics per TD3,
    Fujimoto et al. 2018)."""

    def __init__(self, obs_dim: int, action_dim: int, action_bound: float,
                 hidden: Sequence[int] = (64, 64), twin_q: bool = True):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.action_bound = float(action_bound)
        self.hidden = tuple(hidden)
        self.twin_q = twin_q

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        params: dict = {}
        dims_pi = [self.obs_dim, *self.hidden]
        layers = [
            _init_linear(rng, dims_pi[i], dims_pi[i + 1], np.sqrt(2))
            for i in range(len(dims_pi) - 1)
        ]
        layers.append(_init_linear(rng, dims_pi[-1], self.action_dim, 0.01))
        params["pi"] = layers
        heads = ("q1", "q2") if self.twin_q else ("q1",)
        for head in heads:
            dims_q = [self.obs_dim + self.action_dim, *self.hidden]
            layers = [
                _init_linear(rng, dims_q[i], dims_q[i + 1], np.sqrt(2))
                for i in range(len(dims_q) - 1)
            ]
            layers.append(_init_linear(rng, dims_q[-1], 1, 1.0))
            params[head] = layers
        return params

    # -- numpy path (EnvRunner action selection) --

    def policy_np(self, params: dict, obs: np.ndarray) -> np.ndarray:
        raw = ActorCriticModule._mlp_np(params["pi"], obs)
        return np.tanh(raw) * self.action_bound

    # -- jax path (Learner) --

    def policy(self, params, obs):
        import jax.numpy as jnp

        return jnp.tanh(_mlp_jax(params["pi"], obs)) * self.action_bound

    def q_value(self, params, obs, actions, head: str = "q1"):
        import jax.numpy as jnp

        x = jnp.concatenate([obs, actions], axis=-1)
        return _mlp_jax(params[head], x)[:, 0]


def _gru_init(rng: np.random.Generator, n_in: int, hidden: int) -> dict:
    """GRU cell params: fused r/z/n gates ([n_in,3H] + [H,3H] + [3H])."""
    scale_x = np.sqrt(1.0 / n_in)
    scale_h = np.sqrt(1.0 / hidden)
    return {
        "wx": (rng.standard_normal((n_in, 3 * hidden)) * scale_x).astype(np.float32),
        "wh": (rng.standard_normal((hidden, 3 * hidden)) * scale_h).astype(np.float32),
        "b": np.zeros(3 * hidden, np.float32),
    }


def _gru_step(xp, cell, x, h):
    """One GRU step in either numpy or jax (xp = np | jnp). Gate order
    r, z, n; h' = (1-z)*n + z*h (Cho et al. 2014, the torch convention the
    reference's recurrent_net.py wraps)."""
    H = h.shape[-1]
    gx = x @ cell["wx"] + cell["b"]
    gh = h @ cell["wh"]
    r = 1.0 / (1.0 + xp.exp(-(gx[..., :H] + gh[..., :H])))
    z = 1.0 / (1.0 + xp.exp(-(gx[..., H:2 * H] + gh[..., H:2 * H])))
    n = xp.tanh(gx[..., 2 * H:] + r * gh[..., 2 * H:])
    return (1.0 - z) * n + z * h


class RecurrentQModule:
    """GRU Q-network for partially observable envs — the R2D2 model
    (reference: rllib/models/torch/recurrent_net.py LSTMWrapper;
    rllib_contrib/r2d2 uses it over the DQN head). Encoder MLP -> GRU ->
    Q head. Two paths over the same params:

      * `step_np` — one timestep, numpy, carrying explicit state
        (EnvRunner rollouts; the runner owns per-env state rows).
      * `forward_seq` — jax `lax.scan` over [B, T] sequences with
        start-of-episode state resets, used inside the jitted learner
        update (compiler-friendly: one scan, static shapes).
    """

    is_recurrent = True

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64,), rnn_hidden: int = 64):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)
        self.rnn_hidden = rnn_hidden

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        dims = [self.obs_dim, *self.hidden]
        enc = [
            _init_linear(rng, dims[i], dims[i + 1], np.sqrt(2))
            for i in range(len(dims) - 1)
        ]
        return {
            "enc": enc,
            "gru": _gru_init(rng, dims[-1], self.rnn_hidden),
            "q": [_init_linear(rng, self.rnn_hidden, self.num_actions, 0.01)],
        }

    def initial_state(self, batch_size: int) -> np.ndarray:
        return np.zeros((batch_size, self.rnn_hidden), np.float32)

    def _encode_np(self, params, obs):
        h = obs
        for layer in params["enc"]:
            h = np.tanh(h @ layer["w"] + layer["b"])
        return h

    def step_np(self, params, obs: np.ndarray, state: np.ndarray):
        """(q [B, A], next_state [B, H]) — one rollout timestep."""
        x = self._encode_np(params, obs)
        h = _gru_step(np, params["gru"], x, state)
        head = params["q"][0]
        return h @ head["w"] + head["b"], h

    # EnvRunner's epsilon-greedy branch calls forward_np; for a recurrent
    # module the runner routes through step_np instead (state threading).

    def forward_seq(self, params, obs, state0, resets):
        """jax: obs [B, T, D], state0 [B, H], resets [B, T] (True = zero the
        state BEFORE consuming step t, i.e. t starts a new episode) ->
        (q [B, T, A], final_state [B, H])."""
        import jax
        import jax.numpy as jnp

        def encode(x):
            for layer in params["enc"]:
                x = jnp.tanh(x @ layer["w"] + layer["b"])
            return x

        x_seq = encode(obs)                      # [B, T, hidden[-1]]

        def scan_step(h, inputs):
            x_t, reset_t = inputs
            h = jnp.where(reset_t[:, None], 0.0, h)
            h = _gru_step(jnp, params["gru"], x_t, h)
            return h, h

        xs = (jnp.swapaxes(x_seq, 0, 1), jnp.swapaxes(resets, 0, 1))
        h_final, h_seq = jax.lax.scan(scan_step, state0, xs)
        h_seq = jnp.swapaxes(h_seq, 0, 1)        # [B, T, H]
        head = params["q"][0]
        return h_seq @ head["w"] + head["b"], h_final


def _conv2d_np(x: np.ndarray, w: np.ndarray, b: np.ndarray) -> np.ndarray:
    """SAME-padded 3x3 conv, NHWC, via im2col — the EnvRunner numpy path
    for conv policies (rollout batches are small; matmul via BLAS)."""
    B, H, W, C = x.shape
    kh, kw, _, F = w.shape
    xp = np.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    cols = np.empty((B, H, W, kh * kw * C), x.dtype)
    k = 0
    for dy in range(kh):
        for dx in range(kw):
            cols[..., k * C:(k + 1) * C] = xp[:, dy:dy + H, dx:dx + W, :]
            k += 1
    return cols.reshape(B * H * W, -1) @ w.reshape(-1, F) + b


class ConvActorCriticModule:
    """Conv policy/value net for frame-observation envs (the Atari-class
    workload; reference: rllib VisionNetwork models/catalog defaults for
    image spaces). Obs arrive FLATTENED from the runner ([B, H*W*C]); the
    module owns the reshape. Trunk: two SAME 3x3 convs (relu) -> flatten
    -> dense(128, tanh); separate pi/vf heads. The jax path uses
    lax.conv_general_dilated NHWC (MXU-friendly layout on TPU)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 frame_shape: Sequence[int] = (10, 10, 4),
                 channels: Sequence[int] = (16, 32), hidden: int = 128):
        H, W, C = frame_shape
        if H * W * C != obs_dim:
            raise ValueError(f"frame_shape {frame_shape} != obs_dim {obs_dim}")
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.frame_shape = tuple(frame_shape)
        self.channels = tuple(channels)
        self.hidden = hidden
        # rollout-inference jit cache (lazy; never pickled with the module
        # factory — runners build their module in-process). _jit_ok caches
        # the use-jax-or-not decision so the numpy fallback never re-probes.
        self._jit_fwd = None
        self._dev_params = None
        self._jit_ok: bool | None = None

    def init(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        H, W, C = self.frame_shape
        params: dict = {"conv": []}
        c_in = C
        for c_out in self.channels:
            fan_in = 9 * c_in
            params["conv"].append({
                "w": (rng.standard_normal((3, 3, c_in, c_out)) *
                      np.sqrt(2.0 / fan_in)).astype(np.float32),
                "b": np.zeros(c_out, np.float32),
            })
            c_in = c_out
        flat = H * W * c_in
        params["trunk"] = [_init_linear(rng, flat, self.hidden, np.sqrt(2))]
        params["pi"] = [_init_linear(rng, self.hidden, self.num_actions, 0.01)]
        params["vf"] = [_init_linear(rng, self.hidden, 1, 1.0)]
        return params

    # -- numpy path (EnvRunner rollouts) --

    def _trunk_np(self, params: dict, obs: np.ndarray) -> np.ndarray:
        B = obs.shape[0]
        x = obs.reshape(B, *self.frame_shape)
        for layer in params["conv"]:
            x = _conv2d_np(x, layer["w"], layer["b"])
            x = np.maximum(x, 0.0).reshape(B, *self.frame_shape[:2], -1)
        h = x.reshape(B, -1)
        t = params["trunk"][0]
        return np.tanh(h @ t["w"] + t["b"])

    def forward_np(self, params: dict, obs: np.ndarray):
        """Rollout inference through a CPU-jitted forward: XLA's fused
        conv stack is ~10x the interpreted im2col path, which made conv
        rollouts the EnvRunner bottleneck (2.1k steps/s vs 33k for the
        MLP). The computation is pinned to the host CPU device so runner
        processes never touch the learner's TPU; params transfer once per
        weight broadcast (cached by identity), not per step. Falls back to
        the numpy im2col path wherever jax cannot be used safely (see
        _jit_usable)."""
        if self._jit_ok or (self._jit_ok is None and self._jit_usable()):
            return self._forward_jit(params, obs)
        h = self._trunk_np(params, obs)
        pi, vf = params["pi"][0], params["vf"][0]
        return h @ pi["w"] + pi["b"], (h @ vf["w"] + vf["b"])[:, 0]

    def _jit_usable(self) -> bool:
        """Decide ONCE whether this process may run the jitted path.

        Initializing jax backends is not free of side effects: on a TPU
        host, accelerator discovery can hang on a stalled tunnel or
        exclusively seize the learner's chip (libtpu is single-process) —
        and merely having `jax` in sys.modules proves nothing, because
        the image's sitecustomize imports jax into EVERY process without
        initializing backends. Policy, decided once per module:

          * backends already initialized in this process (the learner, a
            prior jax task) -> safe: `jax.devices("cpu")` reads a cache.
          * backends uninitialized but the platform config is CPU-only
            -> safe: init cannot probe an accelerator.
          * backends uninitialized in a ray_tpu WORKER process (rollout
            actor) -> pin the process to the CPU backend first; rollout
            actors never legitimately need the TPU.
          * anything else (fresh driver/plain process with accelerator
            platforms configured) -> numpy fallback; a rollout must not
            be what initializes TPU backends.
        """
        try:
            import jax
            from jax._src import xla_bridge

            initialized = bool(getattr(xla_bridge, "_backends", None))
            if not initialized:
                plat = jax.config.jax_platforms or ""
                cpu_only = plat and set(plat.split(",")) <= {"cpu"}
                if not cpu_only:
                    from ray_tpu._private import worker as _worker_mod

                    gw = _worker_mod._global_worker
                    if gw is None or gw.mode != "worker":
                        self._jit_ok = False
                        return False
                    jax.config.update("jax_platforms", "cpu")
            self._jit_fwd = (jax.jit(self.forward), jax.devices("cpu")[0])
            self._jit_ok = True
        except Exception:  # noqa: BLE001 — any jax trouble -> numpy path
            self._jit_ok = False
        return self._jit_ok

    def _forward_jit(self, params: dict, obs: np.ndarray):
        import jax

        fwd, cpu = self._jit_fwd
        if self._dev_params is None or self._dev_params[0] is not params:
            dev = jax.tree_util.tree_map(
                lambda x: jax.device_put(np.asarray(x), cpu), params)
            self._dev_params = (params, dev)
        logits, values = fwd(self._dev_params[1],
                             jax.device_put(np.asarray(obs), cpu))
        return np.asarray(logits), np.asarray(values)

    sample_actions_np = ActorCriticModule.sample_actions_np

    # -- jax path (Learner) --

    def forward(self, params, obs):
        import jax
        import jax.numpy as jnp

        B = obs.shape[0]
        x = obs.reshape(B, *self.frame_shape)
        for layer in params["conv"]:
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(1, 1), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ) + layer["b"]
            x = jax.nn.relu(x)
        h = x.reshape(B, -1)
        t = params["trunk"][0]
        h = jnp.tanh(h @ t["w"] + t["b"])
        pi, vf = params["pi"][0], params["vf"][0]
        return h @ pi["w"] + pi["b"], (h @ vf["w"] + vf["b"])[:, 0]
