"""Connectors — composable observation/action transform pipelines.

Equivalent of the reference's connector framework (reference:
rllib/connectors/connector.py — env-to-module pipelines transforming
observations before action computation, with per-worker state carried in
checkpoints). Connectors run INSIDE EnvRunner actors on the numpy path: the
batch the learner sees already holds processed observations, so the jitted
loss never re-does preprocessing (keeps the device graph pure compute).

Stateful connectors (NormalizeObs running stats, FrameStack buffers) are
per-runner, like the reference's per-worker connector state; their state
rides EnvRunner.get_state() for checkpoint/restore.
"""
from __future__ import annotations

import numpy as np


class Connector:
    """One observation transform step: [E, D_in] -> [E, D_out]."""

    def output_dim(self, in_dim: int) -> int:
        return in_dim

    def setup(self, num_envs: int, in_dim: int) -> None:
        pass

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def peek(self, obs: np.ndarray) -> np.ndarray:
        """Transform WITHOUT advancing internal state (used for bootstrap
        values on true-final observations)."""
        return self(obs)

    def on_dones(self, dones: np.ndarray) -> None:
        """Episode boundaries: reset per-env state where dones[i]."""

    def state(self) -> dict:
        return {}

    def load_state(self, state: dict) -> None:
        pass


class FlattenObs(Connector):
    """Flatten trailing observation dims (already-flat obs pass through)."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        return obs.reshape(obs.shape[0], -1)


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = float(low), float(high)

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        return np.clip(obs, self.low, self.high)


class NormalizeObs(Connector):
    """Running mean/std normalization (Welford; the reference's
    MeanStdFilter connector)."""

    def __init__(self, eps: float = 1e-8, clip: float = 10.0):
        self.eps = eps
        self.clip = clip
        self._count = 0.0
        self._mean: np.ndarray | None = None
        self._m2: np.ndarray | None = None

    def setup(self, num_envs: int, in_dim: int) -> None:
        if self._mean is None:
            self._mean = np.zeros(in_dim, np.float64)
            self._m2 = np.zeros(in_dim, np.float64)

    def _update(self, obs: np.ndarray) -> None:
        for row in obs:
            self._count += 1.0
            delta = row - self._mean
            self._mean += delta / self._count
            self._m2 += delta * (row - self._mean)

    def _apply(self, obs: np.ndarray) -> np.ndarray:
        if self._count < 2:
            return obs.astype(np.float32)
        var = self._m2 / (self._count - 1)
        out = (obs - self._mean) / np.sqrt(var + self.eps)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        self._update(obs)
        return self._apply(obs)

    def peek(self, obs: np.ndarray) -> np.ndarray:
        return self._apply(obs)

    def state(self) -> dict:
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def load_state(self, state: dict) -> None:
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class FrameStack(Connector):
    """Stack the last k observations per env (zero-padded at episode start;
    buffers cleared at episode boundaries)."""

    def __init__(self, k: int = 4):
        assert k >= 1
        self.k = k
        self._buf: np.ndarray | None = None  # [E, k, D]

    def output_dim(self, in_dim: int) -> int:
        return in_dim * self.k

    def setup(self, num_envs: int, in_dim: int) -> None:
        self._buf = np.zeros((num_envs, self.k, in_dim), np.float32)

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        self._buf = np.roll(self._buf, -1, axis=1)
        self._buf[:, -1] = obs
        return self._buf.reshape(obs.shape[0], -1)

    def peek(self, obs: np.ndarray) -> np.ndarray:
        buf = np.roll(self._buf, -1, axis=1)
        buf[:, -1] = obs
        return buf.reshape(obs.shape[0], -1)

    def on_dones(self, dones: np.ndarray) -> None:
        self._buf[dones] = 0.0

    def state(self) -> dict:
        return {"buf": self._buf}

    def load_state(self, state: dict) -> None:
        self._buf = state["buf"]


class ConnectorPipeline:
    """Ordered connector chain; the EnvRunner owns one."""

    def __init__(self, connectors: list[Connector] | None = None):
        self.connectors = list(connectors or [])

    def setup(self, num_envs: int, in_dim: int) -> int:
        dim = in_dim
        for c in self.connectors:
            c.setup(num_envs, dim)
            dim = c.output_dim(dim)
        return dim

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            obs = c(obs)
        return obs

    def peek(self, obs: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            obs = c.peek(obs)
        return obs

    def on_dones(self, dones: np.ndarray) -> None:
        for c in self.connectors:
            c.on_dones(dones)

    def state(self) -> list:
        return [c.state() for c in self.connectors]

    def load_state(self, state: list) -> None:
        for c, s in zip(self.connectors, state):
            c.load_state(s)
