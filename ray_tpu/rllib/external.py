"""External-env serving: PolicyClient / PolicyServerInput.

Equivalent of the reference's external-application pattern
(reference: rllib/env/policy_client.py:1, rllib/env/policy_server_input.py:1
— an external simulator process drives episodes over the network; the
algorithm trains on the streamed experience). The reference speaks HTTP +
pickled payloads; here the wire is newline-delimited JSON over TCP so a
client needs nothing but a socket — any language, no framework install.

Server-side ("remote") inference only: the server runs the current policy
for every `get_action`, so clients never hold weights and exploration
state (epsilon) stays consistent with the trainer.

`PolicyServerInput` duck-types the EnvRunner surface (`env_info`,
`set_weights`, `sample`, `get_state`, `set_state`), so the Algorithm
driver loop is unchanged — configure with
`config.external_env(port, obs_dim, num_actions)` and episodes arrive
from outside instead of from an in-process VectorEnv.
"""
from __future__ import annotations

import json
import socket
import threading
import uuid

import numpy as np


class PolicyClient:
    """Client for an external env loop (reference: policy_client.py API —
    start_episode / get_action / log_returns / end_episode)."""

    def __init__(self, address: str, timeout_s: float = 60.0):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout_s)
        self._file = self._sock.makefile("rwb")

    def _call(self, payload: dict) -> dict:
        self._file.write(json.dumps(payload).encode() + b"\n")
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("policy server closed the connection")
        resp = json.loads(line)
        if "error" in resp:
            raise RuntimeError(f"policy server error: {resp['error']}")
        return resp

    def start_episode(self) -> str:
        return self._call({"cmd": "start_episode"})["episode_id"]

    def get_action(self, episode_id: str, obs) -> int:
        resp = self._call({
            "cmd": "get_action", "episode_id": episode_id,
            "obs": np.asarray(obs, np.float32).reshape(-1).tolist(),
        })
        return resp["action"]

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._call({"cmd": "log_returns", "episode_id": episode_id,
                    "reward": float(reward)})

    def end_episode(self, episode_id: str, obs) -> None:
        self._call({
            "cmd": "end_episode", "episode_id": episode_id,
            "obs": np.asarray(obs, np.float32).reshape(-1).tolist(),
        })

    def close(self) -> None:
        try:
            self._file.close()
            self._sock.close()
        except OSError:
            pass


class _Episode:
    __slots__ = ("pending_obs", "pending_action", "pending_extra",
                 "reward_acc", "total", "length", "rows")

    def __init__(self):
        self.pending_obs = None
        self.pending_action = None
        self.pending_extra = {}
        self.reward_acc = 0.0
        self.total = 0.0
        self.length = 0
        self.rows: list[dict] = []  # actor_critic: flushed at episode end


class PolicyServerInput:
    """TCP server collecting external-env experience; EnvRunner-shaped.

    Modes mirror EnvRunner's: `epsilon_greedy` (DQN family — Q argmax with
    annealed exploration) and `actor_critic` (PPO family — categorical
    sampling with logp/value records). Transitions complete when the NEXT
    observation arrives (get_action or end_episode), identical to how the
    reference's server buffers `SampleBatch` rows.
    """

    def __init__(self, port: int, obs_dim: int, num_actions: int,
                 module_factory, rollout_length: int = 64,
                 mode: str = "epsilon_greedy", host: str = "127.0.0.1",
                 seed: int = 0):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.rollout_length = rollout_length
        self.mode = mode
        self.module = module_factory(obs_dim, num_actions)
        if getattr(self.module, "is_recurrent", False):
            raise ValueError(
                "PolicyServerInput does not support recurrent modules: "
                "per-episode hidden state threading + stored-state replay "
                "keys (state_in/resets) are not plumbed through the wire "
                "protocol. Use an in-process EnvRunner for R2D2-family "
                "algorithms.")
        self.epsilon = 1.0
        self._params = None
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Condition()
        self._episodes: dict[str, _Episode] = {}
        self._transitions: list[dict] = []
        self._returns: list[float] = []
        self._lengths: list[int] = []
        self._closed = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="policy-server-accept", daemon=True)
        self._accept_thread.start()

    # -- EnvRunner surface --

    def env_info(self) -> dict:
        return {
            "observation_dim": self.obs_dim,
            "num_actions": self.num_actions,
            "continuous": False,
            "action_dim": 0,
            "action_bound": 1.0,
        }

    def set_weights(self, params, epsilon: float | None = None) -> None:
        with self._lock:
            self._params = params
            if epsilon is not None:
                self.epsilon = epsilon

    def get_state(self) -> dict:
        return {"epsilon": self.epsilon}

    def set_state(self, state: dict) -> None:
        self.epsilon = state["epsilon"]

    def sample(self, timeout_s: float = 300.0) -> dict:
        """Block until one rollout's worth of external transitions arrived;
        shape them [T, E=1] exactly like EnvRunner.sample()."""
        T = self.rollout_length
        with self._lock:
            if not self._lock.wait_for(
                    lambda: len(self._transitions) >= T or self._closed,
                    timeout=timeout_s):
                raise TimeoutError(
                    f"no external experience: {len(self._transitions)}/{T} "
                    f"transitions after {timeout_s}s — is a PolicyClient "
                    "loop running?")
            rows, self._transitions = (self._transitions[:T],
                                       self._transitions[T:])
            returns, self._returns = self._returns, []
            lengths, self._lengths = self._lengths, []
        batch = {
            "obs": np.stack([r["obs"] for r in rows])[:, None, :],
            "actions": np.asarray([r["action"] for r in rows],
                                  np.int32)[:, None],
            "rewards": np.asarray([r["reward"] for r in rows],
                                  np.float32)[:, None],
            "dones": np.asarray([r["done"] for r in rows], np.bool_)[:, None],
            "terminateds": np.asarray([r["done"] for r in rows],
                                      np.bool_)[:, None],
            "episode_returns": np.asarray(returns, np.float32),
            "episode_lengths": np.asarray(lengths, np.int64),
        }
        if self.mode == "actor_critic":
            batch["logp"] = np.asarray([r["logp"] for r in rows],
                                       np.float32)[:, None]
            batch["values"] = np.asarray([r["value"] for r in rows],
                                         np.float32)[:, None]
            boot = np.asarray([r["bootstrap_value"] for r in rows],
                              np.float32)[:, None]
            batch["bootstrap_values"] = boot
            with self._lock:
                params = self._params
            # V of the stream's next pending obs (or 0 if at a boundary)
            nxt = rows[-1].get("next_obs")
            if rows[-1]["done"] or nxt is None:
                batch["last_values"] = np.zeros(1, np.float32)
            else:
                _, v = self.module.forward_np(params, nxt[None, :])
                batch["last_values"] = v.astype(np.float32)
        else:
            batch["next_obs"] = np.stack(
                [r["next_obs"] for r in rows])[:, None, :]
        return batch

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()
        try:
            self._srv.close()
        except OSError:
            pass

    # -- wire handling --

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_client, args=(conn,),
                             name="policy-server-conn", daemon=True).start()

    def _serve_client(self, conn: socket.socket) -> None:
        file = conn.makefile("rwb")
        try:
            for line in file:
                try:
                    resp = self._handle(json.loads(line))
                except Exception as exc:  # noqa: BLE001 — report to client
                    resp = {"error": f"{type(exc).__name__}: {exc}"}
                file.write(json.dumps(resp).encode() + b"\n")
                file.flush()
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, msg: dict) -> dict:
        cmd = msg.get("cmd")
        if cmd == "start_episode":
            eid = uuid.uuid4().hex[:12]
            with self._lock:
                self._episodes[eid] = _Episode()
            return {"episode_id": eid}
        eid = msg.get("episode_id")
        with self._lock:
            ep = self._episodes.get(eid)
            if ep is None:
                return {"error": f"unknown episode_id {eid!r}"}
            if cmd == "log_returns":
                ep.reward_acc += msg["reward"]
                ep.total += msg["reward"]
                return {"ok": True}
            obs = np.asarray(msg["obs"], np.float32)
            if obs.shape != (self.obs_dim,):
                return {"error": f"obs shape {obs.shape} != ({self.obs_dim},)"}
            if cmd == "end_episode":
                self._complete_pending(ep, obs, done=True)
                # actor_critic rows flush per-episode so concurrent
                # clients' episodes stay temporally contiguous in the
                # stream (GAE walks adjacent rows)
                self._transitions.extend(ep.rows)
                self._returns.append(ep.total)
                self._lengths.append(ep.length)
                del self._episodes[eid]
                self._lock.notify_all()
                return {"ok": True}
            if cmd != "get_action":
                return {"error": f"unknown cmd {cmd!r}"}
            self._complete_pending(ep, obs, done=False)
            params, epsilon = self._params, self.epsilon
        # inference OUTSIDE the lock: a slow forward must not serialize
        # other clients or block the trainer's sample()/set_weights
        action, extra = self._infer(params, epsilon, obs)
        with self._lock:
            ep.pending_obs = obs
            ep.pending_action = action
            ep.pending_extra = extra
            ep.length += 1
        return {"action": action}

    def _infer(self, params, epsilon: float, obs: np.ndarray):
        """Action + per-step extras under a weight snapshot (no lock)."""
        if params is None:
            return int(self._rng.integers(self.num_actions)), {}
        if self.mode == "actor_critic":
            actions, logp, values = self.module.sample_actions_np(
                params, obs[None, :], self._rng)
            return int(actions[0]), {"logp": float(logp[0]),
                                     "value": float(values[0])}
        q = self.module.forward_np(params, obs[None, :])
        if self._rng.uniform() < epsilon:
            return int(self._rng.integers(self.num_actions)), {}
        return int(np.argmax(q[0])), {}

    def _complete_pending(self, ep: _Episode, next_obs: np.ndarray,
                          done: bool) -> None:
        """The transition for the PREVIOUS action completes now that its
        successor observation arrived (lock held)."""
        if ep.pending_obs is None:
            return
        row = {
            "obs": ep.pending_obs,
            "action": ep.pending_action,
            "reward": ep.reward_acc,
            "next_obs": next_obs,
            "done": done,
        }
        if self.mode == "actor_critic":
            row["logp"] = ep.pending_extra.get("logp", 0.0)
            row["value"] = ep.pending_extra.get("value", 0.0)
            boot = 0.0
            if done and self._params is not None:
                # external ends are treated as termination; the value of
                # the final obs still rides along for GAE truncation use
                _, v = self.module.forward_np(self._params, next_obs[None, :])
                boot = float(v[0])
            row["bootstrap_value"] = boot
        ep.reward_acc = 0.0
        ep.pending_obs = None
        if self.mode == "actor_critic":
            # held until end_episode so multi-client episodes don't
            # interleave mid-episode in the advantage stream
            ep.rows.append(row)
        else:
            self._transitions.append(row)
            self._lock.notify_all()
