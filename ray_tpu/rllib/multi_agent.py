"""Multi-agent environments and training.

Equivalent of the reference's multi-agent stack (reference:
rllib/env/multi_agent_env.py:30 MultiAgentEnv — dict-keyed obs/action/reward
spaces; policy mapping via config.multi_agent(policies=...,
policy_mapping_fn=...) in algorithm_config.py; per-policy batches in
rllib/evaluation/sample_batch_builder.py MultiAgentSampleBatchBuilder).

TPU mapping: one jitted Learner PER POLICY (separate param pytrees, separate
optimizers — the reference likewise keeps one optimizer per policy), rollout
collection on CPU actors with per-policy [T, E*|agents|] static-shape
batches so every learner update is jit-stable.

Protocol simplifications vs the reference (documented, deliberate):
- every agent observes and acts at EVERY step (no agents appearing or
  disappearing mid-episode) — this is what keeps learner batch shapes
  static for XLA;
- a done agent's sub-episode auto-resets in place (recorded via its done
  flag), so the vectorized runner never blocks on stragglers.
"""
from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import compute_gae, ppo_loss
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import ActorCriticModule


class MultiAgentEnv:
    """Dict-keyed multi-agent env protocol.

    reset(seed) -> {agent_id: obs}
    step({agent_id: action}) -> (obs_d, reward_d, terminated_d, truncated_d)
    where terminated_d/truncated_d carry per-agent flags. Every agent is
    present in every dict, every step.
    """

    agent_ids: List[str]
    observation_dim: int
    num_actions: int

    def reset(self, seed: int | None = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, int]):
        raise NotImplementedError


class IndependentMultiEnv(MultiAgentEnv):
    """N independent copies of a single-agent env presented as one
    multi-agent env (each agent's sub-episode auto-resets on its own) —
    the canonical smoke-test topology for policy mapping."""

    def __init__(self, spec, n_agents: int = 2, seed: int = 0):
        from ray_tpu.rllib.env import make_env

        self.agent_ids = [f"agent_{i}" for i in range(n_agents)]
        self._envs = {a: make_env(spec) for a in self.agent_ids}
        first = self._envs[self.agent_ids[0]]
        self.observation_dim = first.observation_dim
        self.num_actions = first.num_actions
        self._seed = seed

    def reset(self, seed: int | None = None) -> Dict[str, np.ndarray]:
        base = self._seed if seed is None else seed
        return {
            a: env.reset(seed=base + i)
            for i, (a, env) in enumerate(self._envs.items())
        }

    def step(self, actions: Dict[str, int]):
        obs_d, rew_d, term_d, trunc_d = {}, {}, {}, {}
        for a, env in self._envs.items():
            obs, r, term, trunc = env.step(actions[a])
            if term or trunc:
                obs = env.reset()
            obs_d[a], rew_d[a] = obs, r
            term_d[a], trunc_d[a] = term, trunc
        return obs_d, rew_d, term_d, trunc_d


class MultiAgentEnvRunner:
    """Vectorized multi-agent rollouts grouped into per-policy batches."""

    def __init__(self, env_spec, module_factories: Dict[str, Callable],
                 policy_mapping_fn: Callable[[str], str],
                 num_envs: int = 1, rollout_length: int = 64, seed: int = 0):
        from ray_tpu.rllib.env import make_env  # accepts callables too

        def make(spec):
            env = spec() if callable(spec) else make_env(spec)
            assert isinstance(env, MultiAgentEnv), env
            return env

        self.envs = [make(env_spec) for _ in range(num_envs)]
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        probe = self.envs[0]
        self.agent_ids = list(probe.agent_ids)
        self.policy_mapping_fn = policy_mapping_fn
        # policy -> its agents, in a FIXED order (defines batch columns)
        self.policy_agents: Dict[str, List[str]] = {}
        for a in self.agent_ids:
            self.policy_agents.setdefault(policy_mapping_fn(a), []).append(a)
        self.modules = {
            p: module_factories[p](probe.observation_dim, probe.num_actions)
            for p in self.policy_agents
        }
        self.obs_dim = probe.observation_dim
        self.num_actions = probe.num_actions
        self._obs = [
            env.reset(seed=seed + 97 * i) for i, env in enumerate(self.envs)
        ]
        self._rng = np.random.default_rng(seed + 1000)
        self._params: Dict[str, dict] | None = None
        # per-(env, agent) episode accounting
        self._ep_ret = {(i, a): 0.0 for i in range(num_envs)
                        for a in self.agent_ids}
        self.completed_returns: list[float] = []

    def env_info(self) -> dict:
        return {
            "observation_dim": self.obs_dim,
            "num_actions": self.num_actions,
            "policies": {p: list(ags) for p, ags in self.policy_agents.items()},
        }

    def set_weights(self, params_by_policy: Dict[str, dict]) -> None:
        self._params = params_by_policy

    def _stack_obs(self, policy: str) -> np.ndarray:
        """[E * |agents_p|, D] — env-major, agent-minor column order."""
        ags = self.policy_agents[policy]
        return np.stack([self._obs[i][a]
                         for i in range(self.num_envs) for a in ags])

    def sample(self) -> Dict[str, dict]:
        if self._params is None:
            raise RuntimeError("set_weights must be called before sample()")
        T, E = self.rollout_length, self.num_envs
        out: Dict[str, dict] = {}
        for p, ags in self.policy_agents.items():
            C = E * len(ags)
            out[p] = {
                "obs": np.empty((T, C, self.obs_dim), np.float32),
                "actions": np.empty((T, C), np.int32),
                "logp": np.empty((T, C), np.float32),
                "values": np.empty((T, C), np.float32),
                "rewards": np.empty((T, C), np.float32),
                "dones": np.empty((T, C), np.bool_),
                "terminateds": np.empty((T, C), np.bool_),
                "bootstrap_values": np.zeros((T, C), np.float32),
            }
        for t in range(T):
            acts: list[dict] = [dict() for _ in range(E)]
            for p, ags in self.policy_agents.items():
                obs = self._stack_obs(p)
                a, logp, v = self.modules[p].sample_actions_np(
                    self._params[p], obs, self._rng
                )
                b = out[p]
                b["obs"][t], b["actions"][t] = obs, a
                b["logp"][t], b["values"][t] = logp, v
                for c, (i, ag) in enumerate(
                    (i, ag) for i in range(E) for ag in ags
                ):
                    acts[i][ag] = int(a[c])
            results = [env.step(acts[i]) for i, env in enumerate(self.envs)]
            for p, ags in self.policy_agents.items():
                b = out[p]
                for c, (i, ag) in enumerate(
                    (i, ag) for i in range(E) for ag in ags
                ):
                    obs_d, rew_d, term_d, trunc_d = results[i]
                    done = bool(term_d[ag] or trunc_d[ag])
                    b["rewards"][t, c] = rew_d[ag]
                    b["dones"][t, c] = done
                    b["terminateds"][t, c] = term_d[ag]
                    self._ep_ret[(i, ag)] += rew_d[ag]
                    if done:
                        self.completed_returns.append(self._ep_ret[(i, ag)])
                        self._ep_ret[(i, ag)] = 0.0
            # post-step obs (env-side auto-reset already applied) feeds the
            # next action; truncated sub-episodes bootstrap from V(reset
            # obs) — accepted simplification, built-in MA envs terminate
            for i in range(E):
                self._obs[i] = results[i][0]
        for p in self.policy_agents:
            b = out[p]
            _, last_v = self.modules[p].forward_np(
                self._params[p], self._stack_obs(p)
            )
            b["last_values"] = last_v.astype(np.float32)
            rets = self.completed_returns
            b["episode_returns"] = np.asarray(rets, np.float32)
            b["episode_lengths"] = np.zeros(len(rets), np.int64)
        self.completed_returns = []
        return out


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.gae_lambda = 0.95
        self.policies: List[str] = ["default_policy"]
        self.policy_mapping_fn: Callable[[str], str] = (
            lambda agent_id: "default_policy"
        )
        self.algo_class = MultiAgentPPO

    def multi_agent(self, policies: List[str] | None = None,
                    policy_mapping_fn: Callable | None = None
                    ) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = list(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self


class MultiAgentPPO(Algorithm):
    """PPO over per-policy batches: one Learner per policy."""

    def _setup(self) -> None:
        cfg = self.config
        hidden = tuple(cfg.hidden)
        factories = {
            p: (lambda od, na, h=hidden: ActorCriticModule(od, na, h))
            for p in cfg.policies
        }
        if cfg.num_env_runners > 0:
            import ray_tpu

            runner_cls = ray_tpu.remote(num_cpus=1)(MultiAgentEnvRunner)
            self._runners = [
                runner_cls.remote(
                    cfg.env_spec, factories, cfg.policy_mapping_fn,
                    num_envs=cfg.num_envs_per_runner,
                    rollout_length=cfg.rollout_length,
                    seed=cfg.seed + 1 + i,
                )
                for i in range(cfg.num_env_runners)
            ]
            info = ray_tpu.get(self._runners[0].env_info.remote(), timeout=120)
        else:
            self._local_runner = MultiAgentEnvRunner(
                cfg.env_spec, factories, cfg.policy_mapping_fn,
                num_envs=cfg.num_envs_per_runner,
                rollout_length=cfg.rollout_length,
                seed=cfg.seed,
            )
            info = self._local_runner.env_info()
        self.obs_dim = info["observation_dim"]
        self.num_actions = info["num_actions"]
        self._rng = np.random.default_rng(cfg.seed + 7)
        self.learners: Dict[str, Learner] = {}
        for j, p in enumerate(cfg.policies):
            module = ActorCriticModule(self.obs_dim, self.num_actions,
                                       cfg.hidden)
            self.learners[p] = Learner(
                module,
                ppo_loss,
                config={
                    "clip_param": cfg.clip_param,
                    "vf_loss_coeff": cfg.vf_loss_coeff,
                    "entropy_coeff": cfg.entropy_coeff,
                },
                learning_rate=cfg.lr,
                max_grad_norm=cfg.max_grad_norm,
                mesh=cfg.mesh,
                seed=cfg.seed + 31 * j,  # per-policy init (self-play asym.)
            )
        self._broadcast()

    # base-class helpers that assume a single learner
    @property
    def learner(self):  # save_state/load_state compatibility
        class _Multi:
            def __init__(s, learners):
                s._l = learners

            def state(s):
                return {p: l.state() for p, l in s._l.items()}

            def load_state(s, st):
                for p, l in s._l.items():
                    l.load_state(st[p])

        return _Multi(self.learners)

    def _broadcast(self) -> None:
        w = {p: l.get_weights_np() for p, l in self.learners.items()}
        if self._local_runner is not None:
            self._local_runner.set_weights(w)
        else:
            import ray_tpu

            ray_tpu.get([r.set_weights.remote(w) for r in self._runners],
                        timeout=120)

    def _sample_ma(self) -> List[Dict[str, dict]]:
        if self._local_runner is not None:
            samples = [self._local_runner.sample()]
        else:
            import ray_tpu

            samples = ray_tpu.get([r.sample.remote() for r in self._runners],
                                  timeout=300)
        for s in samples:
            first = next(iter(s.values()))
            self._recent_returns.extend(first["episode_returns"].tolist())
            self._recent_returns = self._recent_returns[-100:]
            self._total_env_steps += first["rewards"].size
        return samples

    def training_step(self) -> dict:
        cfg = self.config
        samples = self._sample_ma()
        metrics: dict = {}
        for p, learner in self.learners.items():
            flat = {"obs": [], "actions": [], "logp_old": [],
                    "advantages": [], "value_targets": []}
            for s in samples:
                b = s[p]
                adv, ret = compute_gae(b, cfg.gamma, cfg.gae_lambda)
                T, C = b["rewards"].shape
                flat["obs"].append(b["obs"].reshape(T * C, -1))
                flat["actions"].append(b["actions"].reshape(-1))
                flat["logp_old"].append(b["logp"].reshape(-1))
                flat["advantages"].append(adv.reshape(-1))
                flat["value_targets"].append(ret.reshape(-1))
            train = {k: np.concatenate(v) for k, v in flat.items()}
            a = train["advantages"]
            train["advantages"] = (a - a.mean()) / (a.std() + 1e-8)
            n = len(train["actions"])
            mb = min(cfg.minibatch_size, n)
            acc: dict[str, list[float]] = {}
            for _ in range(cfg.num_epochs):
                perm = self._rng.permutation(n)
                for start in range(0, n - mb + 1, mb):
                    idx = perm[start:start + mb]
                    m = learner.update({k: v[idx] for k, v in train.items()})
                    for k, v in m.items():
                        acc.setdefault(k, []).append(v)
            for k, v in acc.items():
                metrics[f"{p}/{k}"] = float(np.mean(v))
        self._broadcast()
        return metrics
