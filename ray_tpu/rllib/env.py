"""RL environment layer: Env protocol, vectorization, built-in envs.

Equivalent of the reference's env layer (reference: rllib/env/env_runner.py:9
EnvRunner protocol, rllib/env/ vector/external envs; gymnasium is the
reference's env API). Envs here are plain-Python with numpy observations —
env stepping stays on CPU actors by design (SURVEY.md §3.5: "EnvRunners stay
CPU actors"); only the learner touches the device mesh.

A gymnasium env can be wrapped with GymEnv when the package is available,
but the built-ins avoid the dependency entirely.
"""
from __future__ import annotations

import numpy as np


class Env:
    """Single-agent episodic env protocol (gymnasium-shaped).

    reset(seed) -> obs ; step(action) -> (obs, reward, terminated, truncated).
    Discrete envs take an int action (num_actions); continuous envs set
    `continuous = True` and take a float array of `action_dim` values in
    [-action_bound, action_bound].
    """

    observation_dim: int
    num_actions: int = 0
    max_episode_steps: int = 1000
    continuous: bool = False
    action_dim: int = 0
    action_bound: float = 1.0

    def reset(self, seed: int | None = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action):
        raise NotImplementedError

    def close(self) -> None:
        """Release simulator resources (no-op for the built-ins)."""


class CartPole(Env):
    """Classic cart-pole balancing (standard physics; reference uses
    gymnasium's CartPole-v1 throughout its tuned examples)."""

    observation_dim = 4
    num_actions = 2
    max_episode_steps = 500

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    X_LIMIT = 2.4
    THETA_LIMIT = 12 * np.pi / 180

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._state = np.zeros(4, np.float32)
        self._steps = 0

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4).astype(np.float32)
        self._steps = 0
        return self._state.copy()

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + pole_ml * theta_dot**2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0 - self.POLE_MASS * cos_t**2 / total_mass)
        )
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x = x + self.DT * x_dot
        x_dot = x_dot + self.DT * x_acc
        theta = theta + self.DT * theta_dot
        theta_dot = theta_dot + self.DT * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._steps += 1
        terminated = bool(
            abs(x) > self.X_LIMIT or abs(theta) > self.THETA_LIMIT
        )
        truncated = self._steps >= self.max_episode_steps
        return self._state.copy(), 1.0, terminated, truncated


class Corridor(Env):
    """Deterministic N-cell corridor: start left, +1 at the right end,
    small step penalty (the reference's SimpleCorridor custom-env example)."""

    num_actions = 2  # 0 = left, 1 = right
    observation_dim = 1

    def __init__(self, length: int = 5):
        self.length = length
        self.max_episode_steps = 4 * length
        self._pos = 0
        self._steps = 0

    def reset(self, seed: int | None = None) -> np.ndarray:
        self._pos = 0
        self._steps = 0
        return np.array([self._pos], np.float32)

    def step(self, action: int):
        self._pos = max(0, self._pos + (1 if action == 1 else -1))
        self._steps += 1
        done = self._pos >= self.length - 1
        reward = 1.0 if done else -0.05
        truncated = self._steps >= self.max_episode_steps
        return np.array([self._pos], np.float32), reward, done, truncated


class Pendulum(Env):
    """Classic underactuated pendulum swing-up (standard dynamics; the
    reference's tuned continuous-control examples use gymnasium's
    Pendulum-v1). obs = [cos th, sin th, th_dot]; reward penalizes angle,
    velocity, and torque; episodes truncate at 200 steps."""

    observation_dim = 3
    continuous = True
    action_dim = 1
    action_bound = 2.0
    max_episode_steps = 200

    G = 10.0
    MASS = 1.0
    LENGTH = 1.0
    DT = 0.05
    MAX_SPEED = 8.0

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._th = 0.0
        self._th_dot = 0.0
        self._steps = 0

    def _obs(self) -> np.ndarray:
        return np.array(
            [np.cos(self._th), np.sin(self._th), self._th_dot], np.float32
        )

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._th_dot = self._rng.uniform(-1.0, 1.0)
        self._steps = 0
        return self._obs()

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.action_bound, self.action_bound))
        th, th_dot = self._th, self._th_dot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th**2 + 0.1 * th_dot**2 + 0.001 * u**2
        th_dot = th_dot + (
            3 * self.G / (2 * self.LENGTH) * np.sin(th)
            + 3.0 / (self.MASS * self.LENGTH**2) * u
        ) * self.DT
        th_dot = float(np.clip(th_dot, -self.MAX_SPEED, self.MAX_SPEED))
        th = th + th_dot * self.DT
        self._th, self._th_dot = th, th_dot
        self._steps += 1
        truncated = self._steps >= self.max_episode_steps
        return self._obs(), -cost, False, truncated


class GymEnv(Env):
    """Adapter for a gymnasium env (discrete action space)."""

    def __init__(self, env_id: str, **kwargs):
        import gymnasium as gym

        self._env = gym.make(env_id, **kwargs)
        self.observation_dim = int(np.prod(self._env.observation_space.shape))
        self.num_actions = int(self._env.action_space.n)
        self.max_episode_steps = getattr(
            self._env.spec, "max_episode_steps", None
        ) or 1000

    def reset(self, seed: int | None = None) -> np.ndarray:
        obs, _ = self._env.reset(seed=seed)
        return np.asarray(obs, np.float32).reshape(-1)

    def step(self, action: int):
        obs, reward, terminated, truncated, _ = self._env.step(int(action))
        return (
            np.asarray(obs, np.float32).reshape(-1),
            float(reward),
            bool(terminated),
            bool(truncated),
        )

    def close(self) -> None:
        self._env.close()


_REGISTRY: dict[str, type] = {
    "CartPole-v1": CartPole,
    "Corridor": Corridor,
    "Pendulum-v1": Pendulum,
}


def register_env(name: str, creator) -> None:
    """Register a custom env constructor (reference: ray.tune.register_env)."""
    _REGISTRY[name] = creator


def make_env(spec) -> Env:
    """spec: registered name, Env subclass, or zero-arg callable."""
    if isinstance(spec, str):
        if spec in _REGISTRY:
            return _REGISTRY[spec]()
        return GymEnv(spec)
    if isinstance(spec, type) and issubclass(spec, Env):
        return spec()
    if callable(spec):
        return spec()
    raise TypeError(f"cannot build env from {spec!r}")


class VectorEnv:
    """Synchronous vector of N env copies with auto-reset on episode end."""

    def __init__(self, spec, num_envs: int, base_seed: int = 0):
        self.envs = [make_env(spec) for _ in range(num_envs)]
        self.num_envs = num_envs
        self.observation_dim = self.envs[0].observation_dim
        self.num_actions = self.envs[0].num_actions
        self.continuous = self.envs[0].continuous
        self.action_dim = self.envs[0].action_dim
        self.action_bound = self.envs[0].action_bound
        self._episode_return = np.zeros(num_envs, np.float64)
        self._episode_len = np.zeros(num_envs, np.int64)
        self.completed_returns: list[float] = []
        self.completed_lengths: list[int] = []
        self._obs = np.stack(
            [e.reset(seed=base_seed + i) for i, e in enumerate(self.envs)]
        )

    @property
    def obs(self) -> np.ndarray:
        return self._obs

    def step(self, actions: np.ndarray):
        """Returns (true_next_obs, rewards, dones[terminated|truncated],
        terminateds). Finished envs auto-reset internally — `self.obs` then
        holds the RESET obs for the next action selection, while the
        returned array holds the TRUE final obs, so TD/GAE targets at
        truncation boundaries bootstrap from the real successor state."""
        true_next, cur_obs, rewards, dones, terms = [], [], [], [], []
        for i, (env, a) in enumerate(zip(self.envs, actions)):
            obs, r, terminated, truncated = env.step(
                a if self.continuous else int(a))
            self._episode_return[i] += r
            self._episode_len[i] += 1
            done = terminated or truncated
            true_next.append(obs)
            if done:
                self.completed_returns.append(float(self._episode_return[i]))
                self.completed_lengths.append(int(self._episode_len[i]))
                self._episode_return[i] = 0.0
                self._episode_len[i] = 0
                obs = env.reset()
            cur_obs.append(obs)
            rewards.append(r)
            dones.append(done)
            terms.append(terminated)
        self._obs = np.stack(cur_obs)
        return (
            np.stack(true_next),
            np.asarray(rewards, np.float32),
            np.asarray(dones, np.bool_),
            np.asarray(terms, np.bool_),
        )

    def pop_episode_stats(self) -> tuple[list[float], list[int]]:
        r, l = self.completed_returns, self.completed_lengths
        self.completed_returns, self.completed_lengths = [], []
        return r, l


class CooperativeMatrixGame:
    """One-step cooperative team game for value-factorization algorithms
    (QMIX; reference: rllib/algorithms/qmix — evaluated on cooperative
    team-reward tasks). TEAM-reward protocol, distinct from MultiAgentEnv's
    per-agent dicts:

        reset() -> {agent: obs}
        step({agent: action}) -> ({agent: obs}, team_reward, term, trunc)
        global_state() -> np.ndarray   (the mixer conditions on this)

    Payoff: both pick 0 -> +8 (the coordinated optimum); both pick the
    same nonzero arm -> +3; miscoordinate -> 0. Greedy independent
    learners frequently settle on the safe +3; the mixed team value makes
    the +8 joint action identifiable.
    """

    num_actions = 3
    observation_dim = 1
    agent_ids = ["a0", "a1"]

    def __init__(self):
        self._t = 0

    def reset(self, seed: int | None = None) -> dict:
        self._t = 0
        return {a: np.ones(1, np.float32) for a in self.agent_ids}

    def global_state(self) -> np.ndarray:
        return np.ones(2, np.float32)

    def step(self, actions: dict):
        a0, a1 = actions["a0"], actions["a1"]
        if a0 == a1 == 0:
            reward = 8.0
        elif a0 == a1:
            reward = 3.0
        else:
            reward = 0.0
        self._t += 1
        obs = {a: np.ones(1, np.float32) for a in self.agent_ids}
        return obs, reward, True, False

    def close(self) -> None:
        pass


class ContextualBanditEnv(Env):
    """Linear contextual bandit (reference: rllib/examples/env/bandit_envs —
    the bandit algorithms' test surface). Each reset draws a context
    x ~ U[0,1]^d; pulling arm a pays x[a] plus small noise, so the optimal
    policy is argmax over context features and regret is measurable in
    closed form. Episodes are length-1 (bandit convention)."""

    num_actions = 3
    observation_dim = 3

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._x = np.zeros(self.observation_dim, np.float32)

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._x = self._rng.random(self.observation_dim).astype(np.float32)
        return self._x

    def step(self, action: int):
        reward = float(self._x[action]) + 0.01 * float(
            self._rng.standard_normal())
        # length-1 episode; next context arrives via the terminal reset
        return self._x, reward, True, False


class TwoStepGame:
    """The QMIX paper's two-step cooperative game (Rashid et al. 2018;
    reference: rllib/examples/env/two_step_game.py — THE canonical QMIX
    eval env). Step 1: agent a0's action selects which matrix game is
    played. Step 2: state 2A pays 7 for any joint action; state 2B pays
    [[0, 1], [1, 8]]. The optimum (choose 2B, then both play 1 -> 8)
    requires agent a1 to condition on the state a0 produced — value
    factorization with a state-conditioned mixer finds it, independent
    learners typically settle on the safe 7.

    Same TEAM-reward protocol as CooperativeMatrixGame: obs/action dicts,
    one scalar reward, `global_state()` for the mixer.
    """

    num_actions = 2
    observation_dim = 3  # one-hot of {s0, s2A, s2B}
    agent_ids = ["a0", "a1"]
    max_episode_steps = 2

    def __init__(self):
        self._state = 0  # 0 -> start, 1 -> 2A, 2 -> 2B

    def _obs(self) -> dict:
        o = np.zeros(3, np.float32)
        o[self._state] = 1.0
        return {a: o.copy() for a in self.agent_ids}

    def reset(self, seed: int | None = None) -> dict:
        self._state = 0
        return self._obs()

    def global_state(self) -> np.ndarray:
        s = np.zeros(3, np.float32)
        s[self._state] = 1.0
        return s

    def step(self, actions: dict):
        if self._state == 0:
            self._state = 1 if actions["a0"] == 0 else 2
            return self._obs(), 0.0, False, False
        if self._state == 1:
            reward = 7.0
        else:
            payoff = ((0.0, 1.0), (1.0, 8.0))
            reward = payoff[actions["a0"]][actions["a1"]]
        return self._obs(), reward, True, False

    def close(self) -> None:
        pass


_REGISTRY["CooperativeMatrixGame"] = CooperativeMatrixGame
_REGISTRY["TwoStepGame"] = TwoStepGame
_REGISTRY["ContextualBandit"] = ContextualBanditEnv


class MiniBreakout(Env):
    """MinAtar-style Breakout (10x10x4 binary frames) — the Atari-class
    conv-policy workload (reference: RLlib's Atari benchmarks; MinAtar,
    Young & Tian 2019, is the accepted small-scale stand-in: same visual
    structure — paddle/ball/trail/brick CHANNELS — at 1/600th the pixels).
    Observation is the flattened [10, 10, 4] frame (conv modules reshape);
    reward +1 per brick, episode ends on ball loss or board clear."""

    H = W = 10
    num_actions = 3  # left / stay / right
    observation_dim = H * W * 4

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self.max_episode_steps = 500
        self.reset()

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._paddle = self.W // 2
        self._ball = [self.H - 4, int(self._rng.integers(1, self.W - 1))]
        self._dball = [1, 1 if self._rng.random() < 0.5 else -1]
        self._bricks = np.zeros((self.H, self.W), np.bool_)
        self._bricks[1:4, :] = True
        self._trail = list(self._ball)
        self._steps = 0
        return self._obs()

    def _obs(self) -> np.ndarray:
        f = np.zeros((self.H, self.W, 4), np.float32)
        f[self.H - 1, self._paddle, 0] = 1.0           # paddle
        f[self._ball[0], self._ball[1], 1] = 1.0       # ball
        f[self._trail[0], self._trail[1], 2] = 1.0     # last ball position
        f[:, :, 3] = self._bricks                      # bricks
        return f.reshape(-1)

    def step(self, action: int):
        self._steps += 1
        self._paddle = int(np.clip(self._paddle + (action - 1), 0, self.W - 1))
        self._trail = list(self._ball)
        r, c = self._ball
        dr, dc = self._dball
        nr, nc = r + dr, c + dc
        reward = 0.0
        if nc < 0 or nc >= self.W:           # side wall
            dc = -dc
            nc = c + dc
        if nr < 0:                           # ceiling
            dr = -dr
            nr = r + dr
        if 0 <= nr < self.H and self._bricks[nr, nc]:
            self._bricks[nr, nc] = False     # brick: bounce + score
            reward = 1.0
            dr = -dr
            nr = r + dr
        terminated = False
        if nr >= self.H - 1:                 # paddle row
            if abs(nc - self._paddle) <= 1:
                dr = -1
                nr = self.H - 2
            else:
                terminated = True            # ball lost
        if not self._bricks.any():
            terminated = True                # board cleared
            reward += 5.0
        self._ball = [int(np.clip(nr, 0, self.H - 1)), int(nc)]
        self._dball = [dr, dc]
        truncated = self._steps >= self.max_episode_steps
        return self._obs(), reward, terminated, truncated


_REGISTRY["MiniBreakout"] = MiniBreakout


class TMaze(Env):
    """Memory corridor (Bakker 2002's T-maze, the standard recurrence
    probe; reference: R2D2/rllib_contrib recurrent learning tests use
    memory-requiring envs like StatelessCartPole). The goal side is shown
    ONLY in the first observation; the agent walks a featureless corridor
    and must turn the remembered way at the junction. A feed-forward
    policy is capped at coin-flip performance at the junction; a recurrent
    one solves it.

    obs = [cue (+1 up / -1 down, zero after t=0), at_junction, pos/L].
    actions: 0 = forward, 1 = up, 2 = down (turns are no-ops with a small
    penalty before the junction). Reward: +4.0 correct turn, -0.1 wrong,
    -0.01 per step.
    """

    num_actions = 3
    observation_dim = 3

    def __init__(self, length: int = 4, seed: int = 0):
        self.length = length
        self.max_episode_steps = 3 * length + 4
        self._rng = np.random.default_rng(seed)
        self._pos = 0
        self._steps = 0
        self._goal_up = True

    def _obs(self, show_cue: bool) -> np.ndarray:
        return np.array(
            [
                (1.0 if self._goal_up else -1.0) if show_cue else 0.0,
                1.0 if self._pos >= self.length else 0.0,
                self._pos / self.length,
            ],
            np.float32,
        )

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = 0
        self._steps = 0
        self._goal_up = bool(self._rng.random() < 0.5)
        return self._obs(show_cue=True)

    def step(self, action: int):
        self._steps += 1
        reward = -0.01
        terminated = False
        at_junction = self._pos >= self.length
        if action == 0 and not at_junction:
            self._pos += 1
        elif action in (1, 2):
            if at_junction:
                correct = (action == 1) == self._goal_up
                reward += 4.0 if correct else -0.1
                terminated = True
            else:
                reward -= 0.04  # turning against a corridor wall
        truncated = self._steps >= self.max_episode_steps
        return self._obs(show_cue=False), reward, terminated, truncated


_REGISTRY["TMaze"] = TMaze
