"""ray_tpu — a TPU-native distributed AI framework.

Distributed core (tasks / actors / objects) with TPU chips and ICI slices as
first-class scheduled resources, plus JAX/XLA/Pallas library layers: train,
tune, data, serve, rllib. The capability surface mirrors the reference
surveyed in SURVEY.md; the architecture is TPU-first throughout.

Public core API (reference: python/ray/_private/worker.py — ray.init:1139,
get:2461, put:2590, wait:2653, remote:3027).
"""
from __future__ import annotations

import threading
from typing import Any, Sequence

from ray_tpu._private import worker as _worker_mod
from ray_tpu._private.config import reset_config
from ray_tpu._private.ids import JobID, NodeID
from ray_tpu._private.generator import ObjectRefGenerator
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.worker import CoreWorker, global_worker, set_global_worker
from ray_tpu.actor import ActorHandle, get_actor, kill
from ray_tpu.remote_function import remote_decorator as remote
from ray_tpu import exceptions

__version__ = "0.1.0"

_init_lock = threading.Lock()
_node_handle = None

# module alias so `ray_tpu.worker.global_worker()` works (used by ObjectRef)
worker = _worker_mod


def is_initialized() -> bool:
    return _worker_mod.global_worker_or_none() is not None


def init(
    *,
    address: str | None = None,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    resources: dict[str, float] | None = None,
    object_store_memory: int | None = None,
    labels: dict[str, str] | None = None,
    _system_config: dict[str, Any] | None = None,
    ignore_reinit_error: bool = False,
):
    """Start a single-host cluster (store daemon + GCS + raylet) and connect
    this process as the driver — or, with `address=`, connect to an EXISTING
    cluster's GCS (the `ray.init(address=...)` analog; node discovery via
    the GCS node table). `address="auto"` reads RT_ADDRESS from the
    environment (set for job-submission drivers)."""
    import os as _os

    if address is not None:
        if address == "auto":
            address = _os.environ.get("RT_ADDRESS", "")
            if not address:
                raise RuntimeError('init(address="auto") needs RT_ADDRESS set')
        if address.startswith("ray://"):
            # out-of-cluster driver: proxy the whole API through the head's
            # client server (util/client.py; reference: ray client,
            # python/ray/util/client/)
            if is_initialized():
                if ignore_reinit_error:
                    return None
                raise RuntimeError(
                    "ray_tpu.init() called twice; pass ignore_reinit_error=True")
            from ray_tpu.util.client import connect_client

            connect_client(address)
            return None
        if is_initialized():
            if ignore_reinit_error:
                return None
            raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")
        from ray_tpu._private.rpc import RpcClient

        probe = RpcClient(address)
        try:
            nodes = [n for n in probe.call("get_nodes")["nodes"] if n["alive"]]
        finally:
            probe.close()
        # Attach to a node on THIS host: the driver needs a local raylet and
        # a local store daemon (reference: the driver always connects to its
        # node's raylet/plasma over unix sockets). A node is local iff its
        # store socket path exists here.
        local = [
            n
            for n in nodes
            if n.get("store_socket") and _os.path.exists(n["store_socket"])
        ]
        if not local:
            raise RuntimeError(
                f"no cluster node is running on this host (cluster at "
                f"{address} has {len(nodes)} alive nodes); run "
                f"`ray_tpu start --address {address}` here first"
            )
        connect(
            gcs_address=address,
            raylet_address=local[0]["address"],
            store_socket=local[0]["store_socket"],
        )
        return None
    global _node_handle
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                return _node_handle
            raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")
        cfg = reset_config(_system_config)
        from ray_tpu._private.node import start_fake_tpu_hosts, start_head

        _node_handle = start_head(
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources,
            labels=labels,
            object_store_memory=object_store_memory,
        )
        if cfg.fake_tpu_hosts > 0:
            # fake multi-host TPU pod-slice topology (SURVEY §4.3 harness)
            start_fake_tpu_hosts(_node_handle, cfg.fake_tpu_hosts,
                                 cfg.tpu_chips_per_host_default)
        job_id = JobID(
            _node_handle.raylet.gcs.call("next_job_id")["job_id"]
        )
        core = CoreWorker(
            mode="driver",
            gcs_address=_node_handle.gcs_address,
            raylet_address=_node_handle.raylet.address,
            store_socket=_node_handle.store_socket,
            job_id=job_id,
            node_id=_node_handle.node_id,
        )
        set_global_worker(core)
        # ray:// client server (ephemeral port unless pinned via env;
        # the CLI `start --head` pins the reference's canonical 10001)
        try:
            from ray_tpu.util.client import ClientServer

            port = int(_os.environ.get("RAY_TPU_CLIENT_SERVER_PORT", "0"))
            _node_handle.client_server = ClientServer(
                _node_handle, host="127.0.0.1", port=port)
        except Exception:  # noqa: BLE001 — client server is auxiliary
            _node_handle.client_server = None
        return _node_handle


def connect(
    *,
    gcs_address: str,
    raylet_address: str,
    store_socket: str,
) -> None:
    """Connect this process as a driver to an existing cluster (the
    `ray.init(address=...)` analog)."""
    with _init_lock:
        if is_initialized():
            raise RuntimeError("already connected")
        from ray_tpu._private.rpc import RpcClient

        gcs = RpcClient(gcs_address)
        job_id = JobID(gcs.call("next_job_id")["job_id"])
        gcs.close()
        core = CoreWorker(
            mode="driver",
            gcs_address=gcs_address,
            raylet_address=raylet_address,
            store_socket=store_socket,
            job_id=job_id,
            node_id=NodeID.nil(),
        )
        set_global_worker(core)


def shutdown() -> None:
    global _node_handle
    with _init_lock:
        if _node_handle is not None:
            # opt-in usage report lands in the session dir before teardown
            # (local file only — see _private/usage_stats.py)
            from ray_tpu._private import usage_stats

            usage_stats.write_report(
                getattr(_node_handle, "session_dir", None))
        w = _worker_mod.global_worker_or_none()
        if w is not None:
            w.shutdown()
            set_global_worker(None)
        if _node_handle is not None:
            cs = getattr(_node_handle, "client_server", None)
            if cs is not None:
                cs.stop()
            _node_handle.shutdown()
            _node_handle = None


def put(value: Any) -> ObjectRef:
    return global_worker().put(value)


def get(refs: ObjectRef | Sequence[ObjectRef], *, timeout: float | None = None):
    return global_worker().get(refs, timeout=timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: float | None = None,
):
    return global_worker().wait(refs, num_returns=num_returns, timeout=timeout)


def cluster_resources() -> dict[str, float]:
    return global_worker().gcs.call("cluster_resources")["total"]


def available_resources() -> dict[str, float]:
    return global_worker().gcs.call("cluster_resources")["available"]


def nodes() -> list[dict]:
    return global_worker().gcs.call("get_nodes")["nodes"]


__all__ = [
    "init",
    "shutdown",
    "connect",
    "is_initialized",
    "remote",
    "put",
    "get",
    "wait",
    "kill",
    "get_actor",
    "cluster_resources",
    "available_resources",
    "nodes",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorHandle",
    "exceptions",
]
