"""Content-hash-gated builds of the C++ runtime components.

Artifacts are compiled into ``ray_tpu/cpp/build/`` (never committed) with
the source digest in the filename, so a checkout can never load a stale or
foreign binary: a changed source hashes to a new path and rebuilds; the
mtime of files restored by git is irrelevant.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_lock = threading.Lock()


def build_native(
    src: str,
    out_name: str,
    compile_args: list[str],
    link_args: list[str] | None = None,
) -> str:
    """Compile ``src`` with g++ if no artifact for its current content
    exists; returns the artifact path. Safe under concurrent callers
    (atomic rename; same digest converges to the same path)."""
    with _lock:
        # no memoized early-return: the digest MUST be recomputed per call
        # or an in-process edit to a header would keep serving the stale
        # binary; hashing a few small sources is microseconds
        hasher = hashlib.sha256()
        with open(src, "rb") as f:
            hasher.update(f.read())
        # sibling headers are part of the translation unit: an edit to
        # util.hpp must rebuild every binary that includes it
        src_dir = os.path.dirname(src)
        for name in sorted(os.listdir(src_dir)):
            if name.endswith((".hpp", ".h")):
                with open(os.path.join(src_dir, name), "rb") as f:
                    hasher.update(f.read())
        digest = hasher.hexdigest()[:12]
        build_dir = os.path.join(os.path.dirname(src), "build")
        os.makedirs(build_dir, exist_ok=True)
        out = os.path.join(build_dir, f"{out_name}.{digest}")
        if not os.path.exists(out):
            tmp = f"{out}.tmp.{os.getpid()}"
            subprocess.run(
                ["g++", *compile_args, "-o", tmp, src, *(link_args or [])],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, out)
        return out
