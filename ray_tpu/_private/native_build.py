"""Content-hash-gated builds of the C++ runtime components.

Artifacts are compiled into ``ray_tpu/cpp/build/`` (never committed) with
the source digest in the filename, so a checkout can never load a stale or
foreign binary: a changed source hashes to a new path and rebuilds; the
mtime of files restored by git is irrelevant.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_lock = threading.Lock()
_cache: dict[tuple[str, str], str] = {}


def build_native(
    src: str,
    out_name: str,
    compile_args: list[str],
    link_args: list[str] | None = None,
) -> str:
    """Compile ``src`` with g++ if no artifact for its current content
    exists; returns the artifact path. Safe under concurrent callers
    (atomic rename; same digest converges to the same path)."""
    with _lock:
        # key by (src, out_name): one source builds multiple variants
        # (production vs sanitizer-instrumented) and a src-only key would
        # hand one variant's binary to the other's caller
        cached = _cache.get((src, out_name))
        if cached and os.path.exists(cached):
            return cached
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:12]
        build_dir = os.path.join(os.path.dirname(src), "build")
        os.makedirs(build_dir, exist_ok=True)
        out = os.path.join(build_dir, f"{out_name}.{digest}")
        if not os.path.exists(out):
            tmp = f"{out}.tmp.{os.getpid()}"
            subprocess.run(
                ["g++", *compile_args, "-o", tmp, src, *(link_args or [])],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, out)
        _cache[(src, out_name)] = out
        return out
