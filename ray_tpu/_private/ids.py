"""Binary unique identifiers for the distributed core.

TPU-native rebuild of the reference's ID layer (reference: src/ray/common/id.h —
JobID 4B, ActorID 16B, TaskID 24B, ObjectID 28B with embedded task + index).
We keep the same *structural* idea — ObjectIDs embed their creating TaskID plus a
return/put index so ownership and lineage can be derived from the ID alone — but
use a simpler uniform layout: every ID is raw bytes with a type-tagged hex repr.
"""
from __future__ import annotations

import os
import threading

_rand_lock = threading.Lock()


def _random_bytes(n: int) -> bytes:
    return os.urandom(n)


class BaseID:
    SIZE = 16
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = id_bytes
        self._hash = hash(id_bytes)

    @classmethod
    def from_random(cls):
        return cls(_random_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class UniqueID(BaseID):
    SIZE = 16


class JobID(BaseID):
    SIZE = 4

    _counter = 0

    @classmethod
    def next(cls) -> "JobID":
        with _rand_lock:
            cls._counter += 1
            return cls(cls._counter.to_bytes(4, "little"))


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    """12 random bytes + 4-byte job id."""

    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_random_bytes(12) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[12:16])


class TaskID(BaseID):
    """8 random bytes + 16-byte parent/actor scope."""

    SIZE = 24

    @classmethod
    def for_task(cls, job_id: JobID) -> "TaskID":
        return cls(_random_bytes(20) + job_id.binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_random_bytes(8) + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(b"\x00" * 20 + job_id.binary())


class ObjectID(BaseID):
    """TaskID (24B) + 4-byte little-endian index.

    Index 0..2**31 are task returns; indices with the high bit set are
    `put` objects. The creating task — hence the owner — is recoverable
    from the ID (reference: ObjectID::ForTaskReturn semantics).
    """

    SIZE = 28
    PUT_BIT = 1 << 31

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + (index | cls.PUT_BIT).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:24])

    def index(self) -> int:
        return int.from_bytes(self._bytes[24:28], "little") & ~self.PUT_BIT

    def is_put(self) -> bool:
        return bool(int.from_bytes(self._bytes[24:28], "little") & self.PUT_BIT)


class PlacementGroupID(BaseID):
    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(_random_bytes(12) + job_id.binary())
