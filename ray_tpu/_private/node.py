"""Node bootstrap: object store daemon + GCS + raylet for one host.

Equivalent of the reference's node bootstrap
(reference: python/ray/_private/node.py — Node.start_head_processes:1395
spawns gcs_server, start_ray_processes:1424 spawns the raylet which embeds
plasma). Here the store is a real subprocess (C++ daemon); GCS and raylet
run as threads in the driver process by default — same protocol, fewer
processes — and the `Cluster` harness stacks extra in-process raylets for
multi-node tests (reference: python/ray/cluster_utils.py:108).
"""
from __future__ import annotations

import atexit
import os
import tempfile
import uuid

from ray_tpu._private.config import global_config
from ray_tpu._private.gcs import GcsService
from ray_tpu._private.ids import NodeID
from ray_tpu._private.object_store import start_store
from ray_tpu._private.raylet import Raylet


def autodetect_tpu_chips() -> int:
    """Detect local TPU chips without initializing JAX.

    Reference: python/ray/_private/accelerator.py:153 _autodetect_num_tpus
    reads /dev/accel* and GKE env vars. We honor TPU_CHIPS_OVERRIDE for
    tests, /dev/accel* device files, and fall back to 0.
    """
    override = os.environ.get("RT_NUM_TPUS")
    if override:
        return int(override)
    try:
        return len([d for d in os.listdir("/dev") if d.startswith("accel")])
    except OSError:
        return 0


class NodeHandle:
    def __init__(self, *, gcs: GcsService | None, gcs_address: str,
                 raylet: Raylet, store_proc, store_socket: str, session_dir: str):
        self.gcs = gcs
        self.gcs_address = gcs_address
        self.raylet = raylet
        self.store_proc = store_proc
        self.store_socket = store_socket
        self.session_dir = session_dir
        self.node_id = raylet.node_id

    def shutdown(self) -> None:
        self.raylet.stop()
        if self.gcs is not None:
            self.gcs.stop()
        if self.store_proc is not None:
            try:
                self.store_proc.terminate()
                self.store_proc.wait(timeout=5)
            except Exception:
                pass


def start_head(
    *,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    resources: dict[str, float] | None = None,
    labels: dict[str, str] | None = None,
    object_store_memory: int | None = None,
) -> NodeHandle:
    cfg = global_config()
    session_dir = tempfile.mkdtemp(prefix="ray_tpu_session_")
    store_socket = os.path.join(session_dir, "store.sock")
    store_proc = start_store(
        store_socket, object_store_memory or cfg.object_store_memory_bytes
    )
    # build+load the native scheduling core NOW so the first dispatch never
    # stalls on a synchronous g++ compile
    from ray_tpu._private import scheduler as _sched

    _sched._load_native()

    gcs = GcsService()
    gcs_address = gcs.start()

    node_resources = dict(resources or {})
    node_resources.setdefault("CPU", float(num_cpus if num_cpus is not None else os.cpu_count() or 1))
    node_resources.setdefault(
        "TPU", float(num_tpus if num_tpus is not None else autodetect_tpu_chips())
    )
    node_resources.setdefault("memory", float(2 * 1024**3))
    node_labels = dict(labels or {})
    if node_resources["TPU"] > 0:
        node_labels.setdefault("ici-domain", "slice-0")

    raylet = Raylet(
        NodeID.from_random(), gcs_address, store_socket, node_resources, node_labels
    )
    handle = NodeHandle(
        gcs=gcs,
        gcs_address=gcs_address,
        raylet=raylet,
        store_proc=store_proc,
        store_socket=store_socket,
        session_dir=session_dir,
    )
    atexit.register(handle.shutdown)
    return handle


class Cluster:
    """In-process fake multi-node cluster for tests.

    Reference: python/ray/cluster_utils.py:108 Cluster — extra raylets in one
    process against one GCS. All nodes share the single host store (valid:
    on one physical host the reference's plasma is also per-node but our
    tests only assert scheduling semantics, not store isolation).
    """

    def __init__(self, head_resources: dict[str, float] | None = None):
        self.head = start_head(
            num_cpus=(head_resources or {}).get("CPU", 2),
            num_tpus=(head_resources or {}).get("TPU", 0),
            resources={
                k: v for k, v in (head_resources or {}).items() if k not in ("CPU", "TPU")
            },
        )
        self.nodes: list[Raylet] = [self.head.raylet]

    @property
    def gcs_address(self) -> str:
        return self.head.gcs_address

    def add_node(
        self,
        *,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: dict[str, float] | None = None,
        labels: dict[str, str] | None = None,
    ) -> Raylet:
        node_resources = dict(resources or {})
        node_resources["CPU"] = float(num_cpus)
        node_resources["TPU"] = float(num_tpus)
        node_resources.setdefault("memory", float(2 * 1024**3))
        node_labels = dict(labels or {})
        if num_tpus > 0:
            node_labels.setdefault("ici-domain", f"slice-{len(self.nodes)}")
        raylet = Raylet(
            NodeID.from_random(),
            self.head.gcs_address,
            self.head.store_socket,
            node_resources,
            node_labels,
        )
        self.nodes.append(raylet)
        return raylet

    def remove_node(self, raylet: Raylet) -> None:
        raylet.stop()
        self.nodes.remove(raylet)
        try:
            self.head.gcs.rpc_drain_node(None, 0, {"node_id": raylet.node_id.binary()})
        except Exception:
            pass

    def shutdown(self) -> None:
        for raylet in self.nodes[1:]:
            try:
                raylet.stop()
            except Exception:
                pass
        self.head.shutdown()
