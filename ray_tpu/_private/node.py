"""Node bootstrap: object store daemon + GCS + raylet for one host.

Equivalent of the reference's node bootstrap
(reference: python/ray/_private/node.py — Node.start_head_processes:1395
spawns gcs_server, start_ray_processes:1424 spawns the raylet which embeds
plasma). The store is always a real subprocess (C++ daemon), ONE PER NODE;
GCS and raylet run as threads in the hosting process — same protocol, fewer
processes. Standalone node processes (`ray_tpu start --head` /
`--address=<gcs>`) are hosted by _private/node_main.py; the `Cluster`
harness stacks extra in-process raylets, each with its own store daemon,
for multi-node tests (reference: python/ray/cluster_utils.py:108).
"""
from __future__ import annotations

import atexit
import os
import tempfile
from typing import Any

from ray_tpu._private.config import global_config
from ray_tpu._private.gcs import GcsService
from ray_tpu._private.ids import NodeID
from ray_tpu._private.object_store import start_store
from ray_tpu._private.raylet import Raylet


def autodetect_tpu_chips() -> int:
    """Detect local TPU chips without initializing JAX.

    Reference: python/ray/_private/accelerator.py:153 _autodetect_num_tpus
    reads /dev/accel* and GKE env vars. We honor TPU_CHIPS_OVERRIDE for
    tests, /dev/accel* device files, and fall back to 0.
    """
    override = os.environ.get("RT_NUM_TPUS")
    if override:
        return int(override)
    try:
        return len([d for d in os.listdir("/dev") if d.startswith("accel")])
    except OSError:
        return 0


class NodeHandle:
    def __init__(self, *, gcs: GcsService | None, gcs_address: str,
                 raylet: Raylet, store_proc, store_socket: str, session_dir: str):
        self.gcs = gcs
        self.gcs_address = gcs_address
        self.raylet = raylet
        self.store_proc = store_proc
        self.store_socket = store_socket
        self.session_dir = session_dir
        self.node_id = raylet.node_id
        # fake multi-host TPU topology (config.fake_tpu_hosts): extra
        # in-process raylets + their store daemons, torn down with the head
        self.fake_nodes: list[tuple[Raylet, Any]] = []

    def shutdown(self) -> None:
        for raylet, store_proc in self.fake_nodes:
            try:
                raylet.stop()
            except Exception:
                pass
            if store_proc is not None:
                try:
                    store_proc.terminate()
                    store_proc.wait(timeout=5)
                except Exception:
                    pass
        self.fake_nodes = []
        self.raylet.stop()
        if self.gcs is not None:
            self.gcs.stop()
        if self.store_proc is not None:
            try:
                self.store_proc.terminate()
                self.store_proc.wait(timeout=5)
            except Exception:
                pass


def start_fake_tpu_hosts(head: NodeHandle, n_hosts: int,
                         chips_per_host: int) -> None:
    """SURVEY §4.3 fake-accelerator harness: present an n-host TPU pod
    slice on one machine. Each fake host is a real in-process raylet with
    its own store daemon, `TPU: chips_per_host` resources, and pod-slice
    labels (one shared ici-domain — scheduler slice-affinity sees a real
    topology). Enabled by config.fake_tpu_hosts > 0; chips per host come
    from config.tpu_chips_per_host_default."""
    cfg = global_config()
    for i in range(n_hosts):
        store_socket = os.path.join(head.session_dir, f"fake-tpu-{i}.sock")
        store_proc = start_store(
            store_socket, cfg.object_store_memory_bytes,
            spill_dir=cfg.object_spilling_dir or None,
        )
        raylet = Raylet(
            NodeID.from_random(),
            head.gcs_address,
            store_socket,
            {"CPU": 1.0, "TPU": float(chips_per_host),
             "memory": float(2 * 1024**3)},
            {"ici-domain": "fake-slice-0", "fake-tpu-host": str(i)},
        )
        head.fake_nodes.append((raylet, store_proc))


def _default_node_resources(
    num_cpus: float | None,
    num_tpus: float | None,
    resources: dict[str, float] | None,
    labels: dict[str, str] | None,
) -> tuple[dict[str, float], dict[str, str]]:
    node_resources = dict(resources or {})
    node_resources.setdefault(
        "CPU", float(num_cpus if num_cpus is not None else os.cpu_count() or 1)
    )
    node_resources.setdefault(
        "TPU", float(num_tpus if num_tpus is not None else autodetect_tpu_chips())
    )
    node_resources.setdefault("memory", float(2 * 1024**3))
    node_labels = dict(labels or {})
    if node_resources["TPU"] > 0:
        node_labels.setdefault("ici-domain", "slice-0")
    return node_resources, node_labels


def start_head(
    *,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    resources: dict[str, float] | None = None,
    labels: dict[str, str] | None = None,
    object_store_memory: int | None = None,
    gcs_port: int = 0,
) -> NodeHandle:
    cfg = global_config()
    session_dir = tempfile.mkdtemp(prefix="ray_tpu_session_")
    store_socket = os.path.join(session_dir, "store.sock")
    store_proc = start_store(
        store_socket,
        object_store_memory or cfg.object_store_memory_bytes,
        spill_dir=cfg.object_spilling_dir or None,
    )
    # build+load the native scheduling core NOW so the first dispatch never
    # stalls on a synchronous g++ compile
    from ray_tpu._private import scheduler as _sched

    _sched._load_native()

    gcs = GcsService()
    gcs_address = gcs.start(port=gcs_port)

    node_resources, node_labels = _default_node_resources(
        num_cpus, num_tpus, resources, labels
    )
    raylet = Raylet(
        NodeID.from_random(), gcs_address, store_socket, node_resources, node_labels
    )
    handle = NodeHandle(
        gcs=gcs,
        gcs_address=gcs_address,
        raylet=raylet,
        store_proc=store_proc,
        store_socket=store_socket,
        session_dir=session_dir,
    )
    atexit.register(handle.shutdown)
    return handle


def start_worker_node(
    gcs_address: str,
    *,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    resources: dict[str, float] | None = None,
    labels: dict[str, str] | None = None,
    object_store_memory: int | None = None,
) -> NodeHandle:
    """Join an existing cluster as a new node: own store daemon + raylet
    (reference: `ray start --address=<gcs>`, scripts.py:548 worker path)."""
    cfg = global_config()
    session_dir = tempfile.mkdtemp(prefix="ray_tpu_session_")
    store_socket = os.path.join(session_dir, "store.sock")
    store_proc = start_store(
        store_socket,
        object_store_memory or cfg.object_store_memory_bytes,
        spill_dir=cfg.object_spilling_dir or None,
    )
    node_resources, node_labels = _default_node_resources(
        num_cpus, num_tpus, resources, labels
    )
    raylet = Raylet(
        NodeID.from_random(), gcs_address, store_socket, node_resources, node_labels
    )
    handle = NodeHandle(
        gcs=None,
        gcs_address=gcs_address,
        raylet=raylet,
        store_proc=store_proc,
        store_socket=store_socket,
        session_dir=session_dir,
    )
    atexit.register(handle.shutdown)
    return handle


class Cluster:
    """In-process fake multi-node cluster for tests.

    Reference: python/ray/cluster_utils.py:108 Cluster — extra raylets in one
    process against one GCS. Every node runs its OWN store daemon; objects
    move between nodes through the raylet pull/push object plane, exactly as
    they would across physical hosts.
    """

    def __init__(self, head_resources: dict[str, float] | None = None):
        self.head = start_head(
            num_cpus=(head_resources or {}).get("CPU", 2),
            num_tpus=(head_resources or {}).get("TPU", 0),
            resources={
                k: v for k, v in (head_resources or {}).items() if k not in ("CPU", "TPU")
            },
        )
        self.nodes: list[Raylet] = [self.head.raylet]
        self._store_procs: dict[bytes, Any] = {}

    @property
    def gcs_address(self) -> str:
        return self.head.gcs_address

    def add_node(
        self,
        *,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: dict[str, float] | None = None,
        labels: dict[str, str] | None = None,
        object_store_memory: int | None = None,
    ) -> Raylet:
        cfg = global_config()
        node_resources = dict(resources or {})
        node_resources["CPU"] = float(num_cpus)
        node_resources["TPU"] = float(num_tpus)
        node_resources.setdefault("memory", float(2 * 1024**3))
        node_labels = dict(labels or {})
        if num_tpus > 0:
            node_labels.setdefault("ici-domain", f"slice-{len(self.nodes)}")
        store_socket = os.path.join(
            self.head.session_dir, f"store-{len(self.nodes)}.sock"
        )
        store_proc = start_store(
            store_socket,
            object_store_memory or cfg.object_store_memory_bytes,
            spill_dir=cfg.object_spilling_dir or None,
        )
        raylet = Raylet(
            NodeID.from_random(),
            self.head.gcs_address,
            store_socket,
            node_resources,
            node_labels,
        )
        self.nodes.append(raylet)
        self._store_procs[raylet.node_id.binary()] = store_proc
        return raylet

    def remove_node(self, raylet: Raylet) -> None:
        raylet.stop()
        self.nodes.remove(raylet)
        proc = self._store_procs.pop(raylet.node_id.binary(), None)
        if proc is not None:
            try:
                proc.terminate()
            except Exception:
                pass
        try:
            self.head.gcs.rpc_drain_node(None, 0, {"node_id": raylet.node_id.binary()})
        except Exception:
            pass

    def shutdown(self) -> None:
        for raylet in self.nodes[1:]:
            try:
                raylet.stop()
            except Exception:
                pass
        for proc in self._store_procs.values():
            try:
                proc.terminate()
            except Exception:
                pass
        self._store_procs.clear()
        self.head.shutdown()
