"""Client for the C++ shared-memory object store daemon.

Equivalent of the reference's plasma client
(reference: src/ray/object_manager/plasma/client.cc — create/seal/get/release
over a unix socket, with the payload memory-mapped into the client). Objects
are written into per-object POSIX shm segments; `get` returns a zero-copy
memoryview over the mapping, suitable for feeding `jax.device_put` without an
extra host copy.
"""
from __future__ import annotations

import mmap
import os
import socket
import struct
import subprocess
import threading
import time
from dataclasses import dataclass

from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError, GetTimeoutError

(OP_CREATE, OP_SEAL, OP_GET, OP_RELEASE, OP_DELETE, OP_CONTAINS, OP_LIST,
 OP_STATS, OP_SHUTDOWN, OP_SUBSCRIBE, OP_ABORT, OP_PIN, OP_UNPIN,
 OP_WAIT) = range(1, 15)
ST_OK, ST_NOT_FOUND, ST_EXISTS, ST_FULL, ST_TIMEOUT, ST_ERR, ST_EVICTED = range(7)
EV_SEALED, EV_EVICTED = 1, 2

# Sentinel returned by get() for objects that existed but were evicted —
# the trigger for owner-side lineage reconstruction.
EVICTED = object()

_CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "cpp")


def build_store_binary() -> str:
    """Compile the store daemon with g++ (content-hash cached)."""
    from ray_tpu._private.native_build import build_native

    src = os.path.join(_CPP_DIR, "store.cpp")
    return build_native(src, "ray_tpu_store",
                        ["-O2", "-std=c++17", "-pthread"], ["-lrt"])


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        c = sock.recv(n)
        if not c:
            raise ConnectionError("object store connection closed")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def _gc_stale_segments() -> None:
    """Unlink rt_store shm segments whose creating daemon is dead — a
    crash/teardown race can orphan a segment; this makes every store start
    self-healing instead of letting tmpfs fill over weeks of runs."""
    try:
        names = os.listdir("/dev/shm")
    except OSError:
        return
    for name in names:
        if not name.startswith("rt_store_"):
            continue
        try:
            pid = int(name.split("_")[2])
        except (IndexError, ValueError):
            continue
        try:
            os.kill(pid, 0)  # raises if the daemon is gone
        except ProcessLookupError:
            try:
                os.unlink("/dev/shm/" + name)
            except OSError:
                pass
        except PermissionError:
            pass  # someone else's live process


def start_store(
    socket_path: str, capacity_bytes: int, spill_dir: str | None = None,
    min_spilling_size: int | None = None,
) -> subprocess.Popen:
    """Launch the daemon and wait for its READY handshake. spill_dir
    defaults to <socket>.spill next to the socket; pass "" to disable
    spilling (pressure then fails creates instead). min_spilling_size is
    the per-pass spill batch floor (config.min_spilling_size)."""
    from ray_tpu._private.config import global_config

    binary = build_store_binary()
    _gc_stale_segments()
    if spill_dir is None:
        spill_dir = socket_path + ".spill"
    if min_spilling_size is None:
        min_spilling_size = global_config().min_spilling_size
    argv = [binary, socket_path, str(capacity_bytes)]
    if spill_dir:
        argv.append(spill_dir)
        argv.append(str(min_spilling_size))
    proc = subprocess.Popen(
        argv,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
    )
    line = proc.stdout.readline()
    if b"READY" not in line:
        raise RuntimeError(f"object store failed to start: {line!r}")
    return proc


@dataclass
class _Mapping:
    buf: memoryview
    mm: mmap.mmap | None  # None for zero-size objects

    def close(self) -> None:
        # Views may still be exported (numpy arrays aliasing the mapping);
        # in that case leave the mapping to the GC rather than erroring.
        try:
            self.buf.release()
            if self.mm is not None:
                self.mm.close()
        except BufferError:
            pass


class ObjectStoreClient:
    """Thread-safe client; one socket, one lock (requests are short)."""

    # Max cached mmaps; beyond this the least-recently-used unreferenced
    # mapping is closed (closed-but-viewed mappings survive via the exported
    # memoryview's reference to the mmap object).
    MAX_MAPPINGS = 4096

    def __init__(self, socket_path: str):
        self._socket_path = socket_path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        deadline = time.monotonic() + 10
        while True:
            try:
                self._sock.connect(socket_path)
                break
            except (FileNotFoundError, ConnectionRefusedError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.02)
        self._lock = threading.Lock()
        # object id -> open mapping; LRU-capped. Guarded by _map_lock.
        from collections import OrderedDict

        self._mappings: "OrderedDict[bytes, _Mapping]" = OrderedDict()
        # created-but-not-sealed mappings, promoted to _mappings on seal()
        self._pending_creates: dict[bytes, _Mapping] = {}
        self._map_lock = threading.Lock()
        # pooled secondary connections for blocking OP_WAITs
        self._wait_socks: list[socket.socket] = []
        self._wait_lock = threading.Lock()

    _MAX_WAIT_SOCKS = 8

    def _checkout_wait_sock(self) -> socket.socket:
        with self._wait_lock:
            if self._wait_socks:
                return self._wait_socks.pop()
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(self._socket_path)
        return s

    def _checkin_wait_sock(self, s: socket.socket) -> None:
        with self._wait_lock:
            if len(self._wait_socks) < self._MAX_WAIT_SOCKS:
                self._wait_socks.append(s)
                return
        try:
            s.close()
        except OSError:
            pass

    def _request(self, op: int, object_id: bytes, payload: bytes = b"") -> tuple[int, bytes]:
        msg = struct.pack("<IB", 1 + len(object_id) + len(payload), op) + object_id + payload
        with self._lock:
            self._sock.sendall(msg)
            header = _recv_exact(self._sock, 4)
            (length,) = struct.unpack("<I", header)
            body = _recv_exact(self._sock, length)
        return body[0], body[1:]

    # -- API --

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        """Allocate; returns a writable view. Must call seal() after writing."""
        st, payload = self._request(OP_CREATE, object_id.binary(), struct.pack("<Q", size))
        if st == ST_FULL:
            raise ObjectStoreFullError(f"cannot allocate {size} bytes")
        if st == ST_EXISTS:
            raise ValueError(f"object {object_id} already exists")
        if st != ST_OK:
            raise RuntimeError(f"create failed: status {st}")
        shm_name = payload.decode()
        if size == 0:
            m = _Mapping(memoryview(b""), None)
        else:
            mm = self._map(shm_name, size, writable=True)
            m = _Mapping(memoryview(mm), mm)
        # The writable mapping is NOT published to the get() cache yet —
        # same-process readers must not see unsealed bytes; seal() promotes
        # it. Any stale cached mapping (evict+reconstruct recreates the
        # object under a NEW shm segment) is dropped now so no reader keeps
        # hitting dead pages.
        key = object_id.binary()
        with self._map_lock:
            self._mappings.pop(key, None)  # dropped, not closed: readers may
            #                                still hold exported views
            old_pending = self._pending_creates.pop(key, None)
            self._pending_creates[key] = m
        if old_pending is not None:
            old_pending.close()  # abandoned earlier create by this process
        return m.buf

    def discard_pending(self, object_id: ObjectID) -> None:
        """Drop a created-but-never-sealed mapping (failed write/seal path);
        without this, aborted puts leak writable mmaps outside the LRU cap."""
        with self._map_lock:
            m = self._pending_creates.pop(object_id.binary(), None)
        if m is not None:
            m.close()

    def seal(self, object_id: ObjectID, pin: bool = False) -> None:
        """pin=True seals AND pins atomically (primary copies): the object
        can spill under pressure but never be LRU-evicted until unpinned."""
        st, _ = self._request(
            OP_SEAL, object_id.binary(), b"\x01" if pin else b""
        )
        if st != ST_OK:
            raise RuntimeError(f"seal failed: status {st}")
        key = object_id.binary()
        with self._map_lock:
            m = self._pending_creates.pop(key, None)
        if m is not None:
            self._cache_mapping(key, m, replace=True)

    def get(self, object_id: ObjectID, timeout_ms: int = 0) -> memoryview | None:
        """Zero-copy read view, or None if absent (timeout_ms=0 → no wait).

        Deleted/evicted objects surface PROMPTLY as EVICTED: the daemon
        tombstones on every delete and wakes blocked getters (store.cpp),
        so a get racing a delete returns in one round-trip, not after the
        full timeout. The ``object_store.get`` chaos point fires before
        the local cache is consulted, making store fetch faults (used by
        the KV-handoff chaos tests) injectable like every other RPC."""
        from ray_tpu._private import chaos

        chaos.fire(
            "object_store.get",
            object_id=object_id.hex(),
            timeout_ms=int(timeout_ms),
        )
        key = object_id.binary()
        # Cache hit: the data is immutable and our mmap stays valid even if
        # the server evicts the segment (the kernel keeps mapped pages), so
        # no RPC is needed.
        with self._map_lock:
            cached = self._mappings.get(key)
            if cached is not None:
                self._mappings.move_to_end(key)
                return cached.buf
        # Bounded retry: between the OP_GET reply and our shm_open the
        # server may SPILL the object (unlinking its segment) under memory
        # pressure; a re-request restores it into a fresh segment.
        for _ in range(8):
            st, payload = self._request(OP_GET, key, struct.pack("<Q", timeout_ms))
            if st == ST_NOT_FOUND:
                return None
            if st == ST_EVICTED:
                return EVICTED
            if st == ST_TIMEOUT:
                raise GetTimeoutError(f"get({object_id}) timed out after {timeout_ms}ms")
            if st != ST_OK:
                raise RuntimeError(f"get failed: status {st}")
            (size,) = struct.unpack("<Q", payload[:8])
            shm_name = payload[8:].decode()
            try:
                with self._map_lock:
                    if key in self._mappings:
                        self._mappings.move_to_end(key)
                        return self._mappings[key].buf
                if size == 0:
                    m = _Mapping(memoryview(b""), None)
                else:
                    try:
                        mm = self._map(shm_name, size, writable=False)
                    except FileNotFoundError:
                        continue  # segment spilled mid-handshake: re-request
                    m = _Mapping(memoryview(mm), mm)
                return self._cache_mapping(key, m).buf
            finally:
                # Drop the server-side pin taken by OP_GET as soon as the
                # mmap exists: our mapping keeps the pages valid locally even
                # if the server evicts, and late readers reconstruct from
                # lineage. Pinned bytes on the server thus stay transient.
                self._request(OP_RELEASE, key)
        raise RuntimeError(
            f"get({object_id}): segment vanished {8} times (spill thrash)"
        )

    def _cache_mapping(self, key: bytes, m: _Mapping, replace: bool = False) -> _Mapping:
        """Insert-or-get under the lock; loser of a concurrent double-fetch
        is closed. Returns the canonical mapping for `key`.

        replace=True makes `m` the canonical mapping even if one is cached
        (create() after evict+reconstruct). The displaced mapping is dropped
        without close(): readers may still hold its exported view, and the
        GC closes the mmap once the last view dies."""
        with self._map_lock:
            existing = self._mappings.get(key)
            if existing is not None and replace:
                del self._mappings[key]
                existing = None
            if existing is not None:
                self._mappings.move_to_end(key)
                m.close()
                return existing
            self._mappings[key] = m
            while len(self._mappings) > self.MAX_MAPPINGS:
                _, victim = self._mappings.popitem(last=False)
                victim.close()
            return m

    def release(self, object_id: ObjectID) -> None:
        """Drop the local mapping. Server pins are transient (taken by
        OP_GET, dropped as soon as the mmap exists), so no RPC here."""
        with self._map_lock:
            m = self._mappings.pop(object_id.binary(), None)
        if m is not None:
            m.close()

    def delete(self, object_id: ObjectID) -> None:
        self._request(OP_DELETE, object_id.binary())

    def abort(self, object_id: ObjectID) -> None:
        """Drop an unsealed create server-side (failed write/pull); unlike
        delete() this leaves no eviction tombstone, so a later create of the
        same object succeeds cleanly."""
        self.discard_pending(object_id)
        self._request(OP_ABORT, object_id.binary())

    def wait_objects(
        self, object_ids: list[ObjectID], num_returns: int, timeout_ms: int
    ) -> set[bytes]:
        """BLOCK in the daemon until >= num_returns of object_ids are
        present (or timeout); returns the present subset. Replaces
        client-side contains() busy-polling — the daemon's seal cv wakes
        waiters the moment an object lands. Runs on a CACHED secondary
        connection (one per concurrently-blocked waiter, pooled and
        reused) so it never stalls this client's request socket and
        looping waiters don't churn daemon threads."""
        ids = [o.binary() for o in object_ids]
        payload = struct.pack("<QII", timeout_ms, num_returns, len(ids)) + b"".join(ids)
        msg = struct.pack("<IB", 1 + 28 + len(payload), OP_WAIT) + b"\x00" * 28 + payload
        sock = self._checkout_wait_sock()
        try:
            sock.sendall(msg)
            header = _recv_exact(sock, 4)
            (length,) = struct.unpack("<I", header)
            body = _recv_exact(sock, length)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        self._checkin_wait_sock(sock)
        if body[0] != ST_OK:
            raise RuntimeError(f"wait failed: status {body[0]}")
        (m,) = struct.unpack_from("<I", body, 1)
        return {body[5 + i * 28 : 5 + (i + 1) * 28] for i in range(m)}

    def pin(self, object_id: ObjectID) -> bool:
        """Long-lived reference (primary-copy pin): the object may spill
        under pressure but can never be LRU-evicted while pinned."""
        st, _ = self._request(OP_PIN, object_id.binary())
        return st == ST_OK

    def unpin(self, object_id: ObjectID) -> None:
        self._request(OP_UNPIN, object_id.binary())

    def contains(self, object_id: ObjectID) -> bool:
        st, _ = self._request(OP_CONTAINS, object_id.binary())
        return st == ST_OK

    def status(self, object_id: ObjectID) -> str:
        """'present' | 'missing' | 'evicted' — without pinning the object."""
        st, _ = self._request(OP_CONTAINS, object_id.binary())
        if st == ST_OK:
            return "present"
        if st == ST_EVICTED:
            return "evicted"
        return "missing"

    def list_objects(self) -> list[ObjectID]:
        st, payload = self._request(OP_LIST, b"\x00" * 28)
        (n,) = struct.unpack("<I", payload[:4])
        out = []
        for i in range(n):
            out.append(ObjectID(payload[4 + i * 28 : 4 + (i + 1) * 28]))
        return out

    def stats(self) -> dict:
        _, payload = self._request(OP_STATS, b"\x00" * 28)
        used, cap, count = struct.unpack("<QQQ", payload)
        return {"used_bytes": used, "capacity_bytes": cap, "num_objects": count}

    def shutdown_store(self) -> None:
        try:
            self._request(OP_SHUTDOWN, b"\x00" * 28)
        except ConnectionError:
            pass

    def close(self) -> None:
        with self._map_lock:
            mappings = list(self._mappings.values())
            self._mappings.clear()
        for m in mappings:
            m.close()
        with self._wait_lock:
            socks, self._wait_socks = self._wait_socks, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        self._sock.close()

    @staticmethod
    def _map(shm_name: str, size: int, writable: bool) -> mmap.mmap:
        fd = os.open("/dev/shm" + shm_name, os.O_RDWR if writable else os.O_RDONLY)
        try:
            prot = mmap.PROT_READ | (mmap.PROT_WRITE if writable else 0)
            return mmap.mmap(fd, size, prot=prot)
        finally:
            os.close(fd)


class StoreEventSubscriber:
    """Push stream of seal/evict events from the store daemon — the analog
    of plasma's notification socket (reference: plasma clients subscribe for
    sealed-object notifications; the raylet feeds the object directory from
    it). callback(event: int, object_id_bytes: bytes) runs on the reader
    thread; it must be quick or hand off."""

    def __init__(self, socket_path: str, callback):
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.connect(socket_path)
        self._callback = callback
        self._closed = threading.Event()
        msg = struct.pack("<IB", 1 + 28, OP_SUBSCRIBE) + b"\x00" * 28
        self._sock.sendall(msg)
        header = _recv_exact(self._sock, 4)
        (length,) = struct.unpack("<I", header)
        body = _recv_exact(self._sock, length)
        if body[0] != ST_OK:
            raise RuntimeError(f"store subscribe failed: status {body[0]}")
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="store-events"
        )
        self._thread.start()

    def _loop(self) -> None:
        try:
            while not self._closed.is_set():
                header = _recv_exact(self._sock, 4)
                (length,) = struct.unpack("<I", header)
                body = _recv_exact(self._sock, length)
                try:
                    self._callback(body[0], body[1:29])
                except Exception:  # noqa: BLE001 — subscriber must survive
                    pass
        except (ConnectionError, OSError):
            pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
