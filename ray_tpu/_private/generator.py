"""Streaming generator returns: refs yielded as the producer produces them.

Equivalent of the reference's streaming ObjectRefGenerator
(reference: python/ray/_raylet.pyx:957-1043 — num_returns="streaming" tasks
yield; each yielded value becomes its own return object the consumer can
get before the task finishes). Protocol here: the task's return index 0 is
the COMPLETION MARKER (sealed last, holding the yield count — or the error
payload), and yielded values seal at indices 1..n as they are produced, so
the consumer streams by polling value presence and finishes/raises via the
marker.
"""
from __future__ import annotations


from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef


class ObjectRefGenerator:
    """Iterator of ObjectRefs for one streaming task. Yields each value's
    ref as soon as the producer seals it; raises the task's error (from the
    completion marker) and stops after `count` values."""

    def __init__(self, completed_ref: ObjectRef, spec: dict):
        self._completed_ref = completed_ref
        self._task_id = TaskID(spec["task_id"])
        self._spec = spec
        self._i = 1
        self._count: int | None = None

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        if self._count is not None and self._i > self._count:
            raise StopIteration
        oid_i = ObjectID.for_task_return(self._task_id, self._i)
        oid_0 = self._completed_ref.object_id
        while self._count is None:
            # remote producers: keep pulls triggered for both the value and
            # the completion marker
            w._maybe_fetch(oid_i)
            w._maybe_fetch(oid_0)
            # block in the daemon until the value (stream it out eagerly)
            # or the completion marker (count / producer error) seals —
            # the OP_WAIT cv replaces any status busy-polling
            present = w.store.wait_objects([oid_i, oid_0], 1, timeout_ms=200)
            if oid_i.binary() in present:
                break
            if oid_0.binary() in present:
                self._count = int(w.get(self._completed_ref))  # raises errors
                if self._i > self._count:
                    raise StopIteration
                break
        ref = ObjectRef(oid_i)
        # the consumer now owns this value like any task return: lineage for
        # reconstruction, ownership for zero-ref freeing
        with w._task_lock:
            w._lineage[oid_i.binary()] = self._spec
        with w._ref_lock:
            w._owned.add(oid_i.binary())
        self._i += 1
        return ref

    @property
    def completed_ref(self) -> ObjectRef:
        """Ref of the completion marker (count; raises the task's error)."""
        return self._completed_ref
