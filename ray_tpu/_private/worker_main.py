"""Worker process entry point: register with the raylet, execute pushed tasks.

Equivalent of the reference's default_worker.py + the Cython task-execution
loop (reference: python/ray/_private/workers/default_worker.py;
_raylet.pyx:3044 run_task_loop). Spawned by the raylet's worker pool with
RT_* env vars carrying the connection endpoints.
"""
from __future__ import annotations

import os
import queue
import sys


def main() -> None:
    raylet_addr = os.environ["RT_RAYLET_ADDR"]
    store_sock = os.environ["RT_STORE_SOCK"]
    gcs_addr = os.environ["RT_GCS_ADDR"]
    node_id_hex = os.environ["RT_NODE_ID"]
    worker_id_hex = os.environ["RT_WORKER_ID"]

    from ray_tpu._private.ids import JobID, NodeID, WorkerID
    from ray_tpu._private.worker import CoreWorker, set_global_worker

    core = CoreWorker(
        mode="worker",
        gcs_address=gcs_addr,
        raylet_address=raylet_addr,
        store_socket=store_sock,
        job_id=JobID(b"\x00" * 4),  # replaced per-task from the spec
        node_id=NodeID.from_hex(node_id_hex),
        worker_id=WorkerID.from_hex(worker_id_hex),
    )
    set_global_worker(core)

    tasks: queue.Queue = queue.Queue()

    def on_execute(payload):
        tasks.put(payload)

    core.add_notify_handler("execute_task", on_execute)

    core.raylet.call(
        "register_worker", {"worker_id": worker_id_hex, "pid": os.getpid()}
    )

    while True:
        payload = tasks.get()
        if payload is None:
            break
        from ray_tpu._private.ids import JobID as _J

        core.job_id = _J(payload["spec"]["job_id"])
        core.execute_task(payload["spec"], payload.get("chips", []))


if __name__ == "__main__":
    try:
        main()
    except (KeyboardInterrupt, ConnectionError):
        sys.exit(0)
