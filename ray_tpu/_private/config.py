"""Flag table for the runtime.

Equivalent of the reference's RAY_CONFIG macro table
(reference: src/ray/common/ray_config_def.h — 209 flags, overridable via
RAY_<name> env vars and a `_system_config` dict passed at init). Here every
flag is declared once below, overridable via ``RAY_TPU_<NAME>`` env vars or
the ``_system_config`` dict argument to :func:`ray_tpu.init`.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any

_ENV_PREFIX = "RAY_TPU_"


@dataclass
class Config:
    # --- core worker / scheduling ---
    task_retry_delay_ms: int = 100  # backoff before re-running a crashed task
    # --- object store ---
    object_store_memory_bytes: int = 512 * 1024 * 1024
    object_spilling_dir: str = ""  # default: <store socket>.spill
    # per-pass spill batch floor: under pressure the store spills LRU
    # objects until at least this many bytes moved, amortizing disk IO
    # (reference: min_spilling_size, local_object_manager.cc)
    min_spilling_size: int = 8 * 1024 * 1024
    object_pull_chunk_bytes: int = 8 * 1024 * 1024  # inter-node transfer chunk
    # --- raylet ---
    num_workers_soft_limit: int = -1  # default: num_cpus
    # generous: several python workers cold-spawning serially on a loaded
    # single-CPU host can take 5-10s each
    worker_register_timeout_s: int = 60
    # idle task workers beyond this age are reaped down to one warm worker
    # (reference: worker_pool idle killing); generous default — cold spawn
    # costs seconds on a busy host
    kill_idle_workers_interval_ms: int = 5_000
    idle_worker_killing_time_threshold_ms: int = 300_000
    # OOM protection (reference: memory_monitor.h + worker_killing_policy):
    # above the usage threshold the raylet kills task workers, retriable
    # and newest first. 0 disables the monitor.
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_ms: int = 250
    # above this disk-used fraction on the session filesystem the raylet
    # stops starting new tasks (reference: local_fs_capacity_threshold,
    # file_system_monitor.h). 0 disables the check.
    local_fs_capacity_threshold: float = 0.98
    # --- GCS ---
    gcs_heartbeat_interval_ms: int = 1000
    health_check_failure_threshold: int = 5
    # --- actors ---
    actor_creation_timeout_s: int = 60
    # default restart budget for actors created without max_restarts=
    # (actor.py ActorMethod creation spec)
    max_actor_restarts_default: int = 0
    # --- TPU topology ---
    # chips per fake host in the fake_tpu_hosts harness (node.py
    # start_fake_tpu_hosts) — and the documented pod-slice host width
    tpu_chips_per_host_default: int = 4
    # slice-affinity cost model (scheduler.py schedule_bundles): TPU gangs
    # are constrained to one ici-domain only while ICI beats DCN bandwidth
    ici_bandwidth_gbps: float = 400.0
    # --- observability ---
    task_events_buffer_size: int = 10_000
    # raylet node-gauge refresh cadence (raylet.py _metrics_report_loop)
    metrics_report_interval_ms: int = 2000
    # --- testing ---
    # >0: init() also starts this many fake TPU hosts (in-process raylets
    # with TPU resources + pod-slice labels; node.py start_fake_tpu_hosts)
    fake_tpu_hosts: int = 0

    def apply_overrides(self, system_config: dict[str, Any] | None = None) -> None:
        for f in fields(self):
            env_key = _ENV_PREFIX + f.name.upper()
            if env_key in os.environ:
                setattr(self, f.name, _parse(os.environ[env_key], f.type))
        if system_config:
            for key, value in system_config.items():
                if not any(f.name == key for f in fields(self)):
                    raise ValueError(f"Unknown system config key: {key}")
                setattr(self, key, value)


def _parse(raw: str, ftype: Any) -> Any:
    ftype = str(ftype)
    if "int" in ftype:
        return int(raw)
    if "float" in ftype:
        return float(raw)
    if "bool" in ftype:
        return raw.lower() in ("1", "true", "yes")
    try:
        return json.loads(raw)
    except (ValueError, TypeError):
        return raw


_config: Config | None = None


def global_config() -> Config:
    global _config
    if _config is None:
        _config = Config()
        _config.apply_overrides()
    return _config


def reset_config(system_config: dict[str, Any] | None = None) -> Config:
    global _config
    _config = Config()
    _config.apply_overrides(system_config)
    return _config
