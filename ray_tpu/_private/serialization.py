"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Equivalent of the reference's serialization layer
(reference: python/ray/_private/serialization.py — cloudpickle with protocol-5
out-of-band buffers so large numpy arrays are written zero-copy into plasma).
Here the wire format is::

    [u32 nbuf] [u64 meta_len] [meta pickle bytes] [u64 len, buf bytes]*

Large contiguous buffers (numpy arrays, jax host arrays, bytes) are carried
out-of-band so the object-store write path can splice them without copying
through pickle, and the read path can reconstruct arrays as zero-copy views
onto the shared-memory mapping.
"""
from __future__ import annotations

import io
import pickle
import struct
from typing import Any

import cloudpickle

# Buffers smaller than this are kept in-band; the indirection isn't worth it.
_OOB_THRESHOLD = 4096

# Cross-language (XLANG) envelope: the nbuf slot carries this sentinel and
# the meta bytes are msgpack instead of pickle. Non-Python frontends
# (cpp/frontend.cpp) produce and consume ONLY this format — the analog of
# the reference's msgpack cross-language serialization
# (src/ray/common/function_descriptor.h + java/cpp worker serializers).
XLANG_NBUF = 0xFFFFFFFF


def serialize_xlang(value: Any) -> list[bytes]:
    """Serialize msgpack-able values for cross-language consumers."""
    import msgpack

    meta = msgpack.packb(value, use_bin_type=True)
    return [struct.pack("<IQ", XLANG_NBUF, len(meta)), meta]


def serialize(value: Any) -> list[bytes | memoryview]:
    """Serialize to a list of chunks: header + meta + raw buffers.

    Returns a chunk list rather than one bytes object so callers can write
    the chunks straight into a shared-memory allocation without an extra
    concatenation copy.
    """
    buffers: list[pickle.PickleBuffer] = []

    def buffer_callback(pb: pickle.PickleBuffer) -> bool:
        with pb.raw() as m:
            if m.nbytes < _OOB_THRESHOLD:
                return True  # keep small buffers in-band
        buffers.append(pb)
        return False

    meta = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_callback)
    chunks: list[bytes | memoryview] = []
    raw_views = []
    for pb in buffers:
        m = pb.raw()
        raw_views.append(m if m.contiguous else memoryview(bytes(m)))
    header = struct.pack("<IQ", len(raw_views), len(meta))
    chunks.append(header)
    chunks.append(meta)
    for m in raw_views:
        chunks.append(struct.pack("<Q", m.nbytes))
        chunks.append(m)
    return chunks


def serialized_size(chunks: list[bytes | memoryview]) -> int:
    return sum(c.nbytes if isinstance(c, memoryview) else len(c) for c in chunks)


def write_chunks(chunks: list[bytes | memoryview], dest: memoryview) -> None:
    offset = 0
    for c in chunks:
        n = c.nbytes if isinstance(c, memoryview) else len(c)
        dest[offset : offset + n] = c
        offset += n


def dumps(value: Any) -> bytes:
    out = io.BytesIO()
    for c in serialize(value):
        out.write(c)
    return out.getvalue()


def deserialize(data: bytes | memoryview) -> Any:
    """Deserialize from one contiguous buffer, zero-copy for array payloads.

    When ``data`` is a memoryview over shared memory, reconstructed numpy
    arrays alias that memory — the caller must keep the mapping alive for
    the lifetime of the returned object (the ObjectRef pinning does this).
    """
    view = memoryview(data)
    nbuf, meta_len = struct.unpack_from("<IQ", view, 0)
    offset = 12
    meta = view[offset : offset + meta_len]
    if nbuf == XLANG_NBUF:
        import msgpack

        return msgpack.unpackb(bytes(meta), raw=False)
    offset += meta_len
    out_of_band = []
    for _ in range(nbuf):
        (blen,) = struct.unpack_from("<Q", view, offset)
        offset += 8
        out_of_band.append(view[offset : offset + blen])
        offset += blen
    return pickle.loads(meta, buffers=out_of_band)


loads = deserialize
