"""GCS metadata persistence backends.

Equivalent of the reference's pluggable GCS store
(reference: src/ray/gcs/store_client/ — InMemoryStoreClient default
in_memory_store_client.h:31, RedisStoreClient redis_store_client.h:33 for
GCS fault tolerance). The file-backed store plays Redis's role on one host:
the GCS snapshots its tables into it, and a restarted GCS rehydrates from
it (head restart tolerance, SURVEY.md §5.3 GCS FT).
"""
from __future__ import annotations

import os
import pickle
import tempfile


class InMemoryStoreClient:
    """Default: no persistence (reference default)."""

    persistent = False

    def load(self) -> dict | None:
        return None

    def save(self, snapshot: dict) -> None:
        pass


class FileStoreClient:
    """Atomic pickle snapshots at a fixed path."""

    persistent = True

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def load(self) -> dict | None:
        try:
            with open(self.path, "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            return None
        except Exception:  # noqa: BLE001 — torn write from a crash: start fresh
            return None

    def save(self, snapshot: dict) -> None:
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", prefix=".gcs_snap_"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(snapshot, f)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
