"""Cluster scheduling policies: node selection and bundle placement.

Equivalent of the reference's scheduling policy layer
(reference: src/ray/raylet/scheduling/policy/ — hybrid top-k
(hybrid_scheduling_policy.h:50), spread, node-affinity, and the bundle
policies PACK/SPREAD/STRICT_PACK/STRICT_SPREAD
(bundle_scheduling_policy.cc)). TPU-first addition: bundles that request
``TPU`` prefer nodes sharing an ``ici-domain`` label so a gang lands on one
ICI-connected slice (STRICT_PACK over an ICI domain = "slice bundle").
"""
from __future__ import annotations

import ctypes
import os
import random
import threading
from typing import Sequence

_CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "cpp")
_native_lock = threading.Lock()
_native_lib: ctypes.CDLL | bool | None = None  # None=untried, False=unavailable


def _load_native():
    """Build (cached) + load the C++ scheduling core
    (cpp/sched.cpp — the native analog of hybrid_scheduling_policy.h:50)."""
    global _native_lib
    with _native_lock:
        if _native_lib is not None:
            return _native_lib or None
        src = os.path.join(_CPP_DIR, "sched.cpp")
        try:
            from ray_tpu._private.native_build import build_native

            # content-hash gate: a stale committed/restored binary can never
            # be loaded — the artifact path embeds the source digest
            out = build_native(src, "libray_tpu_sched.so",
                               ["-O2", "-shared", "-fPIC"])
            lib = ctypes.CDLL(out)
            lib.rt_pick_node.restype = ctypes.c_int
            lib.rt_pick_node.argtypes = [
                ctypes.POINTER(ctypes.c_double), ctypes.c_int,
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ]
            _native_lib = lib
        except Exception as e:  # noqa: BLE001 — no compiler / load failure
            import sys

            print(
                f"[ray_tpu] native scheduler unavailable ({e!r}); "
                "using the Python policy",
                file=sys.stderr,
            )
            _native_lib = False
        return _native_lib or None


def _pick_node_native(
    resources: dict[str, float],
    nodes: dict[bytes, dict],
    strategy: str,
    local_node_id: bytes | None,
) -> bytes | None:
    lib = _load_native()
    if lib is None:
        return _SENTINEL
    cols = sorted(set(resources) | {"CPU"})
    cpu_col = cols.index("CPU")
    ids = list(nodes)
    if strategy == "spread":
        # the C++ core takes the first node on ties; shuffling the row
        # order restores the Python policy's uniform tie-breaking so
        # spread bursts don't pile onto one node between heartbeats
        random.shuffle(ids)
    n, r = len(ids), len(cols)
    demand = (ctypes.c_double * r)(*[resources.get(c, 0.0) for c in cols])
    avail = (ctypes.c_double * (n * r))()
    total = (ctypes.c_double * (n * r))()
    alive = (ctypes.c_uint8 * n)()
    for i, nid in enumerate(ids):
        node = nodes[nid]
        av = node.get("available", node["resources"])
        tot = node["resources"]
        for j, c in enumerate(cols):
            avail[i * r + j] = av.get(c, 0.0)
            total[i * r + j] = tot.get(c, 0.0)
        alive[i] = 1 if node.get("alive", True) else 0
    local_index = ids.index(local_node_id) if local_node_id in nodes else -1
    idx = lib.rt_pick_node(
        demand, r, avail, total, alive, n, cpu_col,
        1 if strategy == "spread" else 0, local_index,
    )
    return None if idx < 0 else ids[idx]


_SENTINEL = object()  # native path unavailable marker


def fits(resources: dict[str, float], available: dict[str, float]) -> bool:
    return all(available.get(k, 0.0) + 1e-9 >= v for k, v in resources.items())


def subtract(available: dict[str, float], resources: dict[str, float]) -> None:
    for k, v in resources.items():
        available[k] = available.get(k, 0.0) - v


def add(available: dict[str, float], resources: dict[str, float]) -> None:
    for k, v in resources.items():
        available[k] = available.get(k, 0.0) + v


def pick_node(
    resources: dict[str, float],
    nodes: dict[bytes, dict],
    *,
    strategy: str = "default",
    local_node_id: bytes | None = None,
    affinity_node_id: bytes | None = None,
    soft: bool = False,
) -> bytes | None:
    """Pick a node for one task. ``nodes[nid]['available']`` must be present.

    default (hybrid): local node first if it fits, else the *most* loaded
    feasible remote node (pack; reference hybrid policy packs up to a
    threshold before spreading). spread: least-loaded feasible node.
    """
    if strategy in ("default", "spread"):
        # hot path: dense-matrix selection in the C++ core; Python below is
        # the fallback AND the semantics oracle (tests assert equivalence)
        picked = _pick_node_native(resources, nodes, strategy, local_node_id)
        if picked is not _SENTINEL:
            return picked
    feasible = [
        nid
        for nid, n in nodes.items()
        if n.get("alive", True) and fits(resources, n.get("available", n["resources"]))
    ]
    if strategy == "node_affinity":
        if affinity_node_id in feasible:
            return affinity_node_id
        if not soft:
            return None
        # soft affinity falls through to default choice
    if not feasible:
        return None
    if strategy == "spread":
        return max(
            feasible,
            key=lambda nid: _avail_frac(nodes[nid]) + random.random() * 1e-6,
        )
    # default/hybrid
    if local_node_id in feasible:
        return local_node_id
    return min(feasible, key=lambda nid: _avail_frac(nodes[nid]))


def _avail_frac(node: dict) -> float:
    total = node["resources"]
    avail = node.get("available", total)
    cpu_total = total.get("CPU", 1.0) or 1.0
    return avail.get("CPU", 0.0) / cpu_total


def schedule_bundles(
    bundles: Sequence[dict[str, float]],
    strategy: str,
    nodes: dict[bytes, dict],
) -> list[bytes] | None:
    """Map each bundle to a node id, or None if infeasible.

    Reference: bundle_scheduling_policy.cc — PACK (best effort co-locate),
    SPREAD (best effort spread), STRICT_PACK (all on one node),
    STRICT_SPREAD (all on distinct nodes).
    """
    avail = {
        nid: dict(n.get("available", n["resources"]))
        for nid, n in nodes.items()
        if n.get("alive", True)
    }
    if not avail:
        return None

    def tpu_domain(nid: bytes) -> str:
        return nodes[nid].get("labels", {}).get("ici-domain", "")

    # Slice-affinity cost model: keeping a TPU gang on one ici-domain is
    # worth constraining placement only while ICI is actually faster than
    # the datacenter network (config.ici_bandwidth_gbps vs the ~4x25GbE
    # DCN assumption). An operator benchmarking a DCN-as-fast-as-ICI
    # topology sets the flag low and the affinity preference switches off.
    from ray_tpu._private.config import global_config

    _DCN_BANDWIDTH_GBPS = 100.0
    wants_tpu = (
        any(b.get("TPU", 0) > 0 for b in bundles)
        and global_config().ici_bandwidth_gbps > _DCN_BANDWIDTH_GBPS
    )

    if strategy == "STRICT_PACK":
        for nid in sorted(avail, key=lambda n: -sum(avail[n].values())):
            trial = dict(avail[nid])
            if all(_try_place(b, trial) for b in bundles):
                return [nid] * len(bundles)
        return None

    if strategy == "STRICT_SPREAD":
        placement: list[bytes] = []
        used: set[bytes] = set()
        for b in bundles:
            cands = [nid for nid in avail if nid not in used and fits(b, avail[nid])]
            if not cands:
                return None
            if wants_tpu and placement:
                dom = tpu_domain(placement[0])
                same = [c for c in cands if tpu_domain(c) == dom]
                cands = same or cands
            nid = cands[0]
            subtract(avail[nid], b)
            placement.append(nid)
            used.add(nid)
        return placement

    # PACK / SPREAD (best-effort)
    placement = []
    order = (
        sorted(avail, key=lambda n: -sum(avail[n].values()))
        if strategy == "PACK"
        else sorted(avail, key=lambda n: sum(avail[n].values()))
    )
    for b in bundles:
        chosen = None
        cands = [nid for nid in order if fits(b, avail[nid])]
        if wants_tpu and placement:
            dom = tpu_domain(placement[0])
            same = [c for c in cands if tpu_domain(c) == dom]
            cands = same or cands
        if strategy == "PACK":
            # prefer nodes already hosting earlier bundles of this group
            hosting = [c for c in cands if c in placement]
            chosen = (hosting or cands or [None])[0]
        else:
            not_hosting = [c for c in cands if c not in placement]
            chosen = (not_hosting or cands or [None])[0]
        if chosen is None:
            return None
        subtract(avail[chosen], b)
        placement.append(chosen)
    return placement


def _try_place(bundle: dict[str, float], avail: dict[str, float]) -> bool:
    if fits(bundle, avail):
        subtract(avail, bundle)
        return True
    return False
