"""Runtime-env plugin base + the conda / container plugins.

Equivalent of the reference's plugin system (reference:
python/ray/_private/runtime_env/plugin.py:264 RuntimeEnvPlugin — each
plugin owns one runtime_env dict key, creates resources once per distinct
value, and mutates the worker context; conda.py / container plugins build
hermetic interpreter environments). Differences, by design:

- Registration is by importable descriptor ("module:Class") in the
  RAY_TPU_RUNTIME_ENV_PLUGINS env var (comma-separated), resolved at
  worker startup — plugins registered only in a driver's memory could
  never take effect in freshly spawned worker processes.
- `apply(value) -> restore_callable` replaces the reference's
  modify_context indirection: the plugin mutates this process directly
  and returns how to undo it (None = nothing to restore).
- The conda plugin gates on a `conda` binary; the container plugin gates
  on docker/podman. NEITHER tool ships in this build image, so both
  raise actionable errors at VALIDATION time rather than failing deep in
  a worker — the extension point itself is fully exercised by tests via
  a custom plugin.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
import threading
from typing import Any, Callable, Optional

_PLUGIN_ENV_VAR = "RAY_TPU_RUNTIME_ENV_PLUGINS"


class RuntimeEnvPlugin:
    """Base: subclass, set `name` (the runtime_env key you own), and
    implement any of validate/create/apply/delete."""

    name: str = ""
    priority: int = 10  # lower applies first

    def validate(self, value: Any) -> None:
        """Raise ValueError on a malformed value. Called driver-side at
        task/actor declaration, so misconfiguration fails fast."""

    def create(self, value: Any, env_dir: str) -> None:
        """Materialize expensive resources once per distinct value (the
        framework content-hashes `value` and only calls create for a
        cache miss). `env_dir` is this value's private directory."""

    def apply(self, value: Any, env_dir: str) -> Optional[Callable[[], None]]:
        """Mutate THIS worker process for the task; return an undo
        callable (or None)."""
        return None

    def delete(self, env_dir: str) -> None:
        """Release cached resources (GC of stale runtime envs)."""
        shutil.rmtree(env_dir, ignore_errors=True)


_registry: dict[str, RuntimeEnvPlugin] = {}
# RLock: env-var plugin modules call register_plugin() while the loader
# still holds the lock (the load must be COMPLETE before the loaded flag
# becomes visible, or a concurrent validate sees an empty registry)
_registry_lock = threading.RLock()
_env_var_loaded = False


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name:
        raise ValueError("plugin needs a non-empty name")
    with _registry_lock:
        _registry[plugin.name] = plugin


def _load_env_var_plugins() -> None:
    """Resolve "module:Class" descriptors from RAY_TPU_RUNTIME_ENV_PLUGINS
    (reference: RAY_RUNTIME_ENV_PLUGINS env var, plugin.py:36) — this runs
    in every process, so worker processes see the same plugin set as the
    driver that spawned them (env vars propagate through the raylet)."""
    global _env_var_loaded
    import importlib

    with _registry_lock:
        if _env_var_loaded:
            return
        for desc in filter(None,
                           os.environ.get(_PLUGIN_ENV_VAR, "").split(",")):
            mod_name, _, cls_name = desc.strip().partition(":")
            cls = getattr(importlib.import_module(mod_name), cls_name)
            register_plugin(cls())
        _env_var_loaded = True


def get_plugin(name: str) -> Optional[RuntimeEnvPlugin]:
    _load_env_var_plugins()
    with _registry_lock:
        return _registry.get(name)


def plugin_names() -> set:
    _load_env_var_plugins()
    with _registry_lock:
        return set(_registry)


def _plugin_env_dir(plugin: RuntimeEnvPlugin, value: Any) -> str:
    from ray_tpu._private.runtime_env import _runtime_env_root

    key = hashlib.sha1(
        json.dumps(value, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]
    return os.path.join(_runtime_env_root(), "plugins", plugin.name, key)


def apply_plugin(name: str, value: Any) -> Optional[Callable[[], None]]:
    """create-once (content-addressed) + apply. Creation is guarded by the
    same atomic-mkdir lock + failure-breadcrumb pattern as ensure_pip_env:
    concurrent workers on one node must not run plugin.create() into the
    same env_dir, and a failed create must fail waiters fast instead of
    burning their timeout."""
    import time

    plugin = get_plugin(name)
    if plugin is None:
        return None
    env_dir = _plugin_env_dir(plugin, value)
    ready = os.path.join(env_dir, ".plugin_ready")
    failed = os.path.join(env_dir, ".plugin_failed")
    lock_dir = env_dir + ".lock"
    if not os.path.exists(ready):
        os.makedirs(env_dir, exist_ok=True)
        try:
            os.mkdir(lock_dir)  # atomic: we are the creator
            is_creator = True
        except FileExistsError:
            is_creator = False
        if is_creator:
            try:
                if os.path.exists(failed):
                    os.remove(failed)
                plugin.create(value, env_dir)
                with open(ready, "w") as f:
                    f.write("ok")
            except BaseException as e:
                with open(failed, "w") as f:
                    f.write(str(e)[:2000])
                raise
            finally:
                try:
                    os.rmdir(lock_dir)
                except OSError:
                    pass
        else:
            deadline = time.monotonic() + 600
            while not os.path.exists(ready):
                if os.path.exists(failed):
                    with open(failed) as f:
                        raise RuntimeError(
                            f"runtime_env plugin {name!r} create() failed: "
                            f"{f.read()}")
                if not os.path.isdir(lock_dir):
                    # creator vanished without ready/failed: take over
                    return apply_plugin(name, value)
                try:
                    # SIGKILLed creator (no finally ran): steal stale locks
                    # like ensure_pip_env does, keyed on mtime age
                    if time.time() - os.path.getmtime(lock_dir) > 600:
                        try:
                            os.rmdir(lock_dir)
                        except OSError:
                            pass
                        return apply_plugin(name, value)
                except OSError:
                    pass  # lock vanished between the checks: loop re-checks
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"runtime_env plugin {name!r} not ready after 600s")
                time.sleep(0.2)
    return plugin.apply(value, env_dir)


# ---------------------------------------------------------------------------
# in-tree plugins
# ---------------------------------------------------------------------------


class CondaPlugin(RuntimeEnvPlugin):
    """Hermetic conda env per spec (reference:
    _private/runtime_env/conda.py). Gated on a `conda` binary — absent in
    this build image, so validate() raises an actionable error instead of
    workers dying mid-create."""

    name = "conda"
    priority = 5  # interpreter env applies before path-level tweaks

    @staticmethod
    def _conda_bin() -> Optional[str]:
        return shutil.which("conda") or shutil.which("mamba")

    def validate(self, value: Any) -> None:
        if not isinstance(value, (str, dict)):
            raise ValueError(
                "runtime_env conda must be an env NAME (str) or an "
                "environment.yml-style dict")
        if self._conda_bin() is None:
            raise ValueError(
                "runtime_env {'conda': ...} requires a conda/mamba binary "
                "on PATH; this environment has none — use {'pip': [...]}"
                " (venv-based) instead")

    def create(self, value: Any, env_dir: str) -> None:
        conda = self._conda_bin()
        if isinstance(value, dict):
            spec_path = os.path.join(env_dir, "environment.yml")
            with open(spec_path, "w") as f:
                json.dump(value, f)  # yaml is a json superset
            subprocess.run(
                [conda, "env", "create", "-p",
                 os.path.join(env_dir, "env"), "-f", spec_path],
                check=True, capture_output=True)

    def apply(self, value: Any, env_dir: str):
        if isinstance(value, str):
            # named env: resolve its prefix from conda's env table
            out = subprocess.run(
                [self._conda_bin(), "env", "list", "--json"],
                check=True, capture_output=True, text=True)
            prefixes = json.loads(out.stdout).get("envs", [])
            match = [p for p in prefixes if os.path.basename(p) == value]
            if not match:
                raise ValueError(
                    f"conda env {value!r} not found; known envs: "
                    f"{[os.path.basename(p) for p in prefixes]}")
            env_bin = os.path.join(match[0], "bin")
        else:
            env_bin = os.path.join(env_dir, "env", "bin")
        saved = os.environ.get("PATH")
        os.environ["PATH"] = env_bin + os.pathsep + (saved or "")

        def restore():
            if saved is None:
                os.environ.pop("PATH", None)
            else:
                os.environ["PATH"] = saved

        return restore


class ContainerPlugin(RuntimeEnvPlugin):
    """Container image isolation (reference: container plugin in
    _private/runtime_env/container.py — workers launched inside an image).
    Gated on docker/podman; absent here, so validation fails fast with
    the reason."""

    name = "container"

    def validate(self, value: Any) -> None:
        if not isinstance(value, dict) or "image" not in value:
            raise ValueError(
                'runtime_env container needs {"image": "<ref>", ...}')
        if shutil.which("docker") is None and shutil.which("podman") is None:
            raise ValueError(
                "runtime_env {'container': ...} requires docker or podman "
                "on PATH; this environment has neither — container "
                "isolation is unavailable here")


register_plugin(CondaPlugin())
register_plugin(ContainerPlugin())
