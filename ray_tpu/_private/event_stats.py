"""Event stats — latency/count accounting for control-loop operations.

Equivalent of the reference's event_stats (reference:
src/ray/common/asio/instrumented_io_context.h + event_stats.cc — every
posted handler records queueing + run time, surfaced by `ray debug_state`).
Here each timed block records under a dotted name ("rpc.gcs.heartbeat",
"raylet.dispatch"); `snapshot()` feeds the state API / debug dumps.
Process-local by design, like the reference's per-component stats.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager

_lock = threading.Lock()
_stats: dict[str, dict] = {}


def record(name: str, duration_s: float) -> None:
    with _lock:
        s = _stats.get(name)
        if s is None:
            s = _stats[name] = {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
        s["count"] += 1
        ms = duration_s * 1000.0
        s["total_ms"] += ms
        if ms > s["max_ms"]:
            s["max_ms"] = ms


@contextmanager
def timed(name: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record(name, time.perf_counter() - t0)


def snapshot(prefix: str | None = None) -> dict[str, dict]:
    """Current stats; ``prefix`` restricts to one subsystem's dotted
    namespace (e.g. ``"llm."`` for the serve/llm engine's flight-recorder
    dump) without copying the whole table."""
    with _lock:
        out = {}
        for k, v in _stats.items():
            if prefix is not None and not k.startswith(prefix):
                continue
            d = dict(v)
            d["mean_ms"] = d["total_ms"] / d["count"] if d["count"] else 0.0
            out[k] = d
        return out


def reset() -> None:
    with _lock:
        _stats.clear()


def summary_string(limit: int = 30) -> str:
    """Human debug dump, busiest first (the `event_stats` section of the
    reference's debug_state.txt)."""
    snap = snapshot()
    rows = sorted(snap.items(), key=lambda kv: -kv[1]["total_ms"])[:limit]
    lines = [f"{'event':<40} {'count':>8} {'mean_ms':>9} {'max_ms':>9} {'total_ms':>10}"]
    for name, s in rows:
        lines.append(
            f"{name:<40} {s['count']:>8} {s['mean_ms']:>9.2f} "
            f"{s['max_ms']:>9.2f} {s['total_ms']:>10.1f}"
        )
    return "\n".join(lines)
