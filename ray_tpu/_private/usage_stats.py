"""Usage stats — opt-in, LOCAL-ONLY usage reporting.

Equivalent of the reference's usage-stats subsystem (reference:
python/ray/_private/usage/usage_lib.py — schema of cluster metadata +
library-usage tags collected at shutdown). Deliberate deviation: the
reference POSTs the report to a collection server; this implementation
writes it to `<session_dir>/usage_stats.json` and NOWHERE else. There is no
network path in or out — operators who want fleet telemetry ship the file
themselves. Default remains OFF (RAY_TPU_USAGE_STATS_ENABLED=1 to enable),
matching the reference's env-var gate (usage_constant.py).
"""
from __future__ import annotations

import json
import os
import platform
import threading
import time
from typing import Optional

_SCHEMA_VERSION = "0.1"
_lock = threading.Lock()
_library_usages: set[str] = set()
_extra_tags: dict[str, str] = {}


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "0") == "1"


def record_library_usage(library: str) -> None:
    """Called by library entry points (data/train/tune/serve/rllib) —
    no-op unless stats are enabled (reference: record_library_usage)."""
    if not usage_stats_enabled():
        return
    with _lock:
        _library_usages.add(library)


def record_extra_usage_tag(key: str, value: str) -> None:
    if not usage_stats_enabled():
        return
    with _lock:
        _extra_tags[str(key)] = str(value)


def _collect(worker=None) -> dict:
    import ray_tpu

    report = {
        "schema_version": _SCHEMA_VERSION,
        "source": "ray_tpu",
        "ray_tpu_version": ray_tpu.__version__,
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
        "collect_timestamp_ms": int(time.time() * 1000),
        "libraries_used": sorted(_library_usages),
        "extra_usage_tags": dict(_extra_tags),
    }
    try:
        resources = ray_tpu.cluster_resources()
        report["total_num_cpus"] = int(resources.get("CPU", 0))
        report["total_num_tpus"] = int(resources.get("TPU", 0))
        report["total_num_nodes"] = len(ray_tpu.nodes())
    except Exception:  # noqa: BLE001 — collection must never fail a shutdown
        pass
    return report


def write_report(session_dir: Optional[str]) -> Optional[str]:
    """Write the usage report into the session dir (called at node
    shutdown). Returns the path, or None when disabled/no session."""
    if not usage_stats_enabled() or not session_dir:
        return None
    try:
        path = os.path.join(session_dir, "usage_stats.json")
        with open(path, "w") as f:
            json.dump(_collect(), f, indent=2, sort_keys=True)
        return path
    except Exception:  # noqa: BLE001
        return None


def reset_for_tests() -> None:
    with _lock:
        _library_usages.clear()
        _extra_tags.clear()
