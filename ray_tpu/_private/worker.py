"""Core worker — the in-process runtime of every driver and worker.

Equivalent of the reference's CoreWorker
(reference: src/ray/core_worker/core_worker.h — task submission, put/get,
ownership bookkeeping, lineage for reconstruction; Python surface
python/ray/_private/worker.py ray.get/put/wait at :2461/:2590/:2653).

Ownership model (round-1 simplification, documented deviation): results and
errors are sealed into the shared store keyed by deterministic return
ObjectIDs, so `get` is a blocking store read; the owner keeps the task spec
(lineage) for every object it created and resubmits the creating task when
the store reports the object EVICTED (reference: object_recovery_manager.h:41
lineage reconstruction; task specs pinned via reference_count.h lineage
pinning).
"""
from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future
from typing import Any, Sequence

from ray_tpu._private import object_store as osmod
from ray_tpu._private import serialization as ser
from ray_tpu._private import task_spec as ts
from ray_tpu._private.config import global_config
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_ref import ObjectRef, _ErrorPayload
from ray_tpu._private.object_store import ObjectStoreClient
from ray_tpu._private.rpc import RpcClient
from ray_tpu._private.task_spec import _RefMarker
from ray_tpu.exceptions import (
    GetTimeoutError,
    ObjectLostError,
    TaskError,
)

_GET_POLL_MS = 2000  # per-attempt blocking window; between attempts we check
                     # for eviction + lineage reconstruction


class CoreWorker:
    """One per process. mode: 'driver' or 'worker'."""

    def __init__(
        self,
        *,
        mode: str,
        gcs_address: str,
        raylet_address: str,
        store_socket: str,
        job_id: JobID,
        node_id: NodeID,
        worker_id: WorkerID | None = None,
    ):
        self.mode = mode
        self.job_id = job_id
        self.node_id = node_id
        self.worker_id = worker_id or WorkerID.from_random()
        self.task_id = TaskID.for_driver(job_id)  # current task context
        self.store = ObjectStoreClient(store_socket)
        # auto_reconnect: the GCS may restart in place (GCS FT) — the raylet
        # heals its own client in its heartbeat loop; the worker's client
        # must heal too or actor resolution and task events latch dead
        self.gcs = RpcClient(
            gcs_address, notify_handler=self._on_notify, auto_reconnect=True
        )
        self.raylet = RpcClient(raylet_address, notify_handler=self._on_notify)
        self._put_counter = 0
        self._task_lock = threading.Lock()
        # lineage: object_id bytes -> creating task spec (owner-side),
        # LRU-bounded (reference bounds this via lineage ref-counting,
        # reference_count.h lineage pinning; here oldest entries age out and
        # their objects simply become non-reconstructible)
        from collections import OrderedDict

        self._lineage: "OrderedDict[bytes, dict]" = OrderedDict()
        self._lineage_cap = 100_000
        self._inflight_resubmits: set[bytes] = set()
        # ---- ownership & local reference counting ----
        # (reference: reference_count.h:61-115 — local refs per ObjectRef
        # instance, submitted-task argument references, lineage pinned for
        # live refs, zero refs on the owner → free copies cluster-wide)
        self._ref_lock = threading.RLock()  # RLock: __del__ may re-enter
        self._local_refs: dict[bytes, int] = {}
        self._owned: set[bytes] = set()  # oids created by this worker's
        #                                  puts/submits (it may free them)
        self._dep_holds: dict[bytes, int] = {}  # arg refs of in-flight tasks
        self._task_dep_holds: dict[bytes, list[bytes]] = {}  # task -> deps
        # actor bookkeeping (submitter side)
        self._actor_seqnos: dict[bytes, int] = {}
        self._actor_raylet: dict[bytes, str] = {}  # actor_id -> raylet addr
        self._actor_raylet_clients: dict[str, RpcClient] = {}
        self._notify_handlers: dict[str, list] = {}
        self._current_chips: list[int] = []
        self.current_actor_id: ActorID | None = None
        from ray_tpu._private.task_events import TaskEventBuffer

        self.task_events = TaskEventBuffer(
            self.gcs, self.worker_id.hex(), node_id.hex()
        )
        self._stopped = threading.Event()
        threading.Thread(
            target=self._dep_hold_sweep_loop, daemon=True, name="dep-hold-sweep"
        ).start()

    # ---------------- notifications ----------------

    def _on_notify(self, topic: str, payload: Any) -> None:
        for h in self._notify_handlers.get(topic, []):
            h(payload)
        for h in self._notify_handlers.get("*", []):
            h(topic, payload)

    def add_notify_handler(self, topic: str, handler) -> None:
        self._notify_handlers.setdefault(topic, []).append(handler)

    # ---------------- reference counting ----------------

    def add_local_ref(self, oid: bytes) -> None:
        with self._ref_lock:
            self._local_refs[oid] = self._local_refs.get(oid, 0) + 1

    def remove_local_ref(self, oid: bytes) -> None:
        free = False
        with self._ref_lock:
            n = self._local_refs.get(oid, 0) - 1
            if n > 0:
                self._local_refs[oid] = n
            else:
                self._local_refs.pop(oid, None)
                if n == 0 and oid in self._owned and not self._dep_holds.get(oid):
                    self._owned.discard(oid)
                    free = True
        if free:
            self._free_object(oid)

    def _add_dep_holds(self, task_id: bytes, deps: list[bytes]) -> None:
        """Pin task arguments until the task is observed complete — a ref
        the user dropped must survive for the task that consumes it
        (reference: submitted-task references in reference_count.h)."""
        if not deps:
            return
        with self._ref_lock:
            self._task_dep_holds.setdefault(task_id, []).extend(deps)
            for d in deps:
                self._dep_holds[d] = self._dep_holds.get(d, 0) + 1

    def _release_task_dep_holds(self, task_id: bytes) -> None:
        """Called when a task's result is observed (its deps are consumed)."""
        with self._ref_lock:
            deps = self._task_dep_holds.pop(task_id, None)
        if not deps:
            return
        to_free = []
        with self._ref_lock:
            for d in deps:
                n = self._dep_holds.get(d, 0) - 1
                if n > 0:
                    self._dep_holds[d] = n
                else:
                    self._dep_holds.pop(d, None)
                    if (
                        n == 0
                        and not self._local_refs.get(d)
                        and d in self._owned
                    ):
                        self._owned.discard(d)
                        to_free.append(d)
        for d in to_free:
            self._free_object(d)

    def _free_object(self, oid: bytes) -> None:
        """Zero references on the owner: release copies cluster-wide."""
        try:
            self.gcs.call_async("free_object", {"object_id": oid})
        except Exception:  # noqa: BLE001 — shutting down
            pass

    def _dep_hold_sweep_loop(self) -> None:
        """Fire-and-forget tasks are never observed via get()/wait(); their
        argument holds would pin objects forever. Lazily ask the directory
        whether each held task's first return has ever been sealed (or
        freed) and release the holds then."""
        while not self._stopped.wait(5.0):
            with self._ref_lock:
                held = list(self._task_dep_holds)
            for task_id in held:
                oid = ObjectID.for_task_return(TaskID(task_id), 0)
                try:
                    r = self.gcs.call(
                        "get_object_locations", {"object_id": oid.binary()}
                    )
                except Exception:  # noqa: BLE001 — GCS restarting
                    break
                if r.get("known"):
                    self._release_task_dep_holds(task_id)

    # ---------------- object API ----------------

    def put(self, value: Any) -> ObjectRef:
        with self._task_lock:
            self._put_counter += 1
            oid = ObjectID.for_put(self.task_id, self._put_counter)
        self.put_object(oid, value)
        with self._ref_lock:
            self._owned.add(oid.binary())
        return ObjectRef(oid)

    def put_object(self, oid: ObjectID, value: Any, pin: bool = True,
                   xlang: bool = False) -> None:
        # xlang: msgpack envelope readable by non-Python frontends
        # (requested by cross-language task specs — serialization.py)
        chunks = ser.serialize_xlang(value) if xlang else ser.serialize(value)
        size = ser.serialized_size(chunks)
        try:
            buf = self.store.create(oid, size)
        except ValueError:
            # Already exists: a retried task re-putting under the same
            # deterministic id (its crashed predecessor sealed it first) —
            # idempotent success, keep the existing object.
            return
        try:
            ser.write_chunks(chunks, buf)
            # primary copy: pinned atomically at seal so eviction can never
            # lose an object whose owner still holds references; the raylet
            # unpins it when the owner's refs hit zero (free_object).
            # pin=False (streamed values): nobody may ever claim the ref, so
            # they stay LRU-evictable and recover via lineage if consumed.
            self.store.seal(oid, pin=pin)
        except BaseException:
            self.store.discard_pending(oid)
            raise

    def get(self, refs: ObjectRef | Sequence[ObjectRef], timeout: float | None = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        values = [self._get_one(r, deadline) for r in ref_list]
        return values[0] if single else values

    def _maybe_fetch(self, oid: ObjectID, status: str | None = None) -> str | None:
        """If the object is not in the LOCAL store, ask the raylet to pull it
        from a peer node's store (reference: ray.get triggers the raylet's
        PullManager for remote plasma objects). Pass `status` when the caller
        already polled the local store to save the duplicate round-trip.
        Returns the raylet's fetch status ('fetching'|'evicted'|'unknown'|
        'present') or None when no fetch is needed/possible."""
        try:
            st = status if status is not None else self.store.status(oid)
            if st == "present":
                return None
            # "missing" AND "evicted" both go to the raylet: a local
            # tombstone may hide a live copy on another node
            r = self.raylet.call("fetch_object", {"object_id": oid.binary()})
            return r.get("status")
        except Exception:  # noqa: BLE001 — raylet unreachable; keep polling
            return None

    def _get_one(self, ref: ObjectRef, deadline: float | None):
        oid = ref.object_id
        reconstruct_attempts = 0
        if self._maybe_fetch(oid) == "evicted":
            # evicted cluster-wide before we ever saw it
            self._reconstruct(oid)
            time.sleep(0.05)
        while True:
            remaining_ms = _GET_POLL_MS
            if deadline is not None:
                left = (deadline - time.monotonic()) * 1000
                if left <= 0:
                    raise GetTimeoutError(f"get({ref}) timed out")
                remaining_ms = min(remaining_ms, max(1, int(left)))
            try:
                view = self.store.get(oid, timeout_ms=remaining_ms)
            except GetTimeoutError:
                if self._maybe_fetch(oid) == "evicted":
                    self._reconstruct(oid)
                    time.sleep(0.05)
                continue
            if view is osmod.EVICTED:
                # prefer re-pulling a live copy from another node over
                # re-executing the creating task
                st = self._maybe_fetch(oid, status="evicted")
                if st in ("fetching", "present"):
                    time.sleep(0.01)
                    continue
                self._reconstruct(oid)
                # the resubmitted task needs time to run; don't hammer the
                # store socket while it does
                time.sleep(0.05)
                continue
            if view is None:
                continue
            value = ser.deserialize(view)
            if isinstance(value, _ErrorPayload):
                err = value.error
                if (
                    isinstance(err, ObjectLostError)
                    and oid.binary() in self._lineage
                    and reconstruct_attempts < 3
                ):
                    # NOTE: dep holds are NOT released on this branch — the
                    # resubmitted task still needs its argument objects
                    # A dependency of the creating task was evicted and the
                    # raylet failed the task; clear the error payloads and
                    # re-run the lineage (deps reconstructed recursively).
                    reconstruct_attempts += 1
                    spec = self._lineage[oid.binary()]
                    for ret_oid in ts.return_object_ids(spec):
                        self.store.release(ret_oid)
                        self.store.delete(ret_oid)
                    self._reconstruct(oid)
                    time.sleep(0.05)
                    continue
                # terminal error: the creating task is done for good — its
                # argument references can be released
                self._release_task_dep_holds(oid.task_id().binary())
                if isinstance(err, TaskError) and err.cause is not None:
                    raise err.cause from None
                raise err
            # real result observed: the creating task finished
            self._release_task_dep_holds(oid.task_id().binary())
            return value

    def _reconstruct(self, oid: ObjectID) -> None:
        """Resubmit the creating task for an evicted object (lineage
        reconstruction). Recurses through evicted dependencies."""
        spec = self._lineage.get(oid.binary())
        if spec is None:
            raise ObjectLostError(
                f"object {oid} was evicted and this process has no lineage for it"
            )
        key = spec["task_id"]
        with self._task_lock:
            if key in self._inflight_resubmits:
                return
            self._inflight_resubmits.add(key)
        try:
            for dep in spec["arg_deps"]:
                dep_oid = ObjectID(dep)
                # status() rather than get(): our own cached mapping of the
                # dep doesn't help the executing worker — the store must
                # actually hold it again
                if self.store.status(dep_oid) == "evicted":
                    self._reconstruct(dep_oid)
            self.raylet.call("submit_task", {"spec": dict(spec)})
        finally:
            # allow future reconstructions once this one lands
            def _clear():
                time.sleep(1.0)
                with self._task_lock:
                    self._inflight_resubmits.discard(key)

            threading.Thread(target=_clear, daemon=True).start()

    def wait(
        self,
        refs: Sequence[ObjectRef],
        *,
        num_returns: int = 1,
        timeout: float | None = None,
    ) -> tuple[list[ObjectRef], list[ObjectRef]]:
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        ready: list[ObjectRef] = []
        # trigger remote pulls BEFORE the first blocking window: a short
        # (or zero) timeout must still initiate fetches or repeated polls
        # of a remote object would never make progress
        for r in pending:
            self._maybe_fetch(r.object_id)
        while True:
            # one BLOCKING store-side wait per window (the daemon's seal cv
            # wakes us the instant an object lands — no busy-polling); the
            # window bounds how often we re-trigger fetches of objects that
            # live on other nodes
            window_ms = 200
            if deadline is not None:
                left_ms = int((deadline - time.monotonic()) * 1000)
                if left_ms <= 0:
                    window_ms = 0
                else:
                    window_ms = min(window_ms, left_ms)
            present = self.store.wait_objects(
                [r.object_id for r in pending],
                max(1, num_returns - len(ready)),
                timeout_ms=window_ms,
            )
            for r in list(pending):
                if r.object_id.binary() in present:
                    ready.append(r)
                    pending.remove(r)
                    # observed completion releases the task's argument refs
                    # (same as get(); fire-and-forget is swept lazily)
                    self._release_task_dep_holds(r.object_id.task_id().binary())
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            for r in pending:
                self._maybe_fetch(r.object_id)
        return ready, pending

    def as_future(self, ref: ObjectRef) -> Future:
        fut: Future = Future()

        def waiter():
            try:
                fut.set_result(self.get(ref))
            except Exception as e:
                fut.set_exception(e)

        threading.Thread(target=waiter, daemon=True).start()
        return fut

    # ---------------- task submission ----------------

    def new_task_id(self) -> TaskID:
        return TaskID.for_task(self.job_id)

    def submit_task(self, spec: dict) -> list[ObjectRef]:
        """Submit a normal or actor-creation task to the local raylet."""
        refs = [ObjectRef(o) for o in ts.return_object_ids(spec)]
        self.task_events.record(
            task_id=spec["task_id"], job_id=spec["job_id"], name=spec["name"],
            event="SUBMITTED", task_type=spec["type"],
        )
        with self._ref_lock:
            self._owned.update(r.object_id.binary() for r in refs)
        self._add_dep_holds(spec["task_id"], list(spec["arg_deps"]))
        with self._task_lock:
            for r in refs:
                self._lineage[r.object_id.binary()] = spec
            self._trim_lineage_locked()
        self.raylet.call("submit_task", {"spec": spec})
        return refs

    def _trim_lineage_locked(self) -> None:
        """LRU-bound the lineage, but PIN entries whose objects still have
        live references — those must stay reconstructible (reference:
        lineage pinning, reference_count.h:67-115)."""
        attempts = len(self._lineage)
        while len(self._lineage) > self._lineage_cap and attempts > 0:
            attempts -= 1
            oid, spec = self._lineage.popitem(last=False)
            with self._ref_lock:
                live = any(
                    self._local_refs.get(r.binary())
                    or self._dep_holds.get(r.binary())
                    for r in ts.return_object_ids(spec)
                )
            if live:
                self._lineage[oid] = spec  # reinsert at the fresh end

    def submit_actor_task(self, spec: dict, raylet_address: str | None) -> list[ObjectRef]:
        refs = [ObjectRef(o) for o in ts.return_object_ids(spec)]
        # actor tasks get the same SUBMITTED timeline event as normal tasks
        # (reference: task_events cover every task type; without this the
        # state API showed actor calls springing into RUNNING from nowhere)
        self.task_events.record(
            task_id=spec["task_id"], job_id=spec["job_id"], name=spec["name"],
            event="SUBMITTED", task_type=spec["type"],
        )
        with self._ref_lock:
            self._owned.update(r.object_id.binary() for r in refs)
        self._add_dep_holds(spec["task_id"], list(spec["arg_deps"]))
        client = self.raylet
        if raylet_address and raylet_address != self.raylet.address:
            client = self._peer(raylet_address)
        client.call("submit_task", {"spec": spec})
        return refs

    def _peer(self, address: str) -> RpcClient:
        c = self._actor_raylet_clients.get(address)
        if c is None:
            c = RpcClient(address)
            self._actor_raylet_clients[address] = c
        return c

    def next_actor_seqno(self, actor_id: ActorID) -> int:
        with self._task_lock:
            n = self._actor_seqnos.get(actor_id.binary(), 0)
            self._actor_seqnos[actor_id.binary()] = n + 1
            return n

    def actor_raylet_address(self, actor_id: ActorID, timeout: float = None) -> str:
        """Resolve (and cache) which raylet hosts the actor."""
        cfg = global_config()
        timeout = timeout if timeout is not None else cfg.actor_creation_timeout_s
        cached = self._actor_raylet.get(actor_id.binary())
        if cached:
            return cached
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            r = self.gcs.call("get_actor", {"actor_id": actor_id.binary()})
            actor = r["actor"]
            if actor and actor["state"] == "ALIVE" and actor["raylet_address"]:
                self._actor_raylet[actor_id.binary()] = actor["raylet_address"]
                return actor["raylet_address"]
            if actor and actor["state"] == "DEAD":
                from ray_tpu.exceptions import ActorDiedError

                raise ActorDiedError(actor_id.hex(), "actor is dead")
            time.sleep(0.02)
        raise TimeoutError(f"actor {actor_id} not ALIVE within {timeout}s")

    def invalidate_actor_cache(self, actor_id: ActorID) -> None:
        self._actor_raylet.pop(actor_id.binary(), None)

    # ---------------- task execution (worker mode) ----------------

    # method thread pool for max_concurrency > 1 actors (reference:
    # threaded actors via concurrency_group_manager.cc); created at
    # actor creation, None for ordinary serial actors
    _method_pool = None

    def execute_task(self, spec: dict, chips: list[int]) -> None:
        """Run one task and seal its results. Called on the worker's
        execution thread (reference: _raylet.pyx:1457 execute_task)."""
        if spec["type"] == ts.ACTOR_TASK and self._method_pool is not None:
            # concurrent actor: methods overlap on the pool; shared task
            # context (task_id, chips env) stays that of the creation task
            self._method_pool.submit(self._execute_actor_method_concurrent, spec)
            return
        os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chips)
        os.environ["RT_TASK_RESOURCES"] = repr(spec["resources"])
        prev_task = self.task_id
        self.task_id = TaskID(spec["task_id"])
        self._current_chips = chips
        self.task_events.record(
            task_id=spec["task_id"], job_id=spec["job_id"], name=spec["name"],
            event="RUNNING", task_type=spec["type"],
        )
        self._last_task_failed = False
        from ray_tpu._private.runtime_env import applied_runtime_env

        from ray_tpu.util.tracing import task_span

        try:
            with applied_runtime_env(
                spec.get("runtime_env"),
                permanent=spec["type"] == ts.ACTOR_CREATION,
            ), task_span(spec):
                if spec["type"] == ts.ACTOR_CREATION:
                    self._execute_actor_creation(spec)
                elif spec["type"] == ts.ACTOR_TASK:
                    self._execute_actor_method(spec)
                else:
                    self._execute_normal(spec)
        finally:
            self.task_events.record(
                task_id=spec["task_id"], job_id=spec["job_id"],
                name=spec["name"],
                event="FAILED" if self._last_task_failed else "FINISHED",
                task_type=spec["type"],
            )
            self.task_id = prev_task
            self.raylet.call("task_done", {"task_id": spec["task_id"]})

    def _resolve_args(self, spec: dict) -> tuple[tuple, dict]:
        args, kwargs = ser.deserialize(spec["args_blob"])

        def resolve(v):
            if isinstance(v, _RefMarker):
                return self._get_one(ObjectRef(ObjectID(v.object_id_bytes)), None)
            return v

        return tuple(resolve(a) for a in args), {k: resolve(v) for k, v in kwargs.items()}

    def _store_returns(self, spec: dict, result: Any) -> None:
        n = spec["num_returns"]
        oids = ts.return_object_ids(spec)
        if n == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != n:
                raise ValueError(
                    f"task {spec['name']} declared num_returns={n} but returned "
                    f"{len(values)} values"
                )
        xlang = bool(spec.get("xlang"))
        for oid, v in zip(oids, values):
            try:
                self.put_object(oid, v, xlang=xlang)
            except ValueError:
                pass  # duplicate execution (retry landed first) — keep first

    _last_task_failed = False

    def _store_error(self, spec: dict, exc: Exception) -> None:
        self._last_task_failed = True
        err = TaskError.from_exception(spec["name"], exc)
        for oid in ts.return_object_ids(spec):
            try:
                self.put_object(oid, _ErrorPayload(err))
            except ValueError:
                pass

    _function_cache: dict[bytes, Any] = {}

    def _load_function(self, spec: dict):
        fid = spec["function_id"]
        fn = self._function_cache.get(fid)
        if fn is None:
            desc = spec.get("function_desc")
            if spec.get("function_blob"):
                fn = ts.loads_function(spec["function_blob"])
            elif desc:
                # cross-language submission: "module:callable" descriptor
                # instead of a pickled blob (reference:
                # function_descriptor.h PythonFunctionDescriptor)
                import importlib

                mod_name, _, attr = desc.partition(":")
                fn = getattr(importlib.import_module(mod_name), attr)
            else:
                raise ValueError(
                    f"task {spec['name']} has neither function_blob nor "
                    f"function_desc")
            self._function_cache[fid] = fn
        return fn

    def _execute_normal(self, spec: dict) -> None:
        try:
            fn = self._load_function(spec)
            args, kwargs = self._resolve_args(spec)
            if spec.get("streaming"):
                self._execute_streaming(spec, fn, args, kwargs)
                return
            result = fn(*args, **kwargs)
            self._store_returns(spec, result)
        except Exception as e:  # noqa: BLE001 — user code may raise anything
            self._store_error(spec, e)

    def _execute_streaming(self, spec: dict, fn, args, kwargs) -> None:
        """Generator task: seal each yielded value as return index i (the
        consumer's ObjectRefGenerator streams them), then the completion
        marker (count) at index 0 — errors seal into index 0 instead."""
        tid = TaskID(spec["task_id"])
        try:
            n = 0
            for value in fn(*args, **kwargs):
                n += 1
                # unpinned: an unclaimed streamed value must not stay pinned
                # forever — it is LRU-evictable and lineage-recoverable
                self.put_object(ObjectID.for_task_return(tid, n), value, pin=False)
            self._store_returns(spec, n)
        except Exception as e:  # noqa: BLE001
            self._store_error(spec, e)

    # actor instance lives on the worker singleton
    actor_instance: Any = None

    def _execute_actor_creation(self, spec: dict) -> None:
        try:
            cls = self._load_function(spec)
            args, kwargs = self._resolve_args(spec)
            self.actor_instance = cls(*args, **kwargs)
            self.current_actor_id = ActorID(spec["actor_id"])
            n = int(spec.get("max_concurrency", 1) or 1)
            if n > 1:
                from concurrent.futures import ThreadPoolExecutor

                self._method_pool = ThreadPoolExecutor(
                    max_workers=n, thread_name_prefix="actor-method"
                )
            self._store_returns(spec, None)
            self.raylet.call(
                "actor_started",
                {"actor_id": spec["actor_id"], "worker_id": self.worker_id.binary()},
            )
        except Exception as e:  # noqa: BLE001
            self._store_error(spec, e)
            # record the terminal event NOW: os._exit skips every finally
            # and the buffer's flush thread
            self.task_events.record(
                task_id=spec["task_id"], job_id=spec["job_id"],
                name=spec["name"], event="FAILED", task_type=spec["type"],
            )
            self.task_events.stop()
            # leave the actor unstarted; raylet worker-death/timeout paths
            # surface the failure to callers
            os._exit(1)

    def _execute_actor_method(self, spec: dict) -> None:
        try:
            method = getattr(self.actor_instance, spec["method_name"])
            args, kwargs = self._resolve_args(spec)
            if spec.get("streaming"):
                self._execute_streaming(spec, method, args, kwargs)
                return
            result = method(*args, **kwargs)
            self._store_returns(spec, result)
        except Exception as e:  # noqa: BLE001
            self._store_error(spec, e)

    def _execute_actor_method_concurrent(self, spec: dict) -> None:
        """One method on the concurrency pool. Self-contained: no shared
        task-context mutation (other methods are running), its own events,
        its own task_done."""
        self.task_events.record(
            task_id=spec["task_id"], job_id=spec["job_id"], name=spec["name"],
            event="RUNNING", task_type=spec["type"],
        )
        from ray_tpu.util.tracing import task_span

        failed = False
        try:
            method = getattr(self.actor_instance, spec["method_name"])
            args, kwargs = self._resolve_args(spec)
            # task_span: concurrent methods run on pool threads, so each
            # gets its own contextvar scope — a submitter's trace context
            # propagates into streaming replica methods (serve/llm) exactly
            # as it does on the serial path
            with task_span(spec):
                if spec.get("streaming"):
                    # _execute_streaming seals its own error marker, so the
                    # FINISHED/FAILED event below reports FINISHED; the
                    # consumer still sees the error through the completion
                    # marker
                    self._execute_streaming(spec, method, args, kwargs)
                else:
                    result = method(*args, **kwargs)
                    self._store_returns(spec, result)
        except Exception as e:  # noqa: BLE001 — user code may raise anything
            failed = True
            self._store_error(spec, e)
        self.task_events.record(
            task_id=spec["task_id"], job_id=spec["job_id"], name=spec["name"],
            event="FAILED" if failed else "FINISHED", task_type=spec["type"],
        )
        try:
            self.raylet.call("task_done", {"task_id": spec["task_id"]})
        except Exception:  # noqa: BLE001 — raylet shutting down
            pass

    # ---------------- shutdown ----------------

    def shutdown(self) -> None:
        self._stopped.set()
        self.task_events.stop()
        for c in self._actor_raylet_clients.values():
            c.close()
        self.gcs.close()
        self.raylet.close()
        self.store.close()


_global_worker: CoreWorker | None = None
_global_lock = threading.Lock()


def set_global_worker(w: CoreWorker | None) -> None:
    global _global_worker
    from ray_tpu._private import object_ref as _or

    with _global_lock:
        _global_worker = w
        if w is None:
            _or._on_ref_created = None
            _or._on_ref_deleted = None
        else:
            _or._on_ref_created = w.add_local_ref
            _or._on_ref_deleted = w.remove_local_ref


def global_worker() -> CoreWorker:
    if _global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called in this process")
    return _global_worker


def global_worker_or_none() -> CoreWorker | None:
    return _global_worker
