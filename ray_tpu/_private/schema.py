"""Versioned wire schemas for the control plane.

Equivalent in role to the reference's protobuf schema layer (reference:
src/ray/protobuf/*.proto — versioned message definitions compiled into every
RPC surface). This framework's wire format is msgpack dicts (rpc.py); this
module is the single authoritative declaration of those messages:

  * PROTOCOL_VERSION — bumped on any incompatible wire change; enforced by
    the `_handshake` exchange every RpcClient performs on connect (the
    analog of proto compatibility: an old client cannot silently talk to a
    new server).
  * SCHEMAS — per-method required/optional request fields. In strict mode
    (RAY_TPU_STRICT_SCHEMA=1, enabled by the test harness) servers validate
    every inbound payload against its declaration, catching schema drift at
    the boundary instead of as a KeyError deep in a handler.

Unlike protobuf there is no codegen step: msgpack already handles encoding,
so the schema layer is enforcement + documentation, not serialization.
"""
from __future__ import annotations

import os
from typing import Any

# Bump on ANY incompatible change to message shapes or the framing in
# rpc.py. Clients and servers must match exactly (single-version policy:
# a rolling upgrade runs homogeneous binaries, like the reference's
# same-commit requirement for cluster nodes).
PROTOCOL_VERSION = 1


class SchemaError(Exception):
    pass


def _spec(required: str = "", optional: str = "") -> dict:
    return {
        "required": tuple(required.split()) if required else (),
        "optional": tuple(optional.split()) if optional else (),
    }


# Request schemas by service + method. A method absent from its service's
# table is schema-free (payload passed through opaque); list the core
# surface explicitly so drift is caught where it matters.
SCHEMAS: dict[str, dict[str, dict]] = {
    "gcs": {
        "kv_put": _spec("key value", "ns overwrite"),
        "kv_get": _spec("key", "ns"),
        "kv_del": _spec("key", "ns"),
        "kv_keys": _spec("", "ns prefix"),
        "register_node": _spec(
            "node_id address resources", "labels store_socket"
        ),
        "heartbeat": _spec(
            "node_id",
            "available load pending_shapes disk_used_frac seen_seq",
        ),
        "drain_node": _spec("node_id"),
        "get_nodes": _spec(),
        "cluster_resources": _spec(),
        "object_location_update": _spec("node_id events"),
        "free_object": _spec("object_id"),
        "get_object_locations": _spec("object_id"),
        "next_job_id": _spec(),
        "register_actor": _spec("actor_id", "class_name name max_restarts"),
        "update_actor": _spec(
            "actor_id",
            "state node_id raylet_address worker_id increment_restarts",
        ),
        "get_actor": _spec("actor_id"),
        "get_named_actor": _spec("name"),
        "list_actors": _spec(),
        "create_placement_group": _spec("pg_id bundles", "strategy"),
        "remove_placement_group": _spec("pg_id"),
        "get_placement_group": _spec("pg_id"),
        "subscribe": _spec("topic"),
        "unsubscribe": _spec("topic"),
        "publish": _spec("topic payload"),
        "add_task_events": _spec("events"),
        "list_task_events": _spec("job_id", "trace_id limit"),
    },
    "raylet": {
        "pull_object": _spec("object_id", "length offset"),
        "fetch_object": _spec("object_id"),
        "free_object": _spec("object_id"),
        "register_worker": _spec("worker_id", "pid"),
        "submit_task": _spec("spec"),
        "actor_started": _spec("actor_id worker_id"),
        "kill_actor": _spec("actor_id"),
        "task_done": _spec("", "task_id"),
        "prepare_bundle": _spec("pg_id bundle_index resources"),
        "commit_bundle": _spec("pg_id bundle_index"),
        "cancel_bundle": _spec("pg_id bundle_index"),
        "return_bundle": _spec("pg_id bundle_index"),
        "node_stats": _spec(),
    },
}


def strict_mode() -> bool:
    return os.environ.get("RAY_TPU_STRICT_SCHEMA", "0") == "1"


def validate_request(service: str, method: str, payload: Any) -> None:
    """Raise SchemaError when payload does not match the declared shape.
    Only meaningful for dict payloads; other payload types are opaque."""
    table = SCHEMAS.get(service)
    if table is None:
        return
    spec = table.get(method)
    if spec is None:
        return
    if payload is None:
        payload = {}
    if not isinstance(payload, dict):
        raise SchemaError(
            f"{service}.{method}: expected a dict payload, got "
            f"{type(payload).__name__}"
        )
    missing = [k for k in spec["required"] if k not in payload]
    if missing:
        raise SchemaError(f"{service}.{method}: missing fields {missing}")
    allowed = set(spec["required"]) | set(spec["optional"])
    unknown = [k for k in payload if k not in allowed]
    if unknown:
        raise SchemaError(f"{service}.{method}: unknown fields {unknown}")


def handshake_payload() -> dict:
    import ray_tpu

    return {"protocol": PROTOCOL_VERSION, "version": ray_tpu.__version__}


def check_handshake(payload: Any) -> dict:
    """Server side: validate a client hello; raises SchemaError on
    incompatibility. Returns the server's hello."""
    if not isinstance(payload, dict) or "protocol" not in payload:
        raise SchemaError("malformed handshake")
    theirs = payload["protocol"]
    if theirs != PROTOCOL_VERSION:
        raise SchemaError(
            f"protocol version mismatch: peer speaks {theirs}, "
            f"this node speaks {PROTOCOL_VERSION}"
        )
    return handshake_payload()
