"""ObjectRef: the user-facing future/handle to an object in the store.

Equivalent of the reference's ObjectRef (reference: python/ray/_raylet.pyx:252
— C-extension class wrapping an ObjectID with owner metadata; `ray.get`
resolves it, passing it to tasks forms dependencies). Refs are picklable;
deserializing one in another process yields a usable handle because object
resolution goes through the shared store + lineage in the owner.
"""
from __future__ import annotations

from ray_tpu._private.ids import ObjectID

# Reference-counting hooks, installed by worker.set_global_worker: every
# live ObjectRef instance counts as one local reference in the hosting
# CoreWorker (reference: reference_count.h — local refs tracked per ref
# instance; a deserialized ref counts on the borrower's side).
_on_ref_created = None
_on_ref_deleted = None


class ObjectRef:
    __slots__ = ("object_id", "_owner_hint")

    def __init__(self, object_id: ObjectID, owner_hint: str = ""):
        self.object_id = object_id
        self._owner_hint = owner_hint
        cb = _on_ref_created
        if cb is not None:
            try:
                cb(object_id.binary())
            except Exception:  # noqa: BLE001 — never break ref construction
                pass

    def __del__(self):
        cb = _on_ref_deleted
        if cb is not None:
            try:
                cb(self.object_id.binary())
            except Exception:  # noqa: BLE001 — interpreter may be tearing down
                pass

    def hex(self) -> str:
        return self.object_id.hex()

    def binary(self) -> bytes:
        return self.object_id.binary()

    def task_id(self):
        return self.object_id.task_id()

    def __hash__(self):
        return hash(self.object_id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.object_id == self.object_id

    def __repr__(self):
        return f"ObjectRef({self.object_id.hex()})"

    def __reduce__(self):
        return (ObjectRef, (self.object_id, self._owner_hint))

    def future(self):
        """concurrent.futures.Future resolving to the object's value."""
        import ray_tpu

        return ray_tpu.worker.global_worker().as_future(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


class _ErrorPayload:
    """Stored in place of a return value when the task raised/died.

    Reference analog: RayError stored as the object value so every getter
    of any downstream ref observes the failure.
    """

    __slots__ = ("error",)

    def __init__(self, error: Exception):
        self.error = error

    def __reduce__(self):
        return (_ErrorPayload, (self.error,))
