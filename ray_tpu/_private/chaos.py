"""Seeded fault-injection harness for fault-tolerance tests.

Deterministic fault plans replace hand-rolled ``os._exit`` sprinkling:
a plan is a list of (hook site, trigger, action) triples, and
instrumented code calls ``chaos.fire(point, **context)`` at each site —
a no-op unless a plan is active (reference idea: failpoints / Ray's
``_private.test_utils`` fault injection, Podracer's routine-preemption
framing in PAPERS.md: preemption is a first-class, *tested* state).

Hook sites currently instrumented:

  ``engine.step``     — top of every LLMEngine scheduler iteration
  ``engine.prefill``  — before a batched prefill call
  ``engine.decode``   — before a batched decode call
  ``llm.token``       — after LLMDeployment yields one streamed chunk
                        (context: index, resumed, tag)
  ``llm.snapshot``    — before LLMDeployment reports an autoscaling
                        snapshot (delay here simulates a slow/jittery
                        control plane without touching the data plane)
  ``handle.dispatch`` — before the router dispatches a call to a replica
                        (context: method)
  ``replica_drain``   — when a replica enters DRAINING
                        (context: active — in-flight stream count)
  ``controller_scale``— before the controller applies a replica-count
                        change (context: app, deployment, current, target)
  ``controller.checkpoint`` — in the Serve controller, before each
                        crash-recovery checkpoint write to the GCS KV
                        (context: reason, seq — ``raise`` here proves the
                        warn-and-retry degradation)
  ``controller.kill`` — in the Serve controller, after a SUCCESSFUL
                        checkpoint write (context: reason — e.g.
                        ``{"reason": "drain_start"}`` kills mid-drain)
                        and in the replica-created-but-not-yet-
                        checkpointed window (reason: replica_starting,
                        context also: deployment — the deterministic
                        orphan-replica site)
  ``controller.recover`` — top of the restarted controller's _recover()
                        (``delay`` here stretches the outage window so
                        tests can probe the data plane mid-outage)
  ``llm.handoff.seal`` — on a prefill replica after prefill, before the
                        KV blocks are exported/sealed into the object
                        store (context: request_id, attempt, tag —
                        ``kill`` here is the canonical
                        prefill-dies-mid-handoff chaos test)
  ``llm.handoff.fetch``— on a decode replica before it fetches a handoff
                        payload from the object store
                        (context: attempt, tag)
  ``llm.handoff.land`` — on a decode replica after the fetch, before
                        verify+adopt lands the blocks in its pool
                        (context: attempt, tag)
  ``object_store.get`` — top of ObjectStoreClient.get, before the local
                        mmap cache is consulted (context: object_id hex,
                        timeout_ms — ``raise``/``delay`` here make store
                        fetch faults injectable like every other RPC)
  ``llm.kv.demote``   — in PagedKVCache, before an LRU-evicted prefix
                        block's content is captured into the host cache
                        tier (context: block — ``raise`` here proves a
                        failed spill is a lost cache entry, never a
                        correctness event)
  ``llm.kv.promote``  — in the engine, before a batched host->device
                        promotion landing (context: blocks — staged
                        record count)

Plans install either in-process (``install``, for unit tests driving an
engine directly) or via the ``RAY_TPU_CHAOS_PLAN`` environment variable
(JSON; worker processes inherit the environment, so a plan exported
before ``serve.run`` reaches every replica). ``tests/conftest.py``
exposes both paths as the ``chaos_plan`` fixture.

Determinism: triggers are counters and exact-match context filters, and
``FaultPlan.seed`` seeds any randomized action (currently jittered
delays), so a failure schedule replays identically run to run.
"""
from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import asdict, dataclass

ENV_VAR = "RAY_TPU_CHAOS_PLAN"


class ChaosFault(RuntimeError):
    """Raised by a ``raise``-action fault (simulates e.g. a jitted step
    blowing up) and by ``drop`` via its ConnectionError subclass below."""


class ChaosDroppedRPC(ChaosFault, ConnectionError):
    """A ``drop``-action fault: the instrumented RPC never happened."""


@dataclass(frozen=True)
class Fault:
    """One fault: fire ``action`` at hook site ``point``.

    after  — trigger on the Nth *matching* hit (1-based; 0 = first hit).
    when   — exact-match filter on the fire() context (e.g.
             {"index": 3, "resumed": False}); None matches every hit.
    times  — max firings for this fault (None = unlimited).
    arg    — action parameter: delay seconds / jitter ceiling, message.
    """

    point: str
    action: str  # kill | raise | delay | drop
    after: int = 0
    when: dict | None = None
    times: int | None = 1
    arg: float | str | None = None


@dataclass
class FaultPlan:
    seed: int = 0
    faults: tuple = ()

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "faults": [asdict(f) for f in self.faults]}
        )

    @staticmethod
    def from_json(blob: str) -> "FaultPlan":
        raw = json.loads(blob)
        return FaultPlan(
            seed=int(raw.get("seed", 0)),
            faults=tuple(Fault(**f) for f in raw.get("faults", ())),
        )


class _State:
    """Per-process chaos state: the active plan + per-fault counters."""

    def __init__(self, plan: FaultPlan):
        import numpy as np

        self.plan = plan
        self.hits = [0] * len(plan.faults)    # matching-hit counts
        self.fired = [0] * len(plan.faults)   # firings so far
        self.rng = np.random.default_rng(plan.seed)
        self.lock = threading.Lock()


_installed: _State | None = None
_env_state: _State | None = None
_env_checked = False
_mutex = threading.Lock()


def install(plan: FaultPlan) -> FaultPlan:
    """Activate ``plan`` in this process (overrides any env-var plan)."""
    global _installed
    with _mutex:
        _installed = _State(plan)
    return plan


def clear() -> None:
    """Deactivate the in-process plan (an env-var plan, if any, resumes)."""
    global _installed, _env_state, _env_checked
    with _mutex:
        _installed = None
        # re-read the env next fire(): the fixture may have unset it
        _env_state = None
        _env_checked = False


def _active() -> _State | None:
    global _env_state, _env_checked
    if _installed is not None:
        return _installed
    if not _env_checked:
        with _mutex:
            if not _env_checked:
                blob = os.environ.get(ENV_VAR)
                if blob:
                    try:
                        _env_state = _State(FaultPlan.from_json(blob))
                    except Exception:  # noqa: BLE001 — bad plan = no chaos
                        _env_state = None
                _env_checked = True
    return _installed or _env_state


def fire(point: str, **context) -> None:
    """Hook-site entry: trigger any matching active faults. No-op (one
    attribute read + one env check, once) when no plan is active."""
    state = _active()
    if state is None:
        return
    for i, f in enumerate(state.plan.faults):
        if f.point != point:
            continue
        if f.when and any(context.get(k) != v for k, v in f.when.items()):
            continue
        with state.lock:
            state.hits[i] += 1
            if f.after and state.hits[i] < f.after:
                continue
            if f.times is not None and state.fired[i] >= f.times:
                continue
            state.fired[i] += 1
        _act(f, state)


def _act(f: Fault, state: _State) -> None:
    if f.action == "delay":
        base = float(f.arg or 0.1)
        # seeded jitter keeps schedules deterministic yet non-degenerate
        time.sleep(base if f.times == 1 else base * (0.5 + state.rng.random()))
    elif f.action == "raise":
        raise ChaosFault(str(f.arg or f"chaos fault at {f.point}"))
    elif f.action == "drop":
        raise ChaosDroppedRPC(str(f.arg or f"chaos dropped rpc at {f.point}"))
    elif f.action == "kill":
        os._exit(1)
    else:
        raise ValueError(f"unknown chaos action {f.action!r}")
