"""Task specification: the unit handed from owners to raylets to workers.

Equivalent of the reference's TaskSpecification
(reference: src/ray/common/task/task_spec.h:244 — protobuf-backed spec with
function descriptor, args, resources, scheduling strategy, actor fields).
Here the spec is a msgpack-able dict built/validated by this module.

Top-level ObjectRef args are replaced by dependency markers and resolved to
values by the executing worker (reference semantics: dependency_resolver.cc
inlines resolved args); nested refs stay refs.
"""
from __future__ import annotations

import hashlib
from typing import Any

import cloudpickle

from ray_tpu._private import serialization as ser
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID

NORMAL = "normal"
ACTOR_CREATION = "actor_creation"
ACTOR_TASK = "actor_task"

# Scheduling strategy types (reference: policy/scheduling_options.h:30-102).
SCHED_DEFAULT = "default"  # hybrid: prefer local, spill when saturated
SCHED_SPREAD = "spread"
SCHED_NODE_AFFINITY = "node_affinity"


def function_id(func_blob: bytes) -> bytes:
    return hashlib.sha1(func_blob).digest()[:16]


def make_task_spec(
    *,
    task_id: TaskID,
    job_id: JobID,
    name: str,
    task_type: str = NORMAL,
    function_blob: bytes | None = None,
    method_name: str | None = None,
    args: tuple = (),
    kwargs: dict | None = None,
    num_returns: int = 1,
    streaming: bool = False,
    resources: dict[str, float] | None = None,
    actor_id: ActorID | None = None,
    seqno: int = 0,
    max_retries: int = 0,
    placement: dict | None = None,
    scheduling: dict | None = None,
    runtime_env: dict | None = None,
    max_restarts: int = 0,
    max_concurrency: int = 1,
    owner_address: str = "",
) -> dict:
    from ray_tpu._private.object_ref import ObjectRef  # circular import

    arg_deps: list[bytes] = []
    proc_args = []
    for a in args:
        if isinstance(a, ObjectRef):
            arg_deps.append(a.object_id.binary())
            proc_args.append(_RefMarker(a.object_id.binary()))
        else:
            proc_args.append(a)
    proc_kwargs = {}
    for k, v in (kwargs or {}).items():
        if isinstance(v, ObjectRef):
            arg_deps.append(v.object_id.binary())
            proc_kwargs[k] = _RefMarker(v.object_id.binary())
        else:
            proc_kwargs[k] = v

    args_blob = ser.dumps((tuple(proc_args), proc_kwargs))
    return {
        "task_id": task_id.binary(),
        "job_id": job_id.binary(),
        "name": name,
        "type": task_type,
        "function_blob": function_blob,
        "function_id": function_id(function_blob) if function_blob else b"",
        "method_name": method_name,
        "args_blob": args_blob,
        "arg_deps": arg_deps,
        "num_returns": num_returns,
        # streaming: yielded values seal at return indices 1..n as produced;
        # index 0 is the completion marker (count or error) — reference:
        # streaming generator returns, _raylet.pyx:957-1043
        "streaming": streaming,
        "resources": resources or {"CPU": 1.0},
        "actor_id": actor_id.binary() if actor_id else None,
        "seqno": seqno,
        "max_retries": max_retries,
        "retry_count": 0,
        "placement": placement,
        "scheduling": scheduling or {"type": SCHED_DEFAULT},
        "runtime_env": runtime_env,
        "max_restarts": max_restarts,
        "max_concurrency": max_concurrency,
        "owner_address": owner_address,
    }


class _RefMarker:
    """Placeholder for a top-level ObjectRef arg; replaced before execution."""

    __slots__ = ("object_id_bytes",)

    def __init__(self, object_id_bytes: bytes):
        self.object_id_bytes = object_id_bytes

    def __reduce__(self):
        return (_RefMarker, (self.object_id_bytes,))


def return_object_ids(spec: dict) -> list[ObjectID]:
    tid = TaskID(spec["task_id"])
    return [
        ObjectID.for_task_return(tid, i) for i in range(spec["num_returns"])
    ]


def dumps_function(func: Any) -> bytes:
    return cloudpickle.dumps(func)


def loads_function(blob: bytes) -> Any:
    return cloudpickle.loads(blob)
