"""Runtime environments: per-task/actor env application.

Equivalent of the reference's runtime_env subsystem, narrowed to the
single-host fields (reference: python/ray/runtime_env/ +
python/ray/_private/runtime_env/ — plugin base plugin.py:264; the
conda/pip/container plugins need an agent + package store and are out of
scope this round; design doc python/ray/runtime_env/ARCHITECTURE.md).

Supported fields:
  * env_vars: {name: value} — set for the task's duration (actor lifetime
    for actor-creation tasks, since the process is dedicated).
  * working_dir: local directory — cwd for the task's duration. Local path
    only (the reference ships zips through its GCS package store).
  * py_modules: list of local dirs prepended to sys.path.
"""
from __future__ import annotations

import contextlib
import os
import sys

_KNOWN = {"env_vars", "working_dir", "py_modules"}


def validate_runtime_env(env: dict | None) -> None:
    if not env:
        return
    unknown = set(env) - _KNOWN
    if unknown:
        raise ValueError(
            f"unsupported runtime_env fields {sorted(unknown)}; supported: "
            f"{sorted(_KNOWN)}"
        )
    wd = env.get("working_dir")
    if wd is not None and not os.path.isdir(wd):
        raise ValueError(f"runtime_env working_dir {wd!r} is not a directory")


@contextlib.contextmanager
def applied_runtime_env(env: dict | None, *, permanent: bool = False):
    """Apply env for the duration of the block; `permanent=True` (actor
    creation — the worker process is dedicated to the actor) skips the
    restore so the environment outlives the creation task."""
    if not env:
        yield
        return
    saved_env: dict[str, str | None] = {}
    saved_cwd = None
    saved_path = None
    for k, v in (env.get("env_vars") or {}).items():
        saved_env[k] = os.environ.get(k)
        os.environ[k] = str(v)
    wd = env.get("working_dir")
    if wd:
        saved_cwd = os.getcwd()
        os.chdir(wd)
    mods = env.get("py_modules") or []
    if mods:
        saved_path = list(sys.path)
        for m in reversed(mods):
            sys.path.insert(0, m)
    try:
        yield
    finally:
        if not permanent:
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            if saved_cwd is not None:
                os.chdir(saved_cwd)
            if saved_path is not None:
                sys.path[:] = saved_path
