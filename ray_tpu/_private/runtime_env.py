"""Runtime environments: per-task/actor env application.

Equivalent of the reference's runtime_env subsystem (reference:
python/ray/runtime_env/ + python/ray/_private/runtime_env/ — plugin base
plugin.py:264, pip plugin pip.py; design doc
python/ray/runtime_env/ARCHITECTURE.md).

Supported fields:
  * env_vars: {name: value} — set for the task's duration (actor lifetime
    for actor-creation tasks, since the process is dedicated).
  * working_dir: local directory — cwd for the task's duration. Local path
    only (the reference ships zips through its GCS package store).
  * py_modules: list of local dirs prepended to sys.path.
  * pip: list of requirement specs, or {"packages": [...],
    "pip_install_options": [...]} — materialized ONCE per unique spec as a
    content-addressed venv under RAY_TPU_RUNTIME_ENV_DIR
    (~/.ray_tpu/runtime_envs by default) whose site-packages is injected
    onto sys.path for the task. Deviation from the reference (pip.py swaps
    the worker's interpreter for the venv python): injection keeps the
    already-warm worker process — and its loaded jax/XLA runtime — alive,
    which matters on TPU where backend re-init costs seconds.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import subprocess
import sys
import time

_KNOWN = {"env_vars", "working_dir", "py_modules", "pip"}


def validate_runtime_env(env: dict | None) -> None:
    if not env:
        return
    from ray_tpu._private import runtime_env_plugin as rep

    unknown = set(env) - _KNOWN - rep.plugin_names()
    if unknown:
        raise ValueError(
            f"unsupported runtime_env fields {sorted(unknown)}; supported: "
            f"{sorted(_KNOWN | rep.plugin_names())}"
        )
    wd = env.get("working_dir")
    if wd is not None and not os.path.isdir(wd):
        raise ValueError(f"runtime_env working_dir {wd!r} is not a directory")
    pip = env.get("pip")
    if pip is not None:
        if isinstance(pip, dict):
            if "packages" not in pip:
                raise ValueError('runtime_env pip dict needs a "packages" key')
        elif not isinstance(pip, (list, tuple)):
            raise ValueError("runtime_env pip must be a list or dict")
    for key in set(env) - _KNOWN:
        plugin = rep.get_plugin(key)
        if plugin is not None:
            plugin.validate(env[key])


# ---------------------------------------------------------------------------
# pip venvs — content-addressed, created once, shared by all workers
# ---------------------------------------------------------------------------


def _runtime_env_root() -> str:
    return os.environ.get(
        "RAY_TPU_RUNTIME_ENV_DIR",
        os.path.join(os.path.expanduser("~"), ".ray_tpu", "runtime_envs"),
    )


def _pip_spec(pip) -> tuple[list[str], list[str]]:
    if isinstance(pip, dict):
        return list(pip["packages"]), list(pip.get("pip_install_options", []))
    return list(pip), []


def ensure_pip_env(pip) -> str:
    """Create (or reuse) the venv for this pip spec; returns its
    site-packages directory. Concurrent creators race on an atomic mkdir;
    losers wait for the winner's .ready marker."""
    packages, options = _pip_spec(pip)
    key = hashlib.sha1(
        json.dumps([packages, options, sys.version_info[:2]],
                   sort_keys=True).encode()
    ).hexdigest()[:16]
    env_dir = os.path.join(_runtime_env_root(), "pip", key)
    ready = os.path.join(env_dir, ".ready")
    site = os.path.join(
        env_dir, "lib",
        f"python{sys.version_info[0]}.{sys.version_info[1]}", "site-packages",
    )
    if os.path.exists(ready):
        return site
    os.makedirs(os.path.dirname(env_dir), exist_ok=True)
    lock_dir = env_dir + ".lock"
    failed = os.path.join(env_dir, ".failed")
    try:
        os.mkdir(lock_dir)  # atomic: we are the creator
    except FileExistsError:
        deadline = time.monotonic() + 300
        while not os.path.exists(ready):
            if os.path.exists(failed):
                with open(failed) as f:
                    raise RuntimeError(
                        f"pip runtime_env {key} failed to build: {f.read()}")
            # a creator killed mid-install leaves the lock forever: steal
            # stale locks (no .ready/.failed and no mtime progress) and
            # retry the build ourselves
            lock_alive = True
            try:
                age = time.time() - os.path.getmtime(lock_dir)
            except OSError:
                # lock vanished: winner just finished (ready lands next
                # poll) OR crashed between rmdir and ready — retry the
                # build ourselves rather than waiting on nothing
                lock_alive = False
                age = 0.0
            if not lock_alive and not os.path.exists(ready):
                return ensure_pip_env(pip)
            if age > 600:
                with contextlib.suppress(OSError):
                    os.rmdir(lock_dir)
                return ensure_pip_env(pip)
            if time.monotonic() > deadline:
                # a live creator refreshes the lock mtime every 30s; a long
                # (>5 min) but progressing install must not strand waiters —
                # extend the deadline while progress is visible
                if lock_alive and age < 120:
                    deadline = time.monotonic() + 120
                else:
                    raise TimeoutError(
                        f"pip runtime_env {key} not ready after 300s "
                        f"with no creator progress for {int(age)}s")
            time.sleep(0.2)
        return site
    try:
        # --system-site-packages: jax/numpy stay importable (reference pip
        # plugin default); venv pip itself installs only the requested specs
        with contextlib.suppress(OSError):
            os.remove(failed)  # we are rebuilding after a prior failure
        subprocess.run(
            [sys.executable, "-m", "venv", "--clear",
             "--system-site-packages", env_dir],
            check=True, capture_output=True,
        )
        # when THIS interpreter is itself a venv, --system-site-packages
        # exposes the base python, not our site-packages — bridge them in
        # with a .pth so build backends (setuptools) resolve inside the env
        os.makedirs(site, exist_ok=True)
        parent_sites = [p for p in sys.path if p.endswith("site-packages")]
        if parent_sites:
            with open(os.path.join(site, "_parent_site.pth"), "w") as f:
                f.write("\n".join(parent_sites) + "\n")
        vpy = os.path.join(env_dir, "bin", "python")
        cmd = [vpy, "-m", "pip", "install", "--no-warn-script-location",
               *options, *packages]
        # touch the lock while pip runs so waiters see mtime progress and
        # never steal the lock from a live (just slow) build; output goes
        # to a log file (a PIPE left undrained deadlocks chatty installs)
        log_path = os.path.join(env_dir, "pip_install.log")
        with open(log_path, "w") as log:
            proc = subprocess.Popen(cmd, stdout=log,
                                    stderr=subprocess.STDOUT, text=True)
            while True:
                try:
                    rc = proc.wait(timeout=30)
                    break
                except subprocess.TimeoutExpired:
                    with contextlib.suppress(OSError):
                        os.utime(lock_dir)
        if rc != 0:
            with open(log_path) as f:
                tail = f.read()[-2000:]
            raise RuntimeError(
                f"pip install failed for runtime_env {packages}: {tail}")
        with open(ready, "w") as f:
            f.write(json.dumps({"packages": packages, "options": options}))
        return site
    except BaseException as e:
        # leave a breadcrumb so concurrent waiters fail fast with the real
        # error instead of burning their full timeout
        with contextlib.suppress(OSError):
            os.makedirs(env_dir, exist_ok=True)
            with open(failed, "w") as f:
                f.write(str(e)[:2000])
        raise
    finally:
        with contextlib.suppress(OSError):
            os.rmdir(lock_dir)


@contextlib.contextmanager
def applied_runtime_env(env: dict | None, *, permanent: bool = False):
    """Apply env for the duration of the block; `permanent=True` (actor
    creation — the worker process is dedicated to the actor) skips the
    restore so the environment outlives the creation task."""
    if not env:
        yield
        return
    saved_env: dict[str, str | None] = {}
    saved_cwd = None
    saved_path = None
    plugin_restores: list = []
    # EVERY mutation happens inside the try: a failure mid-setup (a pip
    # install, a plugin create) must still restore the mutations already
    # made — a pooled worker keeps running other tasks afterwards
    try:
        for k, v in (env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = str(v)
        wd = env.get("working_dir")
        if wd:
            saved_cwd = os.getcwd()
            os.chdir(wd)
        mods = list(env.get("py_modules") or [])
        if env.get("pip"):
            mods.append(ensure_pip_env(env["pip"]))
        if mods:
            saved_path = list(sys.path)
            for m in reversed(mods):
                sys.path.insert(0, m)
        # plugin-owned keys (conda/container/custom — runtime_env_plugin.py):
        # create-once resources + per-task process mutation with undo
        from ray_tpu._private import runtime_env_plugin as rep

        for key in sorted(
                set(env) - _KNOWN,
                key=lambda k: getattr(rep.get_plugin(k), "priority", 10)):
            restore = rep.apply_plugin(key, env[key])
            if restore is not None:
                plugin_restores.append(restore)
        yield
    finally:
        if not permanent:
            for restore in reversed(plugin_restores):
                try:
                    restore()
                except Exception:  # noqa: BLE001 — restore is best-effort
                    pass
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            if saved_cwd is not None:
                os.chdir(saved_cwd)
            if saved_path is not None:
                sys.path[:] = saved_path
