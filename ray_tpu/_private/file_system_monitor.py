"""Disk-capacity monitoring for node health.

Equivalent of the reference's FileSystemMonitor (reference:
src/ray/common/file_system_monitor.h — periodic statvfs over the session
paths; OverCapacity() makes the raylet refuse new work so a disk-full node
degrades instead of corrupting spills/checkpoints). The reader is
injectable for tests.
"""
from __future__ import annotations

import os
from typing import Callable, Iterable


def disk_usage(path: str) -> tuple[int, int] | None:
    """(used_bytes, total_bytes) for the filesystem holding `path`."""
    try:
        st = os.statvfs(path)
    except OSError:
        return None
    total = st.f_frsize * st.f_blocks
    free = st.f_frsize * st.f_bavail
    return total - free, total


class FileSystemMonitor:
    """Threshold check over one or more paths (reference:
    file_system_monitor.h OverCapacity)."""

    def __init__(
        self,
        paths: Iterable[str],
        capacity_threshold: float = 0.95,
        read_fn: Callable[[str], tuple[int, int] | None] | None = None,
        cache_ttl_s: float = 0.0,
    ):
        self.paths = [p for p in paths if p]
        self.capacity_threshold = capacity_threshold
        self._read = read_fn or disk_usage
        # cache_ttl_s > 0: amortize the statvfs syscalls for callers on hot
        # paths (the raylet dispatch loop runs per task wakeup; the
        # reference monitor likewise polls on an interval)
        self._ttl = cache_ttl_s
        self._cached: float | None = None
        self._cached_at = float("-inf")

    def usage_fraction(self) -> float | None:
        """Max used/total across the watched paths (None if unreadable)."""
        import time

        if self._ttl > 0 and time.monotonic() - self._cached_at < self._ttl:
            return self._cached
        worst = None
        for p in self.paths:
            r = self._read(p)
            if not r or r[1] <= 0:
                continue
            frac = r[0] / r[1]
            worst = frac if worst is None else max(worst, frac)
        if self._ttl > 0:
            self._cached = worst
            self._cached_at = time.monotonic()
        return worst

    def over_capacity(self) -> bool:
        if self.capacity_threshold <= 0:
            return False
        frac = self.usage_fraction()
        return frac is not None and frac > self.capacity_threshold
