"""Raylet — the per-node manager: worker pool, local scheduling, actors.

Equivalent of the reference's raylet daemon
(reference: src/ray/raylet/ — NodeManager RPC surface (node_manager.h:125),
WorkerPool fork/register/reuse (worker_pool.h:80), LocalTaskManager dispatch
+ spillback (local_task_manager.cc:105), DependencyManager, placement-group
bundle resources (placement_group_resource_manager.h), and the 2-phase PG
prepare/commit handlers (node_manager.cc:1832,1848)).

Differences from the reference, deliberate for round 1:
  * Tasks are pushed raylet→worker over the worker's registered control
    connection rather than leased-then-pushed owner→worker; the raylet stays
    on the dispatch path (the reference takes it off the data path via
    worker leases, direct_task_transport.cc:134 — planned optimization).
  * Worker-crash retries run raylet-side using the spec's max_retries
    (the reference drives retries from the owner's TaskManager).
  * Completion signaling rides the shared object store: results (or error
    payloads) are sealed into the return objects, unblocking any getter.

TPU-first: ``TPU`` is a predefined resource with per-chip assignment — a
dispatched task gets ``TPU_VISIBLE_CHIPS`` set the way the reference sets
``CUDA_VISIBLE_DEVICES`` (reference: python/ray/_private/utils.py:462
TPU_VISIBLE_CHIPS handling; worker.py:430 GPU analog).
"""
from __future__ import annotations

import heapq
import os
import subprocess
import sys
import threading
import time
from typing import Any

from ray_tpu._private import object_store as osmod
from ray_tpu._private import scheduler as sched
from ray_tpu._private import serialization as ser
from ray_tpu._private import task_spec as ts
from ray_tpu._private.config import global_config
from ray_tpu._private.ids import NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_ref import _ErrorPayload
from ray_tpu._private.object_store import ObjectStoreClient, StoreEventSubscriber
from ray_tpu._private.rpc import RpcClient, RpcServer
from ray_tpu.exceptions import ActorDiedError, WorkerCrashedError


class WorkerHandle:
    def __init__(self, worker_id: bytes, proc: subprocess.Popen | None):
        self.worker_id = worker_id
        self.proc = proc
        self.conn = None  # set at registration
        self.registered = threading.Event()
        self.current_task: dict | None = None
        self.is_actor_worker = False
        self.actor_id: bytes | None = None
        self.last_idle = time.monotonic()
        self.task_started = 0.0  # dispatch time of current_task
        self.assigned_chips: list[int] = []
        # memory-monitor kill attribution: (reason, task_id it was running)
        self.oom_killed: tuple[str, bytes] | None = None


_node_gauges_cache = None
_node_gauges_lock = threading.Lock()


def _node_gauges():
    """Process-singleton node gauge families: in-process Cluster tests run
    several raylets per process and prometheus_client rejects duplicate
    registrations — nodes are distinguished by the `node` label instead."""
    global _node_gauges_cache
    with _node_gauges_lock:
        if _node_gauges_cache is None:
            try:
                from ray_tpu.util.metrics import Gauge

                _node_gauges_cache = (
                    Gauge("ray_tpu_node_resource_available",
                          "available per resource", ("node", "resource")),
                    Gauge("ray_tpu_node_tasks_queued",
                          "tasks waiting for dispatch", ("node",)),
                    Gauge("ray_tpu_node_workers",
                          "live worker processes", ("node",)),
                )
            except Exception:  # noqa: BLE001 — prometheus_client missing
                _node_gauges_cache = False
        return _node_gauges_cache or None


class Raylet:
    # strict-mode wire validation against schema.SCHEMAS["raylet"] (rpc.py)
    schema_service = "raylet"

    def __init__(
        self,
        node_id: NodeID,
        gcs_address: str,
        store_socket: str,
        resources: dict[str, float],
        labels: dict[str, str] | None = None,
    ):
        self.node_id = node_id
        self.gcs_address = gcs_address
        self.store_socket = store_socket
        self.resources = dict(resources)
        self.labels = labels or {}
        self.available = dict(resources)
        cfg = global_config()
        self._soft_limit = (
            cfg.num_workers_soft_limit
            if cfg.num_workers_soft_limit > 0
            else max(1, int(resources.get("CPU", 1)))
        )

        self._lock = threading.RLock()
        self._dispatch_cv = threading.Condition(self._lock)
        # TPU chip slots for assignment
        self._free_chips = list(range(int(resources.get("TPU", 0))))
        self._idle_workers: list[WorkerHandle] = []
        self._all_workers: dict[bytes, WorkerHandle] = {}
        self._queued: list[dict] = []  # task specs waiting for deps/resources
        self._missing_deps: dict[bytes, set[bytes]] = {}  # task_id -> dep oids
        # actor_id -> actor record
        self._actors: dict[bytes, dict] = {}
        # pg_id -> bundle_index -> {"resources", "state", "used"}
        self._bundles: dict[bytes, dict[int, dict]] = {}
        self._peer_clients: dict[str, RpcClient] = {}
        self._actor_seq = 0  # tie-breaker for the per-actor method heap
        self._cluster_view: dict[bytes, dict] = {}
        self._cluster_seq = 0  # highest node-table version applied (delta sync)
        self._stopped = threading.Event()
        # disk-full protection: when the session filesystem crosses the
        # threshold the dispatch loop stops STARTING work (queued tasks
        # wait; running ones finish) — reference file_system_monitor.h
        from ray_tpu._private.file_system_monitor import FileSystemMonitor

        self._fs_monitor = FileSystemMonitor(
            [os.path.dirname(store_socket) if store_socket else ""],
            cfg.local_fs_capacity_threshold,
            cache_ttl_s=0.25,  # dispatch runs per task wakeup: amortize
        )
        # inter-node object plane state
        self._fetching: set[bytes] = set()  # pulls in flight
        self._dep_fetch_ts: dict[bytes, float] = {}  # dep oid -> last fetch req
        self._fetch_neg_ts: dict[bytes, float] = {}  # oid -> last unknown-result
        # primary-copy pinning (reference: raylet pins objects for live refs,
        # node_manager.cc:2416 PinObjectIDs): objects SEALED on this node are
        # pinned until the owner frees them; objects PULLED here are
        # secondary copies and stay LRU-evictable
        self._secondary: set[bytes] = set()  # oids being pulled (skip pin)
        self._pinned: set[bytes] = set()
        # pending directory updates: ordered ("s"|"e", oid) pairs — order
        # matters (evict-then-reseal within one batch must end as present)
        self._dir_pending: list[tuple[str, bytes]] = []
        self._dir_event = threading.Event()

        self.store = ObjectStoreClient(store_socket)
        self.gcs = RpcClient(gcs_address)
        self.server = RpcServer(self)
        self.address = self.server.address
        # Feed the GCS object directory from the store's seal/evict stream
        # (reference: the raylet learns sealed objects from plasma's
        # notification socket and the directory resolves locations,
        # object_manager/ownership_based_object_directory.cc:551).
        self._store_events = StoreEventSubscriber(store_socket, self._on_store_event)

        self.gcs.call(
            "register_node",
            {
                "node_id": node_id.binary(),
                "address": self.address,
                "resources": self.resources,
                "labels": self.labels,
                "store_socket": store_socket,
            },
        )
        # push-path of the delta syncer: node-table changes arrive the
        # moment the GCS applies them; the 1 Hz heartbeat pull stays as
        # the gap-filling reconciliation (reference: ray_syncer.h:86 —
        # bidirectional pushed deltas, not poll-only)
        self._delta_sub: RpcClient | None = None
        self._subscribe_node_deltas()
        # immediate baseline pull: pushes are gap-guarded against the local
        # version, so without this the push channel stays inert until the
        # first 1 Hz heartbeat tick establishes a base
        try:
            reply = self.gcs.call("heartbeat", {
                "node_id": node_id.binary(), "seen_seq": 0,
            })
            if reply.get("ok"):
                self._apply_cluster_delta(reply)
        except Exception:  # noqa: BLE001 — the pull loop reconciles anyway
            pass
        self._threads = [
            threading.Thread(target=self._heartbeat_loop, daemon=True, name="raylet-hb"),
            threading.Thread(target=self._dep_loop, daemon=True, name="raylet-deps"),
            threading.Thread(target=self._dispatch_loop, daemon=True, name="raylet-dispatch"),
            threading.Thread(target=self._dir_flush_loop, daemon=True, name="raylet-objdir"),
            threading.Thread(target=self._idle_reaper_loop, daemon=True, name="raylet-reaper"),
            threading.Thread(target=self._memory_monitor_loop, daemon=True, name="raylet-oom"),
            threading.Thread(target=self._metrics_report_loop, daemon=True, name="raylet-metrics"),
        ]
        for t in self._threads:
            t.start()

    # ------------- lifecycle -------------

    def stop(self) -> None:
        self._stopped.set()
        with self._dispatch_cv:
            self._dispatch_cv.notify_all()
        self._dir_event.set()
        for w in list(self._all_workers.values()):
            if w.proc is not None:
                w.proc.terminate()
        self.server.stop()
        self._store_events.close()
        if self._delta_sub is not None:
            try:
                self._delta_sub.close()
            except Exception:  # noqa: BLE001
                pass
        self.gcs.close()
        self.store.close()

    def _heartbeat_loop(self) -> None:
        cfg = global_config()
        interval = cfg.gcs_heartbeat_interval_ms / 1000.0
        while not self._stopped.wait(interval):
            try:
                if self._delta_sub is None:
                    # push channel lost (GCS flap, failed subscribe):
                    # retry — pull-only is correct but slower
                    self._subscribe_node_deltas()
                with self._lock:
                    avail = dict(self.available)
                    load = len(self._queued)
                    # resource shapes of queued work — the autoscaler
                    # bin-packs these onto node types (reference:
                    # resource_demand_scheduler.py:102 get_nodes_to_launch)
                    shapes = [dict(s["resources"]) for s in self._queued[:100]]
                hb = {
                    "node_id": self.node_id.binary(),
                    "available": avail,
                    "load": load,
                    "pending_shapes": shapes,
                    # delta sync: ask only for node-table changes since the
                    # last tick (reference: ray_syncer.h versioned deltas)
                    "seen_seq": self._cluster_seq,
                }
                disk = self._fs_monitor.usage_fraction()
                if disk is not None:
                    hb["disk_used_frac"] = disk
                reply = self.gcs.call("heartbeat", hb)
                if reply.get("reregister"):
                    # the GCS restarted and lost the node table — re-announce
                    # (reference: node_manager.cc:1168 HandleNotifyGCSRestart)
                    self.gcs.call(
                        "register_node",
                        {
                            "node_id": self.node_id.binary(),
                            "address": self.address,
                            "resources": self.resources,
                            "labels": self.labels,
                            "store_socket": self.store_socket,
                        },
                    )
                    # ...and its store contents: the object directory is
                    # in-memory GCS state and died with the old incarnation
                    self._republish_store_contents()
                    with self._lock:
                        # the new GCS incarnation restarts its version
                        # counter — drop the stale view entirely and resync
                        # from zero (nodes that died during the outage have
                        # no tombstone in the new incarnation)
                        self._cluster_seq = 0
                        self._cluster_view = {}
                self._apply_cluster_delta(reply)
            except Exception:
                if self._stopped.is_set():
                    return
                # GCS may be restarting: rebuild the client connection and
                # retry next tick (reference: gcs reconnect timeout,
                # ray_config_def.h:65)
                try:
                    self.gcs.close()
                except Exception:  # noqa: BLE001
                    pass
                try:
                    self.gcs = RpcClient(self.gcs_address)
                    # the push subscription died with the old GCS conn
                    self._subscribe_node_deltas()
                except Exception:  # noqa: BLE001
                    pass

    def _subscribe_node_deltas(self) -> None:
        if self._delta_sub is not None:
            try:
                self._delta_sub.close()
            except Exception:  # noqa: BLE001
                pass
            self._delta_sub = None
        client = None
        try:
            client = RpcClient(
                self.gcs_address, notify_handler=self._on_node_delta_push)
            client.call("subscribe", {"topic": "node_delta"})
            self._delta_sub = client
        except Exception:  # noqa: BLE001 — pull sync still covers us; the
            # heartbeat loop retries the subscription next tick
            if client is not None:
                try:
                    client.close()
                except Exception:  # noqa: BLE001
                    pass

    def _on_node_delta_push(self, topic: str, payload: dict) -> None:
        """Pushed node-table change. Applied only when it is the NEXT
        version — a push stream with gaps (late subscribe, dropped conn)
        must not leapfrog intermediate changes; the heartbeat pull
        reconciles those by asking with seen_seq."""
        if topic != "node_delta":
            return
        with self._lock:  # RLock: atomic check-then-apply vs the pull path
            if payload.get("seq") != self._cluster_seq + 1:
                return
            self._apply_cluster_delta(payload)

    def _apply_cluster_delta(self, reply: dict) -> None:
        """Merge one heartbeat reply's node-table changes into the local
        cluster view. Tombstones FIRST: a node that died and revived within
        one sync window appears in both lists, and its delta entry is always
        newer than its tombstone — applying delta last keeps the revived
        node visible (reference: ray_syncer versioned merge semantics)."""
        with self._lock:
            if reply.get("full"):
                self._cluster_view = {}
            for nid in reply.get("removed", ()):
                self._cluster_view.pop(nid, None)
            for n in reply.get("delta", ()):
                self._cluster_view[n["node_id"]] = n
            if "seq" in reply:
                self._cluster_seq = reply["seq"]

    def _metrics_report_loop(self) -> None:
        """Periodic node-level gauge refresh at
        config.metrics_report_interval_ms (reference: per-node metrics
        agent push cadence, metrics_report_interval_ms in
        ray_config_def.h). Gauges land in the in-process Prometheus
        registry served by util.metrics.start_metrics_server."""
        gauges = _node_gauges()
        if gauges is None:  # prometheus_client unavailable: skip quietly
            return
        avail_g, queued_g, workers_g = gauges
        interval = global_config().metrics_report_interval_ms / 1000.0
        short_id = self.node_id.hex()[:12]
        while not self._stopped.wait(interval):
            try:
                with self._lock:
                    avail = dict(self.available)
                    n_queued = len(self._queued)
                    n_workers = len(self._all_workers)
                for res, val in avail.items():
                    avail_g.set(val, {"node": short_id, "resource": res})
                queued_g.set(n_queued, {"node": short_id})
                workers_g.set(n_workers, {"node": short_id})
            except Exception:  # noqa: BLE001 — metrics must never kill a raylet
                pass

    def _idle_reaper_loop(self) -> None:
        """Reap long-idle task workers down to one warm worker so an idle
        node releases memory (reference: worker_pool.cc idle worker killing,
        kill_idle_workers_interval_ms / idle_worker_killing_time_threshold)."""
        cfg = global_config()
        interval = cfg.kill_idle_workers_interval_ms / 1000.0
        threshold = cfg.idle_worker_killing_time_threshold_ms / 1000.0
        while not self._stopped.wait(interval):
            now = time.monotonic()
            victims = []
            with self._lock:
                if len(self._idle_workers) <= 1:
                    continue
                # oldest-idle first; always keep one warm worker (cold spawn
                # costs seconds)
                for w in sorted(self._idle_workers, key=lambda w: w.last_idle):
                    if len(self._idle_workers) - len(victims) <= 1:
                        break
                    if now - w.last_idle > threshold:
                        victims.append(w)
                for w in victims:
                    self._idle_workers.remove(w)
                    self._all_workers.pop(w.worker_id, None)
            for w in victims:
                try:
                    if w.conn is not None:
                        w.conn.close()
                    if w.proc is not None:
                        w.proc.terminate()
                except Exception:  # noqa: BLE001
                    pass

    def _memory_monitor_loop(self) -> None:
        """Kill workers under memory pressure instead of letting the kernel
        OOM-killer take down the raylet (reference: memory_monitor.h:52 +
        worker_killing_policy.cc:116 — retriable tasks first, newest
        first)."""
        from ray_tpu._private.memory_monitor import MemoryMonitor

        cfg = global_config()
        if cfg.memory_usage_threshold <= 0:
            return
        monitor = MemoryMonitor(cfg.memory_usage_threshold)
        self._memory_monitor = monitor  # tests may swap the read function
        interval = cfg.memory_monitor_refresh_ms / 1000.0
        while not self._stopped.wait(interval):
            try:
                frac = monitor.usage_fraction()
                if frac is None or frac <= cfg.memory_usage_threshold:
                    continue
                victim = self._pick_oom_victim(
                    f"worker killed by the memory monitor: node memory usage "
                    f"{frac:.0%} > threshold {cfg.memory_usage_threshold:.0%}"
                )
                if victim is None:
                    continue
                if victim.proc is not None:
                    victim.proc.terminate()
                elif victim.conn is not None:
                    victim.conn.close()
            except Exception:  # noqa: BLE001 — monitoring must never die
                pass

    def _pick_oom_victim(self, reason: str) -> WorkerHandle | None:
        """Policy (reference: worker_killing_policy.cc retriable-LIFO):
        among busy TASK workers prefer one whose task can retry, NEWEST
        dispatch first (least progress lost); actor workers are spared
        (they carry state). Selection and kill-attribution are marked under
        the lock so a task that finishes before terminate() lands is not
        mislabeled as OOM-killed."""
        with self._lock:
            busy = [
                w for w in self._all_workers.values()
                if not w.is_actor_worker and w.current_task is not None
            ]
            if not busy:
                return None
            retriable = [
                w for w in busy
                if w.current_task["retry_count"] < w.current_task["max_retries"]
            ]
            pool = retriable or busy
            victim = max(pool, key=lambda w: w.task_started)
            victim.oom_killed = (reason, victim.current_task["task_id"])
            return victim

    # ------------- inter-node object plane -------------

    def _on_store_event(self, ev: int, oid: bytes) -> None:
        """Store seal/evict notification (runs on the subscriber thread)."""
        resolved = False
        with self._lock:
            self._dir_pending.append(
                ("s" if ev == osmod.EV_SEALED else "e", oid)
            )
            if ev == osmod.EV_SEALED:
                if oid in self._secondary:
                    self._secondary.discard(oid)  # pulled copy: evictable
                else:
                    # primary copies pin themselves atomically at seal
                    # (seal(pin=True)); track so free_object unpins once
                    self._pinned.add(oid)
                # PUSH-based dependency resolution: a seal is exactly the
                # event the dep manager waits for (reference: the raylet's
                # DependencyManager subscribes to object availability) — the
                # slow _dep_loop poll remains only for remote fetches and
                # eviction detection
                for task_id, deps in list(self._missing_deps.items()):
                    if oid in deps:
                        deps.discard(oid)
                        self._dep_fetch_ts.pop(oid, None)
                        if not deps:
                            del self._missing_deps[task_id]
                            resolved = True
            else:
                self._pinned.discard(oid)
        self._dir_event.set()
        if resolved:
            with self._dispatch_cv:
                self._dispatch_cv.notify_all()

    def _republish_store_contents(self) -> None:
        """After a GCS restart the (in-memory) object directory is empty:
        re-announce every object this node's store still holds, like the
        node re-registration itself."""
        try:
            oids = self.store.list_objects()
        except Exception:  # noqa: BLE001 — store unreachable mid-shutdown
            return
        with self._lock:
            self._dir_pending.extend(("s", o.binary()) for o in oids)
        self._dir_event.set()

    def _dir_flush_loop(self) -> None:
        """Batch location updates to the GCS directory: one RPC per burst of
        seal/evict events instead of one per object."""
        while not self._stopped.is_set():
            self._dir_event.wait(timeout=1.0)
            self._dir_event.clear()
            if self._stopped.is_set():
                return
            with self._lock:
                events, self._dir_pending = self._dir_pending, []
            if not events:
                continue
            try:
                self.gcs.call(
                    "object_location_update",
                    {
                        "node_id": self.node_id.binary(),
                        "events": [[ev, oid] for ev, oid in events],
                    },
                )
            except Exception:
                if self._stopped.is_set():
                    return
                # GCS restarting: requeue and retry next tick (heartbeat
                # loop heals the connection)
                with self._lock:
                    self._dir_pending = events + self._dir_pending
                time.sleep(0.2)
                self._dir_event.set()

    def rpc_pull_object(self, conn, msgid, p):
        """Serve one chunk of a local object to a pulling peer raylet
        (reference: ObjectManager::Push chunked transfer,
        object_manager.h:117 / object_buffer_pool.cc)."""
        view = self.store.get(ObjectID(p["object_id"]), timeout_ms=0)
        if view is None or view is osmod.EVICTED:
            return {"ok": False}
        total = len(view)
        off = int(p.get("offset", 0))
        length = int(p.get("length", total))
        return {"ok": True, "size": total, "data": bytes(view[off : off + length])}

    def rpc_fetch_object(self, conn, msgid, p):
        """Worker/driver asks its raylet to pull an object into the local
        store. Non-blocking: the caller keeps (blocking-)polling its local
        store; the seal wakes it (reference: PullManager, pull_manager.h:52)."""
        return {"status": self._request_fetch(p["object_id"])}

    def _request_fetch(self, oid: bytes) -> str:
        st = self.store.status(ObjectID(oid))
        if st == "present":
            return "present"
        # st is "missing" OR "evicted": a LOCAL tombstone (e.g. an LRU-evicted
        # secondary copy) does not mean the object is gone cluster-wide —
        # consult the directory; re-pulling clears the tombstone via create()
        now = time.monotonic()
        neg = self._fetch_neg_ts.get(oid)
        if neg is not None and now - neg < 0.5:
            return "evicted" if st == "evicted" else "unknown"
        try:
            r = self.gcs.call("get_object_locations", {"object_id": oid})
        except Exception:
            return "evicted" if st == "evicted" else "unknown"
        if not r.get("known"):
            self._fetch_neg_ts[oid] = now
            if len(self._fetch_neg_ts) > 10_000:
                cutoff = now - 0.5
                self._fetch_neg_ts = {
                    k: v for k, v in self._fetch_neg_ts.items() if v > cutoff
                }
            # no directory entry: trust local knowledge (it existed and died)
            return "evicted" if st == "evicted" else "unknown"
        self._fetch_neg_ts.pop(oid, None)
        locs = [l for l in r.get("nodes", ()) if l["node_id"] != self.node_id.binary()]
        if not locs:
            # directory tombstone (or every holder dead) → owners should
            # lineage-reconstruct; no entry → producer hasn't sealed yet
            return "evicted" if (r.get("evicted") or st == "evicted") else "unknown"
        with self._lock:
            if oid in self._fetching:
                return "fetching"
            self._fetching.add(oid)
        threading.Thread(
            target=self._pull_object, args=(oid, locs), daemon=True,
            name="raylet-pull",
        ).start()
        return "fetching"

    def _pull_object(self, oid: bytes, locations: list[dict]) -> None:
        """Pull one object chunk-by-chunk from a holder into the local store."""
        cfg = global_config()
        chunk = cfg.object_pull_chunk_bytes
        obj = ObjectID(oid)
        try:
            for loc in locations:
                created = False
                try:
                    peer = self._peer(loc["address"])
                    r = peer.call(
                        "pull_object", {"object_id": oid, "offset": 0, "length": chunk}
                    )
                    if not r.get("ok"):
                        continue
                    total = r["size"]
                    with self._lock:
                        # mark BEFORE create/seal so the seal event sees a
                        # secondary copy and does not pin it
                        self._secondary.add(oid)
                    try:
                        buf = self.store.create(obj, total)
                    except ValueError:
                        return  # landed locally already (racing seal/pull)
                    created = True
                    data = r["data"]
                    if total:
                        buf[: len(data)] = data
                    off = len(data)
                    while off < total:
                        r = peer.call(
                            "pull_object",
                            {"object_id": oid, "offset": off, "length": chunk},
                        )
                        if not r.get("ok") or not r["data"]:
                            raise ConnectionError("holder dropped object mid-pull")
                        data = r["data"]
                        buf[off : off + len(data)] = data
                        off += len(data)
                    self.store.seal(obj)  # seal event publishes the location
                    return
                except Exception:  # noqa: BLE001 — try the next holder
                    if created:
                        try:
                            self.store.abort(obj)
                        except Exception:  # noqa: BLE001
                            pass
                    with self._lock:
                        # no seal event will clear it; a later PRIMARY seal
                        # of this oid must not be mistaken for a pulled copy
                        self._secondary.discard(oid)
                    continue
        finally:
            with self._lock:
                self._fetching.discard(oid)
            with self._dispatch_cv:
                self._dispatch_cv.notify_all()

    def rpc_free_object(self, conn, msgid, p):
        """Owner's refs hit zero: UNPIN the local copy so it becomes
        LRU-evictable (routed via the GCS directory; reference:
        ReferenceCounter zero-ref → plasma objects become evictable,
        reference_count.h:61-115). Deliberately NOT an immediate delete:
        the owner cannot see borrowers (refs deserialized elsewhere), so
        reclamation happens lazily under memory pressure — a borrower of a
        freed ref keeps working unless pressure evicts it first, and task
        results remain lineage-reconstructible."""
        oid = p["object_id"]
        with self._lock:
            pinned = oid in self._pinned
            self._pinned.discard(oid)
        if pinned:
            try:
                self.store.unpin(ObjectID(oid))
            except Exception:  # noqa: BLE001 — store tearing down
                pass
        return {"ok": True}

    # ------------- dependency resolution -------------

    def _dep_loop(self) -> None:
        """Slow safety-net sweep over missing deps: LOCAL seals resolve
        instantly via the store event stream (_on_store_event); this loop
        only triggers remote pulls and detects cluster-wide eviction, so a
        100ms cadence suffices (was a 5ms contains-poll)."""
        from ray_tpu.exceptions import ObjectLostError

        while not self._stopped.wait(0.1):
            resolved_any = False
            with self._lock:
                items = [(tid, set(deps)) for tid, deps in self._missing_deps.items()]
            for task_id, deps in items:
                done = set()
                evicted = None
                for d in deps:
                    st = self.store.status(ObjectID(d))
                    if st == "present":
                        done.add(d)
                        continue
                    # missing (or tombstoned) locally: pull it if a peer
                    # holds a copy (throttled — _request_fetch dedups
                    # in-flight pulls); only a CLUSTER-WIDE "evicted" fails
                    # the task so a local tombstone never masks a live copy
                    now = time.monotonic()
                    if now - self._dep_fetch_ts.get(d, 0.0) > 0.2:
                        self._dep_fetch_ts[d] = now
                        if self._request_fetch(d) == "evicted":
                            evicted = d
                            break
                if evicted is not None:
                    # Fail the task with ObjectLostError; the owner's get()
                    # reconstructs from lineage and resubmits (worker.py
                    # _get_one handles the ObjectLostError payload).
                    with self._lock:
                        self._missing_deps.pop(task_id, None)
                        spec = next(
                            (s for s in self._queued if s["task_id"] == task_id), None
                        )
                        if spec is not None:
                            self._queued.remove(spec)
                    if spec is not None:
                        self._seal_error(
                            spec,
                            ObjectLostError(
                                f"dependency {ObjectID(evicted)} of task "
                                f"{spec['name']} was evicted"
                            ),
                        )
                    continue
                if done:
                    with self._lock:
                        for d in done:
                            self._dep_fetch_ts.pop(d, None)
                        remaining = self._missing_deps.get(task_id)
                        if remaining is not None:
                            remaining -= done
                            if not remaining:
                                del self._missing_deps[task_id]
                                resolved_any = True
            if resolved_any:
                with self._dispatch_cv:
                    self._dispatch_cv.notify_all()

    # ------------- worker pool -------------

    def _spawn_worker(self) -> WorkerHandle:
        worker_id = WorkerID.from_random()
        env = dict(os.environ)
        env.update(
            {
                "RT_RAYLET_ADDR": self.address,
                "RT_STORE_SOCK": self.store_socket,
                "RT_GCS_ADDR": self.gcs_address,
                "RT_NODE_ID": self.node_id.hex(),
                "RT_WORKER_ID": worker_id.hex(),
            }
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_main"],
            env=env,
            stdout=None,
            stderr=None,
        )
        handle = WorkerHandle(worker_id.binary(), proc)
        with self._lock:
            self._all_workers[worker_id.binary()] = handle
        return handle

    def rpc_register_worker(self, conn, msgid, p):
        wid = bytes.fromhex(p["worker_id"]) if isinstance(p["worker_id"], str) else p["worker_id"]
        with self._lock:
            handle = self._all_workers.get(wid)
            if handle is None:
                handle = WorkerHandle(wid, None)
                self._all_workers[wid] = handle
            handle.conn = conn
            conn.meta["worker_id"] = wid
            handle.registered.set()
            if not handle.is_actor_worker:
                self._idle_workers.append(handle)
        conn.on_close.append(self._on_worker_disconnect)
        with self._dispatch_cv:
            self._dispatch_cv.notify_all()
        return {"ok": True, "node_id": self.node_id.hex()}

    def _on_worker_disconnect(self, conn) -> None:
        wid = conn.meta.get("worker_id")
        if wid is None:
            return
        with self._lock:
            handle = self._all_workers.pop(wid, None)
            if handle is None:
                return
            if handle in self._idle_workers:
                self._idle_workers.remove(handle)
            spec = handle.current_task
        if handle.is_actor_worker and handle.actor_id is not None:
            self._on_actor_worker_death(handle, spec)
        else:
            self._release_task_resources(handle)
            if spec is not None:
                oom_reason = None
                if (
                    handle.oom_killed is not None
                    and handle.oom_killed[1] == spec["task_id"]
                ):
                    # attribute the kill only to the task the monitor saw;
                    # a task that finished in the selection→terminate window
                    # dies as an ordinary worker crash instead
                    oom_reason = handle.oom_killed[0]
                self._on_task_worker_death(spec, oom_reason=oom_reason)

    def _on_task_worker_death(self, spec: dict, oom_reason: str | None = None) -> None:
        from ray_tpu.exceptions import OutOfMemoryError

        if spec["retry_count"] < spec["max_retries"]:
            spec = dict(spec, retry_count=spec["retry_count"] + 1)
            delay = global_config().task_retry_delay_ms / 1000.0

            def _requeue():
                if delay > 0 and self._stopped.wait(delay):
                    return
                with self._dispatch_cv:
                    self._enqueue_locked(spec)
                    self._dispatch_cv.notify_all()

            # backoff before the retry so a crash-looping task doesn't spin
            # the dispatch path (reference: task_retry_delay_ms)
            threading.Thread(target=_requeue, daemon=True).start()
        elif oom_reason is not None:
            self._seal_error(
                spec,
                OutOfMemoryError(
                    f"task {spec['name']} failed: {oom_reason} "
                    f"(retries exhausted: {spec['max_retries']})"
                ),
            )
        else:
            self._seal_error(
                spec,
                WorkerCrashedError(
                    f"worker died executing {spec['name']} "
                    f"(retries exhausted: {spec['max_retries']})"
                ),
            )

    def _on_actor_worker_death(self, handle: WorkerHandle, spec: dict | None) -> None:
        aid = handle.actor_id
        with self._lock:
            actor = self._actors.get(aid)
            if actor is None:
                return
            # snapshot + reset ATOMICALLY: a racing _pump_actor either ran
            # before (its spec is in the snapshot and gets sealed; its
            # failed notify finds the inflight entry gone and skips the
            # requeue) or runs after and sees worker=None
            inflight = list(actor["inflight"].values())
            actor["inflight"].clear()
            actor["executing"] = 0
            actor["worker"] = None
        if spec is not None and spec["type"] == ts.ACTOR_CREATION:
            self._seal_error(spec, ActorDiedError(aid.hex(), "worker process died"))
        for fspec in inflight:
            # every method in flight died with the worker
            self._seal_error(fspec, ActorDiedError(aid.hex(), "worker process died"))
        creation_spec = actor["creation_spec"]
        if actor["num_restarts"] < creation_spec.get("max_restarts", 0):
            actor["num_restarts"] += 1
            self.gcs.call(
                "update_actor",
                {"actor_id": aid, "state": "RESTARTING", "increment_restarts": True},
            )
            # fail queued calls submitted before restart? keep them — they run
            # against the restarted instance (at-least-once actor semantics
            # when max_restarts > 0).
            self._start_actor_worker(aid, creation_spec)
        else:
            with self._lock:
                actor["state"] = "DEAD"
                pending = list(actor["queue"])
                actor["queue"].clear()
                self._return_actor_resources_locked(actor)
            for *_ignore, pspec in pending:
                self._seal_error(pspec, ActorDiedError(aid.hex(), "actor died"))
            self.gcs.call("update_actor", {"actor_id": aid, "state": "DEAD"})
            with self._dispatch_cv:
                self._dispatch_cv.notify_all()

    # ------------- resource accounting -------------

    def _acquire(self, spec: dict) -> dict | None:
        """Try to acquire resources for spec; returns assignment or None."""
        res = spec["resources"]
        placement = spec.get("placement")
        with self._lock:
            if placement is not None:
                pg = self._bundles.get(placement["pg"], {})
                bundle = pg.get(placement["bundle"])
                if bundle is None or bundle["state"] != "COMMITTED":
                    return None
                if not sched.fits(res, bundle["available"]):
                    return None
                sched.subtract(bundle["available"], res)
            else:
                if not sched.fits(res, self.available):
                    return None
                sched.subtract(self.available, res)
            chips: list[int] = []
            n_tpu = int(res.get("TPU", 0))
            if n_tpu > 0:
                chips = self._free_chips[:n_tpu]
                del self._free_chips[:n_tpu]
            return {"chips": chips}

    def _release_task_resources(self, handle: WorkerHandle) -> None:
        spec = handle.current_task
        if spec is None:
            return
        res = spec["resources"]
        placement = spec.get("placement")
        with self._lock:
            if placement is not None:
                pg = self._bundles.get(placement["pg"], {})
                bundle = pg.get(placement["bundle"])
                if bundle is not None:
                    sched.add(bundle["available"], res)
            else:
                sched.add(self.available, res)
            self._free_chips.extend(handle.assigned_chips)
            handle.assigned_chips = []
            handle.current_task = None

    # ------------- task submission -------------

    def rpc_submit_task(self, conn, msgid, p):
        spec = p["spec"]
        if spec["type"] == ts.ACTOR_TASK:
            return self._submit_actor_task(spec)
        with self._dispatch_cv:
            self._enqueue_locked(spec)
            self._dispatch_cv.notify_all()
        return {"ok": True, "queued_on": self.node_id.hex()}

    def _enqueue_locked(self, spec: dict) -> None:
        deps = {d for d in spec["arg_deps"] if not self.store.contains(ObjectID(d))}
        if deps:
            self._missing_deps[spec["task_id"]] = deps
        self._queued.append(spec)

    def _submit_actor_task(self, spec: dict) -> dict:
        aid = spec["actor_id"]
        with self._lock:
            actor = self._actors.get(aid)
            if actor is None or actor["state"] == "DEAD":
                pass  # fall through to error below
            else:
                self._actor_seq += 1
                heapq.heappush(actor["queue"], (spec["seqno"], self._actor_seq, spec))
                self._pump_actor(aid)
                return {"ok": True}
        self._seal_error(spec, ActorDiedError(aid.hex(), "actor not on this node or dead"))
        return {"ok": False, "reason": "actor dead"}

    # ------------- dispatch -------------

    def _dispatch_loop(self) -> None:
        from ray_tpu._private import event_stats

        while not self._stopped.is_set():
            with self._dispatch_cv:
                self._dispatch_cv.wait(timeout=0.05)
                if self._stopped.is_set():
                    return
            with event_stats.timed("raylet.dispatch"):
                self._dispatch_once()

    def _dispatch_once(self) -> None:
        if self._fs_monitor.over_capacity():
            # out-of-disk node: hold queued work (running tasks finish);
            # reference raylet likewise stops granting leases over capacity
            return
        while True:
            dispatched = False
            with self._lock:
                queue = list(self._queued)
            for spec in queue:
                tid = spec["task_id"]
                with self._lock:
                    if tid in self._missing_deps:
                        continue
                if self._maybe_spill(spec):
                    with self._lock:
                        if spec in self._queued:
                            self._queued.remove(spec)
                    dispatched = True
                    continue
                if spec["type"] == ts.ACTOR_CREATION:
                    assignment = self._acquire(spec)
                    if assignment is None:
                        continue  # stay queued until resources free up
                    with self._lock:
                        if spec in self._queued:
                            self._queued.remove(spec)
                    self._create_actor(spec, assignment)
                    dispatched = True
                    continue
                assignment = self._acquire(spec)
                if assignment is None:
                    continue
                worker = self._get_idle_worker()
                if worker is None:
                    self._undo_acquire(spec, assignment)
                    continue
                with self._lock:
                    if spec in self._queued:
                        self._queued.remove(spec)
                    worker.current_task = spec
                    worker.task_started = time.monotonic()
                    worker.assigned_chips = assignment["chips"]
                self._push_task(worker, spec, assignment)
                dispatched = True
            if not dispatched:
                return

    def _undo_acquire(self, spec: dict, assignment: dict) -> None:
        res = spec["resources"]
        placement = spec.get("placement")
        with self._lock:
            if placement is not None:
                pg = self._bundles.get(placement["pg"], {})
                bundle = pg.get(placement["bundle"])
                if bundle is not None:
                    sched.add(bundle["available"], res)
            else:
                sched.add(self.available, res)
            self._free_chips.extend(assignment["chips"])

    def _get_idle_worker(self) -> WorkerHandle | None:
        with self._lock:
            while self._idle_workers:
                w = self._idle_workers.pop()
                if w.conn is not None and not w.conn.closed:
                    return w
            n_task_workers = sum(
                1 for w in self._all_workers.values() if not w.is_actor_worker
            )
            if n_task_workers < self._soft_limit:
                pass  # spawn below, outside the lock
            else:
                return None
        self._spawn_worker()
        return None  # dispatched on registration wake-up

    def _push_task(self, worker: WorkerHandle, spec: dict, assignment: dict) -> None:
        ok = worker.conn.notify(
            "execute_task",
            {"spec": spec, "chips": assignment["chips"]},
        )
        if not ok:
            self._on_worker_disconnect(worker.conn)

    def _maybe_spill(self, spec: dict) -> bool:
        """Spillback: forward to a peer raylet when it's the better target
        (reference: lease spillback in HandleRequestWorkerLease +
        hybrid_scheduling_policy)."""
        if spec.get("spilled") or spec.get("placement") is not None:
            return False
        strategy = spec.get("scheduling", {})
        stype = strategy.get("type", ts.SCHED_DEFAULT)
        with self._lock:
            view = {
                nid: dict(n, available=dict(n.get("available", n["resources"])))
                for nid, n in self._cluster_view.items()
            }
            me = self.node_id.binary()
            if me in view:
                view[me]["available"] = dict(self.available)
        if not view:
            return False
        affinity = strategy.get("node_id")
        target = sched.pick_node(
            spec["resources"],
            view,
            strategy=stype,
            local_node_id=me,
            affinity_node_id=affinity,
            soft=strategy.get("soft", False),
        )
        if target is None or target == me:
            # infeasible locally AND nowhere else: if local total can never
            # fit it, error out rather than hang forever
            if target is None and not sched.fits(spec["resources"], self.resources):
                feasible_somewhere = any(
                    sched.fits(spec["resources"], n["resources"]) for n in view.values()
                )
                if not feasible_somewhere:
                    self._seal_error(
                        spec,
                        ValueError(
                            f"task {spec['name']} requires {spec['resources']} "
                            "which no node in the cluster can ever satisfy"
                        ),
                    )
                    return True
            return False
        # local fits and hybrid prefers local — pick_node returns local above;
        # here target is remote
        spec = dict(spec, spilled=True)
        try:
            self._peer(view[target]["address"]).call("submit_task", {"spec": spec})
            return True
        except Exception:
            return False

    def _peer(self, address: str) -> RpcClient:
        with self._lock:
            c = self._peer_clients.get(address)
            if c is None:
                c = RpcClient(address)
                self._peer_clients[address] = c
            return c

    # ------------- actors -------------

    def _return_actor_resources_locked(self, actor: dict) -> None:
        """Release the actor's lifetime reservation to its origin — PG
        bundle when placement-group-scheduled, node pool otherwise. Caller
        holds self._lock; idempotent."""
        if actor.get("resources_returned"):
            return
        actor["resources_returned"] = True
        creation = actor["creation_spec"]
        res = creation["resources"]
        placement = creation.get("placement")
        if placement is not None:
            bundle = self._bundles.get(placement["pg"], {}).get(placement["bundle"])
            if bundle is not None:
                sched.add(bundle["available"], res)
        else:
            sched.add(self.available, res)
        self._free_chips.extend(actor["assignment"]["chips"])
        actor["assignment"] = {"chips": []}

    def _create_actor(self, spec: dict, assignment: dict) -> None:
        aid = spec["actor_id"]
        with self._lock:
            self._actors[aid] = {
                "state": "STARTING",
                "creation_spec": spec,
                "queue": [],
                # up to max_concurrency methods run at once on the worker's
                # thread pool (reference: concurrency_group_manager.cc /
                # threaded actors); in-flight specs tracked for death sealing
                "max_concurrency": max(1, int(spec.get("max_concurrency", 1))),
                "executing": 0,
                "inflight": {},  # task_id -> spec
                "worker": None,
                "num_restarts": 0,
                "assignment": assignment,
            }
        self._start_actor_worker(aid, spec, assignment)

    def _start_actor_worker(self, aid: bytes, spec: dict, assignment: dict | None = None) -> None:
        if assignment is None:
            assignment = self._actors[aid]["assignment"]
        handle = self._spawn_worker()
        handle.is_actor_worker = True
        handle.actor_id = aid
        handle.assigned_chips = assignment["chips"]
        handle.current_task = None

        def finish_registration():
            if not handle.registered.wait(global_config().worker_register_timeout_s):
                # worker never connected: reap it, free the reservation,
                # mark the actor dead
                self._seal_error(spec, ActorDiedError(aid.hex(), "worker failed to start"))
                if handle.proc is not None:
                    handle.proc.terminate()
                with self._lock:
                    actor = self._actors.get(aid)
                    if actor is not None:
                        actor["state"] = "DEAD"
                        self._return_actor_resources_locked(actor)
                self.gcs.call("update_actor", {"actor_id": aid, "state": "DEAD"})
                with self._dispatch_cv:
                    self._dispatch_cv.notify_all()
                return
            with self._lock:
                actor = self._actors.get(aid)
                if actor is None:
                    return
                if actor["state"] == "DEAD":
                    # killed while restarting: do not resurrect
                    if handle.proc is not None:
                        handle.proc.terminate()
                    self._return_actor_resources_locked(actor)
                    return
                actor["worker"] = handle
                if handle in self._idle_workers:
                    self._idle_workers.remove(handle)
            handle.current_task = spec
            handle.conn.notify(
                "execute_task", {"spec": spec, "chips": assignment["chips"]}
            )

        threading.Thread(target=finish_registration, daemon=True).start()

    def _pump_actor(self, aid: bytes) -> None:
        """Dispatch queued methods while capacity allows: strictly in seqno
        order (reference: actor_scheduling_queue.cc sequential ordering),
        up to max_concurrency in flight at once (threaded-actor semantics —
        ordering of EXECUTION is lost beyond 1, as in the reference)."""
        while True:
            with self._lock:
                actor = self._actors.get(aid)
                if (
                    actor is None
                    or actor["state"] != "ALIVE"
                    or actor["executing"] >= actor["max_concurrency"]
                    or not actor["queue"]
                ):
                    return
                if actor["worker"] is None or actor["worker"].conn is None:
                    return  # restarting; rpc_actor_started will pump
                seqno, _tie, spec = heapq.heappop(actor["queue"])
                actor["executing"] += 1
                actor["inflight"][spec["task_id"]] = spec
                handle = actor["worker"]
            if not handle.conn.notify(
                "execute_task", {"spec": spec, "chips": handle.assigned_chips}
            ):
                # Dead connection: requeue the method and let the disconnect
                # path (or an already-started restart) re-pump; retry shortly
                # in case actor_started raced ahead of this requeue. If the
                # death handler already swept this spec out of inflight it
                # was sealed with ActorDiedError — do NOT also requeue.
                with self._lock:
                    if actor["inflight"].pop(spec["task_id"], None) is not None:
                        actor["executing"] = max(0, actor["executing"] - 1)
                        self._actor_seq += 1
                        heapq.heappush(
                            actor["queue"], (seqno, self._actor_seq, spec)
                        )

                def _retry():
                    time.sleep(0.1)
                    self._pump_actor(aid)

                threading.Thread(target=_retry, daemon=True).start()
                return

    def rpc_actor_started(self, conn, msgid, p):
        """Worker reports actor __init__ finished."""
        aid = p["actor_id"]
        with self._lock:
            actor = self._actors.get(aid)
            if actor is None:
                return {"ok": False}
            if actor["state"] == "DEAD":
                # killed while starting/restarting — do not resurrect
                handle = actor.get("worker")
                if handle is not None and handle.proc is not None:
                    handle.proc.terminate()
                return {"ok": False, "reason": "actor killed"}
            actor["state"] = "ALIVE"
            handle = actor["worker"]
            if handle is not None:
                handle.current_task = None
        self.gcs.call(
            "update_actor",
            {
                "actor_id": aid,
                "state": "ALIVE",
                "node_id": self.node_id.binary(),
                "raylet_address": self.address,
                "worker_id": p["worker_id"],
            },
        )
        self._pump_actor(aid)
        return {"ok": True}

    def rpc_kill_actor(self, conn, msgid, p):
        aid = p["actor_id"]
        with self._lock:
            actor = self._actors.get(aid)
            if actor is None:
                return {"ok": False}
            actor["state"] = "DEAD"
            # prevent restart path from resurrecting it
            actor["creation_spec"] = dict(actor["creation_spec"], max_restarts=0)
            handle = actor["worker"]
            pending = list(actor["queue"])
            actor["queue"].clear()
            if handle is None:
                # no live worker (e.g. mid-restart): the disconnect path
                # won't fire, release the reservation here
                self._return_actor_resources_locked(actor)
        for *_ignore, pspec in pending:
            self._seal_error(pspec, ActorDiedError(aid.hex(), "actor was killed"))
        if handle is not None and handle.proc is not None:
            handle.proc.terminate()
        self.gcs.call("update_actor", {"actor_id": aid, "state": "DEAD"})
        return {"ok": True}

    # ------------- task completion -------------

    def rpc_task_done(self, conn, msgid, p):
        wid = conn.meta.get("worker_id")
        with self._lock:
            handle = self._all_workers.get(wid)
        if handle is None:
            return {"ok": False}
        if handle.is_actor_worker:
            # Actor methods run on the actor's lifetime reservation — no
            # per-method resource release (reference: actor creation task
            # holds the resources; methods are zero-cost by default).
            aid = handle.actor_id
            with self._lock:
                handle.current_task = None
                actor = self._actors.get(aid)
                if actor is not None:
                    tid = p.get("task_id") if isinstance(p, dict) else None
                    # only a task we actually dispatched occupies a slot —
                    # the actor-creation task's task_done must NOT decrement
                    # (it never went through _pump_actor)
                    if tid is not None and actor["inflight"].pop(tid, None) is not None:
                        actor["executing"] = max(0, actor["executing"] - 1)
            self._pump_actor(aid)
        else:
            self._release_task_resources(handle)
            with self._lock:
                handle.last_idle = time.monotonic()
                self._idle_workers.append(handle)
            with self._dispatch_cv:
                self._dispatch_cv.notify_all()
        return {"ok": True}

    def _seal_error(self, spec: dict, error: Exception) -> None:
        """Write an error payload into every return object of the task."""
        for oid in ts.return_object_ids(spec):
            try:
                chunks = ser.serialize(_ErrorPayload(error))
                size = ser.serialized_size(chunks)
                buf = self.store.create(oid, size)
                ser.write_chunks(chunks, buf)
                self.store.seal(oid, pin=True)  # primary copy
            except ValueError:
                pass  # already exists (duplicate failure path) — keep first
            except Exception:
                try:
                    self.store.discard_pending(oid)
                except Exception:  # noqa: BLE001 — connection already gone
                    pass
                if self._stopped.is_set():
                    return  # store already torn down; nobody will get() this
                # e.g. store full: dropping the error would hang the owner's
                # get() forever — log loudly, it indicates store pressure
                import traceback

                print(
                    f"[raylet] FAILED to seal error for task {spec['name']}: "
                    f"{traceback.format_exc()}",
                    flush=True,
                )

    # ------------- placement group bundles -------------

    def rpc_prepare_bundle(self, conn, msgid, p):
        """Phase 1: reserve resources (reference: node_manager.cc:1832)."""
        res = p["resources"]
        with self._lock:
            if not sched.fits(res, self.available):
                return {"ok": False}
            sched.subtract(self.available, res)
            self._bundles.setdefault(p["pg_id"], {})[p["bundle_index"]] = {
                "resources": dict(res),
                "available": dict(res),
                "state": "PREPARED",
            }
        return {"ok": True}

    def rpc_commit_bundle(self, conn, msgid, p):
        """Phase 2 (reference: node_manager.cc:1848)."""
        with self._lock:
            bundle = self._bundles.get(p["pg_id"], {}).get(p["bundle_index"])
            if bundle is None:
                return {"ok": False}
            bundle["state"] = "COMMITTED"
        with self._dispatch_cv:
            self._dispatch_cv.notify_all()
        return {"ok": True}

    def rpc_cancel_bundle(self, conn, msgid, p):
        return self.rpc_return_bundle(conn, msgid, p)

    def rpc_return_bundle(self, conn, msgid, p):
        with self._lock:
            pg = self._bundles.get(p["pg_id"], {})
            bundle = pg.pop(p["bundle_index"], None)
            if bundle is not None:
                sched.add(self.available, bundle["resources"])
        return {"ok": True}

    # ------------- introspection -------------

    def rpc_node_stats(self, conn, msgid, p):
        with self._lock:
            return {
                "node_id": self.node_id.hex(),
                "resources": self.resources,
                "available": dict(self.available),
                "num_workers": len(self._all_workers),
                "num_idle": len(self._idle_workers),
                "queued": len(self._queued),
                "actors": {
                    aid.hex() if isinstance(aid, bytes) else aid: a["state"]
                    for aid, a in self._actors.items()
                },
            }
