"""`ray_tpu start` node process: hosts a full cluster node.

Equivalent of the reference's `ray start` head/worker node processes
(reference: python/ray/scripts/scripts.py:548 `ray start`, which spawns
gcs_server + raylet via Node.start_head_processes node.py:1395/1424). One
OS process per node: the C++ store daemon as a subprocess, GCS (head only)
and the raylet as threads. Writes a JSON info file so `ray_tpu stop` and
drivers on the same host can find the node, prints a readiness line, and
runs until SIGTERM/SIGINT.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def default_info_dir() -> str:
    return os.path.join(os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "nodes")


def default_info_path() -> str:
    """One info file per node process (keyed by pid) — several nodes can
    coexist on a host and `ray_tpu stop` stops all of them."""
    return os.path.join(default_info_dir(), f"node_{os.getpid()}.json")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="ray_tpu-node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", help="existing GCS address (worker node)")
    p.add_argument("--port", type=int, default=0, help="GCS port (head only)")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--object-store-memory", type=int, default=None)
    p.add_argument("--client-server-port", type=int, default=None,
                   help="ray:// client server port (head only; default "
                        "10001, 0 = ephemeral, -1 = disabled)")
    p.add_argument("--resources", default=None, help='JSON dict, e.g. \'{"A":1}\'')
    p.add_argument("--labels", default=None, help="JSON dict of node labels")
    p.add_argument("--info-file", default=None)
    args = p.parse_args(argv)
    if bool(args.head) == bool(args.address):
        p.error("exactly one of --head / --address is required")

    from ray_tpu._private.node import start_head, start_worker_node

    resources = json.loads(args.resources) if args.resources else None
    labels = json.loads(args.labels) if args.labels else None
    common = dict(
        num_cpus=args.num_cpus,
        num_tpus=args.num_tpus,
        resources=resources,
        labels=labels,
        object_store_memory=args.object_store_memory,
    )
    if args.head:
        handle = start_head(gcs_port=args.port, **common)
    else:
        handle = start_worker_node(args.address, **common)

    client_address = None
    if args.head and (args.client_server_port is None
                      or args.client_server_port >= 0):
        # ray:// proxy for out-of-cluster drivers (util/client.py;
        # reference: ray start --head opens the client server on 10001)
        from ray_tpu.util.client import DEFAULT_CLIENT_PORT, ClientServer

        port = (DEFAULT_CLIENT_PORT if args.client_server_port is None
                else args.client_server_port)
        try:
            handle.client_server = ClientServer(handle, port=port)
            client_address = handle.client_server.address
        except OSError:
            # canonical port taken (another head on this host): fall back
            # to an ephemeral port rather than failing the node
            handle.client_server = ClientServer(handle, port=0)
            client_address = handle.client_server.address

    info = {
        "pid": os.getpid(),
        "gcs_address": handle.gcs_address,
        "raylet_address": handle.raylet.address,
        "store_socket": handle.store_socket,
        "node_id": handle.node_id.hex(),
        "session_dir": handle.session_dir,
        "head": bool(args.head),
        "client_address": client_address,
    }
    info_path = args.info_file or default_info_path()
    os.makedirs(os.path.dirname(info_path), exist_ok=True)
    with open(info_path, "w") as f:
        json.dump(info, f)

    # Readiness line for supervisors/tests (parsed like the store's READY).
    print("RAY_TPU_NODE_READY " + json.dumps(info), flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    cs = getattr(handle, "client_server", None)
    if cs is not None:
        cs.stop()
    handle.shutdown()
    try:
        os.remove(info_path)
    except OSError:
        pass


if __name__ == "__main__":
    try:
        main()
    except KeyboardInterrupt:
        sys.exit(0)
