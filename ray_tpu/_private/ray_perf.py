"""Core microbenchmarks — tasks/s, actor calls/s, put/get throughput.

Equivalent of the reference's `ray microbenchmark`
(reference: python/ray/_private/ray_perf.py:1 — the CI gate for core
regressions; release/benchmarks/README.md scalability envelope). Run:
`python -m ray_tpu._private.ray_perf`.
"""
from __future__ import annotations

import time


def _rate(n: int, seconds: float) -> float:
    return n / seconds if seconds > 0 else float("inf")


def run_microbenchmarks(task_count: int = 200, call_count: int = 200,
                        put_count: int = 100, put_mb: int = 1) -> dict:
    import numpy as np

    import ray_tpu

    results: dict[str, float] = {}

    @ray_tpu.remote
    def noop():
        return None

    # warm the worker pool so we measure steady-state dispatch, not spawn
    ray_tpu.get([noop.remote() for _ in range(8)], timeout=120)

    t0 = time.perf_counter()
    ray_tpu.get([noop.remote() for _ in range(task_count)], timeout=300)
    results["tasks_per_s"] = _rate(task_count, time.perf_counter() - t0)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    ray_tpu.get(c.inc.remote(), timeout=120)  # actor cold start
    t0 = time.perf_counter()
    ray_tpu.get([c.inc.remote() for _ in range(call_count)], timeout=300)
    results["actor_calls_per_s"] = _rate(call_count, time.perf_counter() - t0)

    payload = np.zeros(put_mb * 1024 * 1024, np.uint8)
    t0 = time.perf_counter()
    refs = [ray_tpu.put(payload) for _ in range(put_count)]
    results["put_mb_per_s"] = _rate(put_count * put_mb, time.perf_counter() - t0)
    t0 = time.perf_counter()
    for r in refs:
        ray_tpu.get(r, timeout=60)
    results["get_mb_per_s"] = _rate(put_count * put_mb, time.perf_counter() - t0)
    return results


def main() -> None:
    import json

    import ray_tpu

    owns_cluster = not ray_tpu.is_initialized()
    if owns_cluster:
        ray_tpu.init(object_store_memory=512 * 1024 * 1024)
    try:
        results = run_microbenchmarks()
        print(json.dumps({k: round(v, 1) for k, v in results.items()}))
    finally:
        if owns_cluster:
            ray_tpu.shutdown()


if __name__ == "__main__":
    main()
