"""Control-plane RPC: msgpack-framed messages over TCP.

Equivalent in role to the reference's gRPC wrapper layer
(reference: src/ray/rpc/grpc_server.h, client_call.h — async server/client
call templates over an asio io_context). The control plane here is
deliberately small: length-prefixed msgpack arrays over TCP, a
selector-based event-loop server (one loop thread multiplexes every
connection; handlers run on a small on-demand pool with per-connection
FIFO ordering — the asio analog, NOT thread-per-connection, which kept
one idle OS thread per open socket and capped node fan-in), plus
server→client push notifications (used for task completion, pubsub
delivery, and actor state changes — the analog of the reference's
long-poll pubsub, src/ray/pubsub/publisher.h).

Wire format: [u32 len][msgpack array]
  request:  [0, msgid, method: str, payload]
  response: [1, msgid, ok: bool, payload_or_error]
  notify:   [2, 0, topic: str, payload]
"""
from __future__ import annotations

import collections
import selectors
import socket
import struct
import threading
import time
import traceback
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable

import msgpack

from ray_tpu._private import event_stats

REQUEST, RESPONSE, NOTIFY = 0, 1, 2


def _pack(obj: Any) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    return struct.pack("<I", len(body)) + body


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    chunks = []
    while n > 0:
        try:
            c = sock.recv(n)
        except OSError:
            return None
        if not c:
            return None
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def _read_msg(sock: socket.socket) -> list | None:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack("<I", header)
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return msgpack.unpackb(body, raw=False)


class Connection:
    """Server-side handle to one client connection; safe concurrent sends.

    The socket is nonblocking and owned by the server's event loop:
    send() from ANY thread appends to the connection's outbox and wakes
    the loop, which flushes when the socket is writable (asio-style
    buffered writes — a slow reader can no longer block a pool thread
    inside sendall)."""

    def __init__(self, sock: socket.socket, peer: str, server: "RpcServer"):
        self.sock = sock
        self.peer = peer
        self._server = server
        self.closed = False
        # Services can attach identity here (e.g. worker id after register).
        self.meta: dict[str, Any] = {}
        self.on_close: list[Callable[[Connection], None]] = []
        # event-loop state (guarded by the server's conn lock)
        self._rbuf = bytearray()
        self._outbox: collections.deque[bytes] = collections.deque()
        self._out_off = 0  # partial-write offset into outbox[0]
        self._out_bytes = 0  # slow-consumer accounting
        self._handshaken = False
        # per-connection FIFO handler dispatch
        self._tasks: collections.deque[list] = collections.deque()
        self._draining = False
        self._paused = False  # READ interest dropped (task backlog)

    def send(self, msg: list) -> bool:
        """False when the connection is known-dead (reader saw EOF/error).
        Like the old blocking sendall, a send that races death may still
        report True — definitive failure surfaces via on_close."""
        if self.closed:
            return False
        return self._server._enqueue_send(self, _pack(msg))

    def notify(self, topic: str, payload: Any) -> bool:
        return self.send([NOTIFY, 0, topic, payload])

    def close(self) -> None:
        self.closed = True
        self._server._request_close(self)


class RpcServer:
    """Selector-based RPC server dispatching to handler methods.

    One event-loop thread multiplexes accept/read/write for every
    connection (the reference's asio io_context shape,
    src/ray/rpc/grpc_server.h); complete frames dispatch onto a small
    on-demand thread pool with PER-CONNECTION FIFO ordering, so handler
    semantics match the old thread-per-connection server (one in-flight
    request per connection, cross-connection parallelism) without an OS
    thread pinned per idle socket — the former node-fan-in ceiling.

    Handlers are methods named ``rpc_<method>`` on the service object,
    called as ``handler(conn, msgid, payload)``; the return value is the
    response payload. A handler may instead return the DEFERRED sentinel
    and later complete the call via
    ``conn.send([RESPONSE, msgid, True, payload])`` — used for blocking
    calls (e.g. waiting on an actor to start) without tying up a pool
    thread.
    """

    DEFERRED = object()
    _POOL_WORKERS = 16
    # slow-consumer policy: a peer that stops reading while we keep
    # sending gets dropped once its outbox crosses this (gRPC's
    # resource-exhausted analog); a peer that pipelines requests faster
    # than handlers drain has its READ interest paused (TCP backpressure)
    _MAX_OUTBOX_BYTES = 64 * 1024 * 1024
    _MAX_PENDING_TASKS = 10_000

    def __init__(self, service: Any, host: str = "127.0.0.1", port: int = 0):
        self.service = service
        # strict wire-schema validation (schema.py): services declare their
        # schema table via a `schema_service` class attribute
        self._schema_service = getattr(service, "schema_service", None)
        from ray_tpu._private import schema as _schema

        self._strict = _schema.strict_mode()
        # handler-latency accounting (event_stats.py; the reference's
        # instrumented_io_context records every asio handler the same way)
        self._stats_name = (self._schema_service
                            or type(service).__name__.lower())
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if port == 0:
            self._srv.bind((host, port))
        else:
            # fixed ports are used for restart-in-place (GCS FT); lingering
            # sockets from the previous incarnation can hold the port for a
            # moment — retry EADDRINUSE briefly; other errors fail fast
            import errno

            deadline = time.monotonic() + 10
            while True:
                try:
                    self._srv.bind((host, port))
                    break
                except OSError as e:
                    if e.errno != errno.EADDRINUSE or time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
        self._srv.listen(512)
        self._srv.setblocking(False)
        self.address = f"{host}:{self._srv.getsockname()[1]}"
        self._stopped = threading.Event()
        self.connections: set[Connection] = set()
        self._conn_lock = threading.Lock()
        # pool threads spawn on demand up to the cap; an idle server holds
        # only the loop thread. Services whose handlers legitimately BLOCK
        # inline (e.g. the client server's rpc_client_wait) declare a
        # larger cap via a `rpc_pool_workers` class attribute.
        self._pool = ThreadPoolExecutor(
            max_workers=getattr(service, "rpc_pool_workers",
                                self._POOL_WORKERS),
            thread_name_prefix=f"rpc-pool-{self.address}")
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._pending_writes: set[Connection] = set()
        self._pending_closes: set[Connection] = set()
        self._pending_resumes: set[Connection] = set()
        self._sel.register(self._srv, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._loop_thread = threading.Thread(
            target=self._loop, daemon=True, name=f"rpc-loop-{self.address}"
        )
        self._loop_thread.start()

    # ---------------- event loop ----------------

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def _enqueue_send(self, conn: Connection, data: bytes) -> bool:
        with self._conn_lock:
            if conn.closed or conn not in self.connections:
                return False
            conn._outbox.append(data)
            conn._out_bytes += len(data)
            if conn._out_bytes > self._MAX_OUTBOX_BYTES:
                # peer stopped reading: cut it loose rather than buffer
                # toward OOM
                self._pending_closes.add(conn)
            self._pending_writes.add(conn)
        self._wake()
        return True

    def _request_close(self, conn: Connection) -> None:
        with self._conn_lock:
            self._pending_closes.add(conn)
        self._wake()

    def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                events = self._sel.select(timeout=1.0)
            except OSError:
                break
            for key, mask in events:
                tag = key.data
                if tag == "accept":
                    self._do_accept()
                elif tag == "wake":
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except OSError:
                        pass
                else:  # a Connection
                    conn: Connection = tag
                    if mask & selectors.EVENT_READ:
                        self._do_read(conn)
                    if mask & selectors.EVENT_WRITE:
                        self._do_write(conn)
            # apply cross-thread requests (sends/closes/resumes) after IO
            with self._conn_lock:
                writes = [c for c in self._pending_writes
                          if c in self.connections]
                self._pending_writes.clear()
                closes = list(self._pending_closes)
                self._pending_closes.clear()
                resumes = [c for c in self._pending_resumes
                           if c in self.connections]
                self._pending_resumes.clear()
            for conn in resumes:
                want = selectors.EVENT_READ | (
                    selectors.EVENT_WRITE if conn._outbox else 0)
                try:
                    self._sel.register(conn.sock, want, conn)
                except (KeyError, ValueError, OSError):
                    pass
            for conn in writes:
                self._do_write(conn)
            for conn in closes:
                self._drop_conn(conn)
        # loop exit: tear everything down
        with self._conn_lock:
            conns = list(self.connections)
        for conn in conns:
            self._drop_conn(conn)
        try:
            self._sel.close()
        except OSError:
            pass

    def _do_accept(self) -> None:
        while True:
            try:
                sock, addr = self._srv.accept()
            except BlockingIOError:
                return
            except OSError:
                return
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = Connection(sock, f"{addr[0]}:{addr[1]}", self)
            with self._conn_lock:
                self.connections.add(conn)
            try:
                self._sel.register(sock, selectors.EVENT_READ, conn)
            except (KeyError, ValueError, OSError):
                self._drop_conn(conn)

    def _do_read(self, conn: Connection) -> None:
        try:
            while True:
                chunk = conn.sock.recv(1 << 16)
                if not chunk:
                    self._drop_conn(conn)
                    return
                conn._rbuf += chunk
                if len(chunk) < (1 << 16):
                    break
        except BlockingIOError:
            pass
        except OSError:
            self._drop_conn(conn)
            return
        # extract complete frames
        buf = conn._rbuf
        frames = []
        off = 0
        while len(buf) - off >= 4:
            (length,) = struct.unpack_from("<I", buf, off)
            if len(buf) - off - 4 < length:
                break
            frames.append(bytes(buf[off + 4:off + 4 + length]))
            off += 4 + length
        if off:
            del buf[:off]
        if not frames:
            return
        with self._conn_lock:
            for raw in frames:
                conn._tasks.append(raw)
            start = not conn._draining and bool(conn._tasks)
            if start:
                conn._draining = True
            pause = (len(conn._tasks) > self._MAX_PENDING_TASKS
                     and not conn._paused)
            if pause:
                conn._paused = True
        if pause:
            # stop reading this socket: the kernel buffer fills and TCP
            # pushes back on the sender (the drainer resumes us)
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
        if start:
            try:
                self._pool.submit(self._drain_conn, conn)
            except RuntimeError:  # pool shut down mid-teardown
                with self._conn_lock:
                    conn._draining = False

    def _do_write(self, conn: Connection) -> None:
        try:
            while conn._outbox:
                data = conn._outbox[0]
                n = conn.sock.send(
                    memoryview(data)[conn._out_off:])
                conn._out_off += n
                conn._out_bytes -= n
                if conn._out_off < len(data):
                    break  # kernel buffer full
                conn._outbox.popleft()
                conn._out_off = 0
        except BlockingIOError:
            pass
        except OSError:
            self._drop_conn(conn)
            return
        # toggle WRITE interest to match backlog
        want = selectors.EVENT_READ | (
            selectors.EVENT_WRITE if conn._outbox else 0)
        try:
            self._sel.modify(conn.sock, want, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _drop_conn(self, conn: Connection) -> None:
        with self._conn_lock:
            if conn not in self.connections:
                return
            self.connections.discard(conn)
            self._pending_writes.discard(conn)
            self._pending_closes.discard(conn)
            self._pending_resumes.discard(conn)
        conn.closed = True
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        if conn.on_close:
            # death handlers can do real blocking work (the raylet's
            # actor-death path makes GCS calls) — never run them on the
            # event loop, which must keep serving every other connection
            try:
                self._pool.submit(self._run_on_close, conn)
            except RuntimeError:  # pool already shut down (server stop)
                self._run_on_close(conn)
        try:
            conn.sock.close()
        except OSError:
            pass

    @staticmethod
    def _run_on_close(conn: Connection) -> None:
        for cb in conn.on_close:
            try:
                cb(conn)
            except Exception:
                pass

    # ---------------- handler dispatch (pool threads) ----------------

    def _drain_conn(self, conn: Connection) -> None:
        """Process this connection's queued frames in order; exactly one
        drainer per connection at a time (FIFO semantics)."""
        while True:
            with self._conn_lock:
                if not conn._tasks or conn.closed:
                    conn._draining = False
                    return
                raw = conn._tasks.popleft()
                resume = (conn._paused
                          and len(conn._tasks) < self._MAX_PENDING_TASKS // 2)
                if resume:
                    conn._paused = False
                    self._pending_resumes.add(conn)
            if resume:
                self._wake()
            try:
                msg = msgpack.unpackb(raw, raw=False)
                if not (isinstance(msg, list) and len(msg) == 4):
                    raise ValueError(f"malformed frame: {msg!r}")
                self._handle_msg(conn, msg)
            except Exception:
                # a malformed or handler-crashing frame must never wedge
                # the drainer with _draining stuck True — drop the peer,
                # like the old per-connection loop's finally did
                with self._conn_lock:
                    conn._draining = False
                self._request_close(conn)
                return

    def _handle_msg(self, conn: Connection, msg: list) -> None:
        mtype, msgid, method, payload = msg
        if mtype != REQUEST:
            return
        if method == "_handshake":
            # version negotiation, answered by the RPC layer itself
            # (schema.py; the analog of proto compatibility checks)
            from ray_tpu._private import schema

            try:
                conn.send([RESPONSE, msgid, True,
                           schema.check_handshake(payload)])
                conn._handshaken = True
            except schema.SchemaError as e:
                conn.send([RESPONSE, msgid, False, str(e)])
            return
        if self._strict and not conn._handshaken:
            # the documented contract (docs/CROSS_LANGUAGE.md): the
            # FIRST call on a connection must be _handshake; in
            # strict mode enforce it server-side so incompatible
            # clients can't bypass version detection
            conn.send([RESPONSE, msgid, False,
                       "protocol error: first request on a "
                       "connection must be _handshake (strict mode)"])
            return
        handler = getattr(self.service, "rpc_" + method, None)
        if handler is None:
            conn.send([RESPONSE, msgid, False, f"no such method: {method}"])
            return
        try:
            if self._schema_service is not None and self._strict:
                from ray_tpu._private import schema

                schema.validate_request(
                    self._schema_service, method, payload)
            t0 = time.perf_counter()
            c0 = time.thread_time()
            result = handler(conn, msgid, payload)
            event_stats.record(
                f"rpc.{self._stats_name}.{method}",
                time.perf_counter() - t0,
            )
            # CPU seconds of the handler itself: the honest "handler work"
            # measure when hundreds of in-process peers share one GIL and
            # wall time mostly measures the scheduler
            event_stats.record(
                f"rpc.{self._stats_name}.{method}.cpu",
                time.thread_time() - c0,
            )
            if result is not RpcServer.DEFERRED:
                conn.send([RESPONSE, msgid, True, result])
        except Exception:
            conn.send([RESPONSE, msgid, False, traceback.format_exc()])

    def stop(self) -> None:
        self._stopped.set()
        self._wake()
        try:
            self._srv.close()
        except OSError:
            pass
        self._loop_thread.join(timeout=5)
        self._pool.shutdown(wait=False)
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass


class RpcClient:
    """Blocking request/response client with a background reader thread.

    Push notifications are delivered to ``notify_handler(topic, payload)``
    on the reader thread — handlers must be quick or hand off.
    """

    def __init__(
        self,
        address: str,
        notify_handler: Callable[[str, Any], None] | None = None,
        connect_timeout: float = 10.0,
        auto_reconnect: bool = False,
        reconnect_window: float = 10.0,
        handshake: bool = True,
    ):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=connect_timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.address = address
        self._connect_timeout = connect_timeout
        self._auto_reconnect = auto_reconnect
        self._reconnect_window = reconnect_window
        self._send_lock = threading.Lock()
        self._pending: dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._msgid = 0
        self._gen = 0  # connection generation; bumped by reconnect()
        self._notify_handler = notify_handler
        self._closed = threading.Event()
        self._reader = threading.Thread(
            target=self._read_loop, args=(self._sock, 0), daemon=True,
            name=f"rpc-client-{address}",
        )
        self._reader.start()
        if handshake:
            # enforce protocol compatibility before the first real call
            # (schema.py PROTOCOL_VERSION; mismatch fails the connect)
            from ray_tpu._private import schema

            try:
                self.call_async("_handshake", schema.handshake_payload()) \
                    .result(connect_timeout)
            except BaseException as e:
                # any failure mode (mismatch, timeout, peer drop) must tear
                # the client down — a leaked socket + reader thread per
                # retry otherwise accumulates in reconnect loops
                self.close()
                raise RpcError(f"handshake with {address} failed: {e}") from e

    def _read_loop(self, sock: socket.socket, gen: int) -> None:
        while not self._closed.is_set():
            msg = _read_msg(sock)
            if msg is None:
                break
            mtype = msg[0]
            if mtype == RESPONSE:
                _, msgid, ok, payload = msg
                with self._pending_lock:
                    fut = self._pending.pop(msgid, None)
                if fut is not None:
                    if ok:
                        fut.set_result(payload)
                    else:
                        fut.set_exception(RpcError(str(payload)))
            elif mtype == NOTIFY and self._notify_handler is not None:
                _, _, topic, payload = msg
                try:
                    self._notify_handler(topic, payload)
                except Exception:
                    traceback.print_exc()
        # Connection lost: fail all pending calls AND every future call —
        # a send after this point can land in the kernel buffer without
        # error and would otherwise pend forever. _dead is set under
        # _pending_lock so a racing call_async either sees the flag or has
        # its future registered before the sweep below. A reader whose
        # generation was superseded by reconnect() must NOT run the sweep:
        # the pending futures now belong to the new connection.
        with self._pending_lock:
            if gen != self._gen:
                return
            self._dead = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError(f"connection to {self.address} lost"))
            self._pending.clear()

    _dead = False

    def call_async(self, method: str, payload: Any = None) -> Future:
        with self._pending_lock:
            if self._dead:
                fut: Future = Future()
                fut.set_exception(
                    ConnectionError(f"connection to {self.address} lost")
                )
                return fut
            self._msgid += 1
            msgid = self._msgid
            fut: Future = Future()
            self._pending[msgid] = fut
        data = _pack([REQUEST, msgid, method, payload])
        with self._send_lock:
            try:
                self._sock.sendall(data)
            except OSError as e:
                with self._pending_lock:
                    self._pending.pop(msgid, None)
                # The reader thread's connection-lost cleanup may have
                # already failed this future — don't double-complete.
                if not fut.done():
                    fut.set_exception(
                        ConnectionError(f"send to {self.address} failed: {e}")
                    )
        return fut

    def reconnect(self, connect_timeout: float | None = None) -> bool:
        """Re-establish a lost connection in place (e.g. GCS restart-in-place,
        reference: raylet reconnect on NotifyGCSRestart). The client object
        identity is preserved, so holders of this client (task-event buffer,
        cached peers) heal without re-plumbing. Returns True if a live
        connection exists afterwards."""
        with self._send_lock:
            with self._pending_lock:
                if self._closed.is_set():
                    return False
                if not self._dead:
                    return True
            host, port = self.address.rsplit(":", 1)
            try:
                sock = socket.create_connection(
                    (host, int(port)), timeout=connect_timeout or self._connect_timeout
                )
            except OSError:
                return False
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._pending_lock:
                # close() may have landed after the check above: don't
                # install a socket/reader on a closed client
                if self._closed.is_set():
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return False
                self._gen += 1
                gen = self._gen
                for fut in self._pending.values():
                    if not fut.done():
                        fut.set_exception(
                            ConnectionError(f"connection to {self.address} lost")
                        )
                self._pending.clear()
                old = self._sock
                self._sock = sock
                self._dead = False
            try:
                old.close()
            except OSError:
                pass
            self._reader = threading.Thread(
                target=self._read_loop, args=(sock, gen), daemon=True,
                name=f"rpc-client-{self.address}",
            )
            self._reader.start()
        # re-run the protocol check: a restart-in-place may have come back
        # as an upgraded binary. A version mismatch raises (permanent);
        # transient handshake failures report the connection as not healed.
        from ray_tpu._private import schema

        try:
            self.call_async("_handshake", schema.handshake_payload()) \
                .result(self._connect_timeout)
        except RpcError as e:
            self.close()
            raise RpcError(
                f"handshake with {self.address} failed after reconnect: {e}"
            ) from e
        except BaseException:
            return False
        return True

    def call(self, method: str, payload: Any = None, timeout: float | None = None) -> Any:
        try:
            return self.call_async(method, payload).result(timeout)
        except ConnectionError:
            if not self._auto_reconnect:
                raise
        # Auto-reconnect window: the server may be restarting in place.
        # Control-plane calls here are idempotent (registers, heartbeats,
        # gets, event appends), so a retry after reconnect is safe.
        deadline = time.monotonic() + self._reconnect_window
        while True:
            if self.reconnect():
                try:
                    return self.call_async(method, payload).result(timeout)
                except ConnectionError:
                    pass
            if self._closed.is_set() or time.monotonic() >= deadline:
                raise ConnectionError(
                    f"connection to {self.address} lost (reconnect window expired)"
                )
            time.sleep(0.1)

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class RpcError(Exception):
    """Remote handler raised; message carries the remote traceback."""
