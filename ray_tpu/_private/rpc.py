"""Control-plane RPC: msgpack-framed messages over TCP.

Equivalent in role to the reference's gRPC wrapper layer
(reference: src/ray/rpc/grpc_server.h, client_call.h — async server/client
call templates). The control plane here is deliberately small: length-prefixed
msgpack arrays over TCP, thread-per-connection servers, plus server→client
push notifications (used for task completion, pubsub delivery, and actor
state changes — the analog of the reference's long-poll pubsub,
src/ray/pubsub/publisher.h).

Wire format: [u32 len][msgpack array]
  request:  [0, msgid, method: str, payload]
  response: [1, msgid, ok: bool, payload_or_error]
  notify:   [2, 0, topic: str, payload]
"""
from __future__ import annotations

import socket
import struct
import threading
import time
import traceback
from concurrent.futures import Future
from typing import Any, Callable

import msgpack

from ray_tpu._private import event_stats

REQUEST, RESPONSE, NOTIFY = 0, 1, 2


def _pack(obj: Any) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    return struct.pack("<I", len(body)) + body


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    chunks = []
    while n > 0:
        try:
            c = sock.recv(n)
        except OSError:
            return None
        if not c:
            return None
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def _read_msg(sock: socket.socket) -> list | None:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack("<I", header)
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return msgpack.unpackb(body, raw=False)


class Connection:
    """Server-side handle to one client connection; safe concurrent sends."""

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.peer = peer
        self._send_lock = threading.Lock()
        self.closed = False
        # Services can attach identity here (e.g. worker id after register).
        self.meta: dict[str, Any] = {}
        self.on_close: list[Callable[[Connection], None]] = []

    def send(self, msg: list) -> bool:
        data = _pack(msg)
        with self._send_lock:
            try:
                self.sock.sendall(data)
                return True
            except OSError:
                return False

    def notify(self, topic: str, payload: Any) -> bool:
        return self.send([NOTIFY, 0, topic, payload])

    def close(self) -> None:
        self.closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class RpcServer:
    """Thread-per-connection RPC server dispatching to handler methods.

    Handlers are methods named ``rpc_<method>`` on the service object, called
    as ``handler(conn, msgid, payload)``; the return value is the response
    payload.
    A handler may instead return the DEFERRED sentinel and later complete the
    call via ``conn.send([RESPONSE, msgid, True, payload])`` — used for
    blocking calls (e.g. waiting on an actor to start) without tying up the
    connection's request loop.
    """

    DEFERRED = object()

    def __init__(self, service: Any, host: str = "127.0.0.1", port: int = 0):
        self.service = service
        # strict wire-schema validation (schema.py): services declare their
        # schema table via a `schema_service` class attribute
        self._schema_service = getattr(service, "schema_service", None)
        from ray_tpu._private import schema as _schema

        self._strict = _schema.strict_mode()
        # handler-latency accounting (event_stats.py; the reference's
        # instrumented_io_context records every asio handler the same way)
        self._stats_name = (self._schema_service
                            or type(service).__name__.lower())
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if port == 0:
            self._srv.bind((host, port))
        else:
            # fixed ports are used for restart-in-place (GCS FT); lingering
            # sockets from the previous incarnation can hold the port for a
            # moment — retry EADDRINUSE briefly; other errors fail fast
            import errno

            deadline = time.monotonic() + 10
            while True:
                try:
                    self._srv.bind((host, port))
                    break
                except OSError as e:
                    if e.errno != errno.EADDRINUSE or time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
        self._srv.listen(512)
        self.address = f"{host}:{self._srv.getsockname()[1]}"
        self._stopped = threading.Event()
        self.connections: set[Connection] = set()
        self._conn_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"rpc-accept-{self.address}"
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, addr = self._srv.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = Connection(sock, f"{addr[0]}:{addr[1]}")
            with self._conn_lock:
                self.connections.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name=f"rpc-conn-{conn.peer}",
            ).start()

    def _serve_conn(self, conn: Connection) -> None:
        handshaken = False
        try:
            while not self._stopped.is_set():
                msg = _read_msg(conn.sock)
                if msg is None:
                    break
                mtype, msgid, method, payload = msg
                if mtype != REQUEST:
                    continue
                if method == "_handshake":
                    # version negotiation, answered by the RPC layer itself
                    # (schema.py; the analog of proto compatibility checks)
                    from ray_tpu._private import schema

                    try:
                        conn.send([RESPONSE, msgid, True,
                                   schema.check_handshake(payload)])
                        handshaken = True
                    except schema.SchemaError as e:
                        conn.send([RESPONSE, msgid, False, str(e)])
                    continue
                if self._strict and not handshaken:
                    # the documented contract (docs/CROSS_LANGUAGE.md): the
                    # FIRST call on a connection must be _handshake; in
                    # strict mode enforce it server-side so incompatible
                    # clients can't bypass version detection
                    conn.send([RESPONSE, msgid, False,
                               "protocol error: first request on a "
                               "connection must be _handshake (strict mode)"])
                    continue
                handler = getattr(self.service, "rpc_" + method, None)
                if handler is None:
                    conn.send([RESPONSE, msgid, False, f"no such method: {method}"])
                    continue
                try:
                    if self._schema_service is not None and self._strict:
                        from ray_tpu._private import schema

                        schema.validate_request(
                            self._schema_service, method, payload)
                    t0 = time.perf_counter()
                    result = handler(conn, msgid, payload)
                    event_stats.record(
                        f"rpc.{self._stats_name}.{method}",
                        time.perf_counter() - t0,
                    )
                    if result is not RpcServer.DEFERRED:
                        conn.send([RESPONSE, msgid, True, result])
                except Exception:
                    conn.send([RESPONSE, msgid, False, traceback.format_exc()])
        finally:
            with self._conn_lock:
                self.connections.discard(conn)
            for cb in conn.on_close:
                try:
                    cb(conn)
                except Exception:
                    pass
            conn.close()

    def stop(self) -> None:
        self._stopped.set()
        try:
            # shutdown() first: a thread parked in accept() holds the fd
            # alive through CPython's close(), leaving the port LISTENING
            # forever; shutdown wakes it so close() actually releases the
            # port (restart-in-place depends on this)
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._conn_lock:
            for conn in list(self.connections):
                conn.close()


class RpcClient:
    """Blocking request/response client with a background reader thread.

    Push notifications are delivered to ``notify_handler(topic, payload)``
    on the reader thread — handlers must be quick or hand off.
    """

    def __init__(
        self,
        address: str,
        notify_handler: Callable[[str, Any], None] | None = None,
        connect_timeout: float = 10.0,
        auto_reconnect: bool = False,
        reconnect_window: float = 10.0,
        handshake: bool = True,
    ):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=connect_timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.address = address
        self._connect_timeout = connect_timeout
        self._auto_reconnect = auto_reconnect
        self._reconnect_window = reconnect_window
        self._send_lock = threading.Lock()
        self._pending: dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._msgid = 0
        self._gen = 0  # connection generation; bumped by reconnect()
        self._notify_handler = notify_handler
        self._closed = threading.Event()
        self._reader = threading.Thread(
            target=self._read_loop, args=(self._sock, 0), daemon=True,
            name=f"rpc-client-{address}",
        )
        self._reader.start()
        if handshake:
            # enforce protocol compatibility before the first real call
            # (schema.py PROTOCOL_VERSION; mismatch fails the connect)
            from ray_tpu._private import schema

            try:
                self.call_async("_handshake", schema.handshake_payload()) \
                    .result(connect_timeout)
            except BaseException as e:
                # any failure mode (mismatch, timeout, peer drop) must tear
                # the client down — a leaked socket + reader thread per
                # retry otherwise accumulates in reconnect loops
                self.close()
                raise RpcError(f"handshake with {address} failed: {e}") from e

    def _read_loop(self, sock: socket.socket, gen: int) -> None:
        while not self._closed.is_set():
            msg = _read_msg(sock)
            if msg is None:
                break
            mtype = msg[0]
            if mtype == RESPONSE:
                _, msgid, ok, payload = msg
                with self._pending_lock:
                    fut = self._pending.pop(msgid, None)
                if fut is not None:
                    if ok:
                        fut.set_result(payload)
                    else:
                        fut.set_exception(RpcError(str(payload)))
            elif mtype == NOTIFY and self._notify_handler is not None:
                _, _, topic, payload = msg
                try:
                    self._notify_handler(topic, payload)
                except Exception:
                    traceback.print_exc()
        # Connection lost: fail all pending calls AND every future call —
        # a send after this point can land in the kernel buffer without
        # error and would otherwise pend forever. _dead is set under
        # _pending_lock so a racing call_async either sees the flag or has
        # its future registered before the sweep below. A reader whose
        # generation was superseded by reconnect() must NOT run the sweep:
        # the pending futures now belong to the new connection.
        with self._pending_lock:
            if gen != self._gen:
                return
            self._dead = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError(f"connection to {self.address} lost"))
            self._pending.clear()

    _dead = False

    def call_async(self, method: str, payload: Any = None) -> Future:
        with self._pending_lock:
            if self._dead:
                fut: Future = Future()
                fut.set_exception(
                    ConnectionError(f"connection to {self.address} lost")
                )
                return fut
            self._msgid += 1
            msgid = self._msgid
            fut: Future = Future()
            self._pending[msgid] = fut
        data = _pack([REQUEST, msgid, method, payload])
        with self._send_lock:
            try:
                self._sock.sendall(data)
            except OSError as e:
                with self._pending_lock:
                    self._pending.pop(msgid, None)
                # The reader thread's connection-lost cleanup may have
                # already failed this future — don't double-complete.
                if not fut.done():
                    fut.set_exception(
                        ConnectionError(f"send to {self.address} failed: {e}")
                    )
        return fut

    def reconnect(self, connect_timeout: float | None = None) -> bool:
        """Re-establish a lost connection in place (e.g. GCS restart-in-place,
        reference: raylet reconnect on NotifyGCSRestart). The client object
        identity is preserved, so holders of this client (task-event buffer,
        cached peers) heal without re-plumbing. Returns True if a live
        connection exists afterwards."""
        with self._send_lock:
            with self._pending_lock:
                if self._closed.is_set():
                    return False
                if not self._dead:
                    return True
            host, port = self.address.rsplit(":", 1)
            try:
                sock = socket.create_connection(
                    (host, int(port)), timeout=connect_timeout or self._connect_timeout
                )
            except OSError:
                return False
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._pending_lock:
                # close() may have landed after the check above: don't
                # install a socket/reader on a closed client
                if self._closed.is_set():
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return False
                self._gen += 1
                gen = self._gen
                for fut in self._pending.values():
                    if not fut.done():
                        fut.set_exception(
                            ConnectionError(f"connection to {self.address} lost")
                        )
                self._pending.clear()
                old = self._sock
                self._sock = sock
                self._dead = False
            try:
                old.close()
            except OSError:
                pass
            self._reader = threading.Thread(
                target=self._read_loop, args=(sock, gen), daemon=True,
                name=f"rpc-client-{self.address}",
            )
            self._reader.start()
        # re-run the protocol check: a restart-in-place may have come back
        # as an upgraded binary. A version mismatch raises (permanent);
        # transient handshake failures report the connection as not healed.
        from ray_tpu._private import schema

        try:
            self.call_async("_handshake", schema.handshake_payload()) \
                .result(self._connect_timeout)
        except RpcError as e:
            self.close()
            raise RpcError(
                f"handshake with {self.address} failed after reconnect: {e}"
            ) from e
        except BaseException:
            return False
        return True

    def call(self, method: str, payload: Any = None, timeout: float | None = None) -> Any:
        try:
            return self.call_async(method, payload).result(timeout)
        except ConnectionError:
            if not self._auto_reconnect:
                raise
        # Auto-reconnect window: the server may be restarting in place.
        # Control-plane calls here are idempotent (registers, heartbeats,
        # gets, event appends), so a retry after reconnect is safe.
        deadline = time.monotonic() + self._reconnect_window
        while True:
            if self.reconnect():
                try:
                    return self.call_async(method, payload).result(timeout)
                except ConnectionError:
                    pass
            if self._closed.is_set() or time.monotonic() >= deadline:
                raise ConnectionError(
                    f"connection to {self.address} lost (reconnect window expired)"
                )
            time.sleep(0.1)

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class RpcError(Exception):
    """Remote handler raised; message carries the remote traceback."""
