"""GCS — the global control service (cluster metadata + coordination).

Equivalent of the reference's GCS server
(reference: src/ray/gcs/gcs_server/gcs_server.h:79 composing GcsNodeManager,
GcsActorManager (actor FT state machine, gcs_actor_manager.h:281),
GcsPlacementGroupManager with its 2-phase scheduler
(gcs_placement_group_scheduler.cc:884), internal KV (gcs_kv_manager.h:138),
health checks (gcs_health_check_manager.h:39), and pubsub). Here it is one
Python service object behind an RpcServer, storing state in process memory
(the reference's default InMemoryStoreClient) — a Redis-like external store
can be slotted in behind the same table dicts later.

Placement groups use the same 2-phase reserve/commit protocol as the
reference: prepare on every chosen raylet, commit only if all prepared,
else cancel (node_manager.cc:1832,1848 equivalents live in raylet.py).
"""
from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict
from typing import Any

from ray_tpu._private import scheduler as sched
from ray_tpu._private.config import global_config
from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.rpc import RpcClient, RpcServer


class GcsService:
    # strict-mode wire validation against schema.SCHEMAS["gcs"] (rpc.py)
    schema_service = "gcs"

    def __init__(self, store=None):
        """store: a StoreClient (store_client.py). File-backed stores give
        head-restart tolerance — the reference's Redis-backed GCS mode
        (redis_store_client.h:33); None/in-memory is the default mode."""
        from ray_tpu._private.store_client import InMemoryStoreClient

        self._store = store or InMemoryStoreClient()
        self._dirty = 0
        self._persisted = 0
        self._lock = threading.RLock()
        # namespace -> key -> value
        self._kv: dict[str, dict[bytes, bytes]] = defaultdict(dict)
        # node_id(bytes) -> {address, resources, labels, alive, last_heartbeat}
        self.nodes: dict[bytes, dict] = {}
        # delta-sync state: monotonically versioned node-table mutations
        # (reference: ray_syncer.h:86 version-stamped delta gossip)
        self._node_seq = 0
        self._node_tombstones: list[tuple[int, bytes]] = []
        self._tombstone_floor = 0  # removals below this seq were trimmed
        # seq-ordered log of CHANGED nodes so a settled heartbeat's delta
        # read is O(changes since seen), not an O(N) scan of the node
        # table per tick — at N nodes x N heartbeats/s that scan was the
        # control plane's fan-in ceiling
        self._node_change_log: list[tuple[int, bytes]] = []
        self._change_floor = 0  # changes below this seq were trimmed
        # pushed node_delta ordering: seq-ordered outbox (appended under
        # _lock) + a single-flusher lock so publishes can't reorder
        self._delta_outbox: list[dict] = []
        self._delta_pub_lock = threading.Lock()
        # actor_id(bytes) -> {state, class_name, node_id, raylet_address,
        #                     num_restarts, max_restarts, spec}
        self.actors: dict[bytes, dict] = {}
        # pg_id(bytes) -> {bundles, strategy, state, allocations}
        self.placement_groups: dict[bytes, dict] = {}
        self._job_counter = 0
        # object directory: object_id(bytes) -> {"nodes": set[node_id],
        # "evicted": bool}. Locations are runtime state fed by store
        # seal/evict notifications via each raylet; NOT persisted (stores
        # don't survive a head restart either). Reference: the object
        # directory role of ownership_based_object_directory.cc:551, here
        # GCS-resolved (round-3 simplification, owner-resolution later).
        self.object_dir: dict[bytes, dict] = {}
        # tombstoned entries age out (health loop) so the directory doesn't
        # grow with every object ever created; live-location entries are
        # real state and stay
        self._dir_tombstone_ts: dict[bytes, float] = {}
        self._dir_tombstone_ttl_s = 300.0
        # topic -> set of conns
        self._subs: dict[str, set] = defaultdict(set)
        self._raylet_clients: dict[bytes, RpcClient] = {}
        self._task_events: list[dict] = []
        self.server: RpcServer | None = None
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="gcs-health"
        )
        self._stopped = threading.Event()

    def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._restore()
        self.server = RpcServer(self, host, port)
        self._health_thread.start()
        # snapshotting every table under the lock is pure overhead when the
        # store is the no-op in-memory default — only run it for real stores
        if getattr(self._store, "persistent", True):
            self._persist_thread = threading.Thread(
                target=self._persist_loop, daemon=True, name="gcs-persist"
            )
            self._persist_thread.start()
        return self.server.address

    def stop(self) -> None:
        self._stopped.set()
        self._persist_now()
        for c in self._raylet_clients.values():
            c.close()
        if self.server:
            self.server.stop()

    # ---------------- persistence (GCS FT) ----------------

    def _mark_dirty(self) -> None:
        self._dirty += 1

    def _snapshot(self) -> dict:
        with self._lock:
            return {
                "kv": {ns: dict(d) for ns, d in self._kv.items()},
                # connections don't survive a restart; nodes re-register on
                # their next heartbeat (raylet reregister path)
                "actors": {
                    aid: dict(a) for aid, a in self.actors.items()
                },
                "placement_groups": {
                    pid: dict(p) for pid, p in self.placement_groups.items()
                },
                "job_counter": self._job_counter,
                "task_events": list(self._task_events),
            }

    def _persist_now(self) -> None:
        if not getattr(self._store, "persistent", True):
            return
        with self._lock:
            version = self._dirty
            if version == self._persisted:
                return
        try:
            self._store.save(self._snapshot())
            with self._lock:
                self._persisted = version
        except Exception:  # noqa: BLE001 — persistence must not kill the GCS
            pass

    def _persist_loop(self) -> None:
        while not self._stopped.wait(0.2):
            self._persist_now()

    def _restore(self) -> None:
        snap = self._store.load()
        if not snap:
            return
        with self._lock:
            for ns, d in snap.get("kv", {}).items():
                self._kv[ns].update(d)
            self.actors.update(snap.get("actors", {}))
            self.placement_groups.update(snap.get("placement_groups", {}))
            self._job_counter = snap.get("job_counter", 0)
            self._task_events = list(snap.get("task_events", []))

    # ---------------- internal helpers ----------------

    def _raylet(self, node_id: bytes) -> RpcClient:
        with self._lock:
            client = self._raylet_clients.get(node_id)
            if client is None:
                client = RpcClient(self.nodes[node_id]["address"])
                self._raylet_clients[node_id] = client
            return client

    def _publish(self, topic: str, payload: Any) -> None:
        with self._lock:
            conns = list(self._subs.get(topic, ()))
        for conn in conns:
            if not conn.notify(topic, payload):
                with self._lock:
                    self._subs[topic].discard(conn)

    def _queue_node_delta_locked(self, payload: dict) -> None:
        """Called under self._lock at the seq-assignment site: appending
        while holding the lock keeps the outbox in seq order, so the
        flusher (outside the lock) can never publish deltas out of order —
        a reordered push would hit subscribers' seq gap guard and stall
        the push channel until their next pull."""
        self._delta_outbox.append(payload)

    def _flush_node_deltas(self) -> None:
        while True:
            with self._delta_pub_lock:
                with self._lock:
                    if not self._delta_outbox:
                        return
                    payload = self._delta_outbox.pop(0)
                self._publish("node_delta", payload)

    def _health_loop(self) -> None:
        cfg = global_config()
        interval = cfg.gcs_heartbeat_interval_ms / 1000.0
        threshold = cfg.health_check_failure_threshold
        while not self._stopped.wait(interval):
            now = time.monotonic()
            dead = []
            with self._lock:
                for node_id, info in self.nodes.items():
                    if not info["alive"]:
                        continue
                    if now - info["last_heartbeat"] > interval * threshold:
                        info["alive"] = False
                        dead.append(node_id)
                # sweep aged object-directory tombstones (getters that still
                # care learned "evicted" long ago and reconstructed). PENDING
                # frees (freed before any seal, not yet applied) are exempt:
                # their marker must survive until the late seal arrives.
                cutoff = now - self._dir_tombstone_ttl_s
                expired = [
                    oid for oid, ts in self._dir_tombstone_ts.items()
                    if ts < cutoff
                ]
                for oid in expired:
                    e = self.object_dir.get(oid)
                    if e is not None and e.get("freed") and not e.get("free_applied"):
                        continue
                    del self._dir_tombstone_ts[oid]
                    self.object_dir.pop(oid, None)
            for node_id in dead:
                self._on_node_death(node_id)

    def _on_node_death(self, node_id: bytes) -> None:
        """Broadcast death; fail actors on that node (restart handled by owner
        resubmission in round 1 — reference restarts centrally via
        GcsActorManager::RestartActor)."""
        self._publish("node_death", {"node_id": node_id})
        with self._lock:
            self._node_seq += 1
            tomb_seq = self._node_seq
            self._queue_node_delta_locked(
                {"delta": [], "removed": [node_id], "seq": tomb_seq})
            self._node_tombstones.append((self._node_seq, node_id))
            if len(self._node_tombstones) > 1000:
                # clients older than the trimmed horizon get a full resync
                self._tombstone_floor = self._node_tombstones[-1000][0]
                del self._node_tombstones[:-1000]
            affected = [
                aid for aid, a in self.actors.items() if a.get("node_id") == node_id
            ]
            for aid in affected:
                self.actors[aid]["state"] = "DEAD"
        for aid in affected:
            self._publish("actor:" + aid.hex(), {"state": "DEAD", "reason": "node died"})
        # push-path of the delta syncer: subscribers learn of the removal
        # NOW; the 1 Hz heartbeat pull remains the reconciliation backstop
        self._flush_node_deltas()

    # ---------------- RPC: KV ----------------

    def rpc_kv_put(self, conn, msgid, p):
        with self._lock:
            ns = self._kv[p.get("ns", "default")]
            existed = p["key"] in ns
            if p.get("overwrite", True) or not existed:
                ns[p["key"]] = p["value"]
            self._mark_dirty()
        return {"added": not existed}

    def rpc_kv_get(self, conn, msgid, p):
        with self._lock:
            return {"value": self._kv[p.get("ns", "default")].get(p["key"])}

    def rpc_kv_del(self, conn, msgid, p):
        with self._lock:
            deleted = self._kv[p.get("ns", "default")].pop(p["key"], None) is not None
            self._mark_dirty()
            return {"deleted": deleted}

    def rpc_kv_keys(self, conn, msgid, p):
        prefix = p.get("prefix", b"")
        with self._lock:
            return {"keys": [k for k in self._kv[p.get("ns", "default")] if k.startswith(prefix)]}

    # ---------------- RPC: nodes ----------------

    def _bump_node_seq_locked(self, info: dict) -> None:
        """Version-stamp a node-table mutation for the delta syncer
        (reference: ray_syncer.h:86 — components exchange version-stamped
        deltas, not full snapshots)."""
        self._node_seq += 1
        info["_seq"] = self._node_seq
        nid = info.get("node_id")
        if nid is not None:
            self._node_change_log.append((self._node_seq, nid))
            cap = max(1000, 4 * len(self.nodes))
            if len(self._node_change_log) > cap:
                # trim the oldest half; readers older than the floor get a
                # full resync (same protocol as tombstone trimming)
                keep = cap // 2
                self._change_floor = self._node_change_log[-keep][0]
                del self._node_change_log[:-keep]

    def _node_view_locked(self, nid: bytes, n: dict) -> dict:
        view = {
            "node_id": nid,
            "address": n["address"],
            "resources": n["resources"],
            "labels": n["labels"],
            "alive": n["alive"],
            "available": n.get("available", n["resources"]),
            "load": n.get("load", 0),
            "pending_shapes": n.get("pending_shapes", []),
            "store_socket": n.get("store_socket", ""),
        }
        if "disk_used_frac" in n:
            view["disk_used_frac"] = n["disk_used_frac"]
        return view

    def rpc_register_node(self, conn, msgid, p):
        with self._lock:
            self.nodes[p["node_id"]] = info = {
                "node_id": p["node_id"],  # self-identifying for change log
                "address": p["address"],
                "resources": p["resources"],
                "labels": p.get("labels", {}),
                "store_socket": p.get("store_socket", ""),
                "alive": True,
                "last_heartbeat": time.monotonic(),
            }
            self._bump_node_seq_locked(info)
            self._queue_node_delta_locked({
                "delta": [self._node_view_locked(p["node_id"], info)],
                "removed": [], "seq": info["_seq"],
            })
        self._publish("node_added", {"node_id": p["node_id"], "address": p["address"]})
        self._flush_node_deltas()
        return {"ok": True}

    def rpc_heartbeat(self, conn, msgid, p):
        """Periodic resource report — the RaySyncer-gossip analog
        (reference: src/ray/common/ray_syncer/ray_syncer.h:86). With a
        `seen_seq`, the reply carries the DELTA of the node table since
        that version (changed node views + removed ids) instead of the
        raylet re-pulling the full table every tick."""
        with self._lock:
            info = self.nodes.get(p["node_id"])
            if info is None:
                return {"ok": False, "reregister": True}
            info["last_heartbeat"] = time.monotonic()
            # a REVIVAL (health-loop death then the node resumed
            # heartbeating) must re-version the entry even when no value
            # changed: peers popped it on the tombstone and only a newer
            # _seq ever re-adds it to their deltas
            changed = not info["alive"]
            info["alive"] = True
            # ...otherwise bump the sync version ONLY when a reported value
            # actually changed — every-tick bumps would degenerate each
            # delta to a full table
            for k in ("available", "load", "pending_shapes", "disk_used_frac"):
                if k in p and info.get(k) != p[k]:
                    info[k] = p[k]
                    changed = True
            if changed:
                self._bump_node_seq_locked(info)
                # push-path: peers see the new view without waiting for
                # their own next pull tick (reference: RaySyncer's pushed
                # version-stamped deltas, ray_syncer.h:86)
                self._queue_node_delta_locked({
                    "delta": [self._node_view_locked(p["node_id"], info)],
                    "removed": [], "seq": info["_seq"],
                })
            reply = {"ok": True}
            if "seen_seq" in p:
                seen = p["seen_seq"]
                reply["seq"] = self._node_seq
                if seen < self._tombstone_floor or seen < self._change_floor:
                    # history trimmed past this client: full resync
                    seen = 0
                    reply["full"] = True
                if reply.get("full"):
                    reply["delta"] = [
                        self._node_view_locked(nid, n)
                        for nid, n in self.nodes.items()
                        if n["alive"]
                    ]
                else:
                    # O(changes) read off the seq-ordered change log — a
                    # settled cluster's heartbeat must not scan N nodes
                    i = bisect.bisect_left(self._node_change_log,
                                           (seen + 1, b""))
                    seen_nids = set()
                    reply["delta"] = []
                    for _s, nid in self._node_change_log[i:]:
                        if nid in seen_nids:
                            continue
                        seen_nids.add(nid)
                        n = self.nodes.get(nid)
                        if n is not None and n["alive"] and \
                                n.get("_seq", 0) > seen:
                            reply["delta"].append(
                                self._node_view_locked(nid, n))
                j = bisect.bisect_left(self._node_tombstones, (seen + 1, b""))
                reply["removed"] = [
                    nid for _seq, nid in self._node_tombstones[j:]
                ]
        self._flush_node_deltas()
        return reply

    def rpc_drain_node(self, conn, msgid, p):
        with self._lock:
            info = self.nodes.get(p["node_id"])
            if info is not None:
                info["alive"] = False
        self._on_node_death(p["node_id"])
        return {"ok": True}

    def rpc_get_nodes(self, conn, msgid, p):
        with self._lock:
            return {
                "nodes": [
                    self._node_view_locked(nid, n)
                    for nid, n in self.nodes.items()
                ]
            }

    def rpc_cluster_resources(self, conn, msgid, p):
        total: dict[str, float] = defaultdict(float)
        available: dict[str, float] = defaultdict(float)
        with self._lock:
            for n in self.nodes.values():
                if not n["alive"]:
                    continue
                for k, v in n["resources"].items():
                    total[k] += v
                for k, v in n.get("available", n["resources"]).items():
                    available[k] += v
        return {"total": dict(total), "available": dict(available)}

    # ---------------- RPC: object directory ----------------

    def rpc_object_location_update(self, conn, msgid, p):
        """Batched, ORDERED location updates from a raylet's store-event
        stream. p: {node_id, events: [["s"|"e", oid], ...]} — order matters:
        evict-then-reseal within one batch must end as present."""
        nid = p["node_id"]
        now = time.monotonic()
        late_frees: list[tuple[bytes, bytes]] = []  # (node_id, oid)
        with self._lock:
            for ev, oid in p["events"]:
                e = self.object_dir.get(oid)
                if ev == "s":
                    if e is None:
                        e = self.object_dir[oid] = {"nodes": set(), "evicted": False}
                    e["nodes"].add(nid)
                    e["evicted"] = False
                    self._dir_tombstone_ts.pop(oid, None)
                    if e.get("freed"):
                        # owner freed this object before it was ever sealed
                        # (fire-and-forget task result): free it now
                        e["free_applied"] = True
                        self._dir_tombstone_ts[oid] = now  # sweepable again
                        late_frees.append((nid, oid))
                else:
                    if e is None:
                        continue
                    e["nodes"].discard(nid)
                    if not e["nodes"]:
                        e["evicted"] = True  # tombstone: owners reconstruct
                        self._dir_tombstone_ts[oid] = now
        for nid_, oid in late_frees:
            self._free_on_node(nid_, oid)
        return {"ok": True}

    def _free_on_node(self, node_id: bytes, oid: bytes) -> None:
        try:
            self._raylet(node_id).call_async("free_object", {"object_id": oid})
        except Exception:  # noqa: BLE001 — holder died; nothing to free
            pass

    def rpc_free_object(self, conn, msgid, p):
        """Owner reports zero references: release the object's copies
        everywhere (reference: zero-ref plasma free driven by the owner's
        ReferenceCounter). Idempotent; copies sealed later are freed on
        arrival via the 'freed' flag."""
        oid = p["object_id"]
        with self._lock:
            e = self.object_dir.get(oid)
            if e is None:
                e = self.object_dir[oid] = {"nodes": set(), "evicted": False}
            e["freed"] = True
            holders = list(e["nodes"])
            if holders:
                # applied now: the entry may age out via the tombstone sweep
                e["free_applied"] = True
                self._dir_tombstone_ts.setdefault(oid, time.monotonic())
            # else: PENDING free (result not sealed yet) — the sweep skips
            # unapplied frees so a late seal still gets unpinned, however
            # late (bounded by in-flight fire-and-forget tasks)
        for nid in holders:
            self._free_on_node(nid, oid)
        return {"ok": True}

    def rpc_get_object_locations(self, conn, msgid, p):
        oid = p["object_id"]
        with self._lock:
            e = self.object_dir.get(oid)
            if e is None:
                return {"nodes": [], "evicted": False, "known": False}
            alive = [
                {"node_id": nid, "address": self.nodes[nid]["address"]}
                for nid in e["nodes"]
                if nid in self.nodes and self.nodes[nid]["alive"]
            ]
            # every holder died: the object is lost (reconstructible only
            # via lineage) — report it as evicted
            lost = not alive and (e["evicted"] or bool(e["nodes"]))
            return {"nodes": alive, "evicted": lost, "known": True}

    # ---------------- RPC: jobs ----------------

    def rpc_next_job_id(self, conn, msgid, p):
        with self._lock:
            self._job_counter += 1
            self._mark_dirty()
            return {"job_id": self._job_counter.to_bytes(4, "little")}

    # ---------------- RPC: actors ----------------

    def rpc_register_actor(self, conn, msgid, p):
        with self._lock:
            self.actors[p["actor_id"]] = {
                "state": "PENDING_CREATION",
                "class_name": p.get("class_name", ""),
                "name": p.get("name"),
                "node_id": None,
                "raylet_address": None,
                "num_restarts": 0,
                "max_restarts": p.get("max_restarts", 0),
            }
            self._mark_dirty()
        return {"ok": True}

    def rpc_update_actor(self, conn, msgid, p):
        aid = p["actor_id"]
        with self._lock:
            actor = self.actors.get(aid)
            if actor is None:
                return {"ok": False}
            actor.update(
                {k: p[k] for k in ("state", "node_id", "raylet_address", "worker_id") if k in p}
            )
            if p.get("increment_restarts"):
                actor["num_restarts"] += 1
            self._mark_dirty()
            snapshot = dict(actor)
        self._publish("actor:" + aid.hex(), snapshot)
        return {"ok": True}

    def rpc_get_actor(self, conn, msgid, p):
        with self._lock:
            actor = self.actors.get(p["actor_id"])
            return {"actor": dict(actor) if actor else None}

    def rpc_get_named_actor(self, conn, msgid, p):
        with self._lock:
            for aid, a in self.actors.items():
                if a.get("name") == p["name"] and a["state"] != "DEAD":
                    return {"actor_id": aid, "actor": dict(a)}
        return {"actor_id": None, "actor": None}

    def rpc_list_actors(self, conn, msgid, p):
        with self._lock:
            return {
                "actors": [
                    dict(a, actor_id=aid) for aid, a in self.actors.items()
                ]
            }

    # ---------------- RPC: placement groups ----------------

    def rpc_create_placement_group(self, conn, msgid, p):
        """Two-phase bundle reservation across raylets
        (reference: gcs_placement_group_scheduler.cc:884)."""
        pg_id = p["pg_id"]
        bundles: list[dict[str, float]] = p["bundles"]
        strategy = p.get("strategy", "PACK")
        with self._lock:
            nodes = {
                nid: dict(n) for nid, n in self.nodes.items() if n["alive"]
            }
        placement = sched.schedule_bundles(bundles, strategy, nodes)
        if placement is None:
            with self._lock:
                self.placement_groups[pg_id] = {
                    "bundles": bundles,
                    "strategy": strategy,
                    "state": "PENDING",
                    "allocations": None,
                }
                self._mark_dirty()
            return {"ok": False, "state": "PENDING",
                    "reason": "infeasible or insufficient resources"}

        # Phase 1: prepare on each raylet.
        prepared: list[tuple[bytes, int]] = []
        ok = True
        for bundle_index, node_id in enumerate(placement):
            try:
                r = self._raylet(node_id).call(
                    "prepare_bundle",
                    {"pg_id": pg_id, "bundle_index": bundle_index,
                     "resources": bundles[bundle_index]},
                    timeout=10,
                )
                if not r.get("ok"):
                    ok = False
                    break
                prepared.append((node_id, bundle_index))
            except Exception:
                ok = False
                break
        if not ok:
            for node_id, bundle_index in prepared:
                try:
                    self._raylet(node_id).call(
                        "cancel_bundle", {"pg_id": pg_id, "bundle_index": bundle_index}
                    )
                except Exception:
                    pass
            return {"ok": False, "state": "PENDING", "reason": "prepare failed"}
        # Phase 2: commit. A node dying mid-commit rolls back the whole
        # group so no prepared reservation leaks.
        committed: list[tuple[bytes, int]] = []
        try:
            for node_id, bundle_index in prepared:
                self._raylet(node_id).call(
                    "commit_bundle", {"pg_id": pg_id, "bundle_index": bundle_index}
                )
                committed.append((node_id, bundle_index))
        except Exception:
            for node_id, bundle_index in prepared:
                try:
                    self._raylet(node_id).call(
                        "cancel_bundle",
                        {"pg_id": pg_id, "bundle_index": bundle_index},
                    )
                except Exception:
                    pass
            return {"ok": False, "state": "PENDING", "reason": "commit failed"}
        with self._lock:
            self.placement_groups[pg_id] = {
                "bundles": bundles,
                "strategy": strategy,
                "state": "CREATED",
                "allocations": [
                    {"node_id": nid, "bundle_index": bi} for nid, bi in prepared
                ],
            }
            self._mark_dirty()
        self._publish("pg:" + pg_id.hex(), {"state": "CREATED"})
        return {"ok": True, "state": "CREATED",
                "allocations": self.placement_groups[pg_id]["allocations"]}

    def rpc_remove_placement_group(self, conn, msgid, p):
        pg_id = p["pg_id"]
        with self._lock:
            pg = self.placement_groups.get(pg_id)
        if pg and pg.get("allocations"):
            for alloc in pg["allocations"]:
                try:
                    self._raylet(alloc["node_id"]).call(
                        "return_bundle",
                        {"pg_id": pg_id, "bundle_index": alloc["bundle_index"]},
                    )
                except Exception:
                    pass
        with self._lock:
            if pg_id in self.placement_groups:
                self.placement_groups[pg_id]["state"] = "REMOVED"
            self._mark_dirty()
        return {"ok": True}

    def rpc_get_placement_group(self, conn, msgid, p):
        with self._lock:
            pg = self.placement_groups.get(p["pg_id"])
            return {"pg": dict(pg) if pg else None}

    # ---------------- RPC: pubsub ----------------

    def rpc_subscribe(self, conn, msgid, p):
        with self._lock:
            self._subs[p["topic"]].add(conn)
        conn.on_close.append(lambda c: self._unsub_all(c))
        return {"ok": True}

    def rpc_unsubscribe(self, conn, msgid, p):
        with self._lock:
            self._subs[p["topic"]].discard(conn)
        return {"ok": True}

    def _unsub_all(self, conn) -> None:
        with self._lock:
            for subs in self._subs.values():
                subs.discard(conn)

    def rpc_publish(self, conn, msgid, p):
        self._publish(p["topic"], p["payload"])
        return {"ok": True}

    # ---------------- RPC: task events (observability) ----------------

    def rpc_add_task_events(self, conn, msgid, p):
        cfg = global_config()
        with self._lock:
            self._task_events.extend(p["events"])
            overflow = len(self._task_events) - cfg.task_events_buffer_size
            if overflow > 0:
                del self._task_events[:overflow]
            self._mark_dirty()
        return {"ok": True}

    def rpc_list_task_events(self, conn, msgid, p):
        with self._lock:
            events = list(self._task_events)
        if p and p.get("job_id"):
            events = [e for e in events if e.get("job_id") == p["job_id"]]
        if p and p.get("trace_id"):
            # server-side trace filter: one trace's fetch cost no longer
            # scales with total task-event volume (tracing.get_trace)
            events = [e for e in events if e.get("trace_id") == p["trace_id"]]
        if p and p.get("limit"):
            # newest-first cap — a post-mortem wants the tail, not the head
            events = events[-int(p["limit"]):]
        return {"events": events}


# ---------------- client-side internal-KV helpers ----------------
#
# The internal KV has always been server-complete (rpc_kv_* above,
# persisted with the rest of the GCS tables when the store is durable)
# but had no Python client path; the Serve controller's crash-recovery
# checkpoints are the first consumer (reference:
# gcs_kv_manager.h:138 InternalKVInterface — every Ray component stores
# restart-survivable state there rather than in process memory).
# Keys and values are bytes on the wire; ``ns`` scopes independent
# consumers into separate keyspaces.


def kv_put(key: bytes, value: bytes, *, ns: str = "default") -> bool:
    """Store ``key`` -> ``value`` in the GCS internal KV. One RPC, one
    atomic dict assignment server-side — a reader sees the old value or
    the new one, never a torn write. Returns True when the key is new."""
    from ray_tpu._private.worker import global_worker

    r = global_worker().gcs.call(
        "kv_put", {"key": key, "value": value, "ns": ns, "overwrite": True}
    )
    return bool(r.get("added"))


def kv_get(key: bytes, *, ns: str = "default") -> bytes | None:
    """Fetch a value from the GCS internal KV (None when absent)."""
    from ray_tpu._private.worker import global_worker

    return global_worker().gcs.call("kv_get", {"key": key, "ns": ns})["value"]


def kv_del(key: bytes, *, ns: str = "default") -> bool:
    """Delete a key from the GCS internal KV; True if it existed."""
    from ray_tpu._private.worker import global_worker

    r = global_worker().gcs.call("kv_del", {"key": key, "ns": ns})
    return bool(r.get("deleted"))
