"""Memory pressure detection for the raylet's OOM-killing policy.

Equivalent of the reference's MemoryMonitor
(reference: src/ray/common/memory_monitor.h:52 — kernel memory usage vs a
threshold triggers worker-killing policies, worker_killing_policy.cc:116).
Reads cgroup v2 limits when present (containers) and falls back to
/proc/meminfo; the reader is injectable for tests and policies.
"""
from __future__ import annotations

import os
from typing import Callable


def _read_cgroup_v2() -> tuple[int, int] | None:
    """(used_bytes, limit_bytes) from cgroup v2, or None."""
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        if raw == "max":
            return None  # unlimited: defer to system meminfo
        limit = int(raw)
        with open("/sys/fs/cgroup/memory.current") as f:
            used = int(f.read().strip())
        return used, limit
    except (OSError, ValueError):
        return None


def _read_meminfo() -> tuple[int, int] | None:
    """(used_bytes, total_bytes) from /proc/meminfo, or None."""
    try:
        fields = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                fields[k] = int(rest.strip().split()[0]) * 1024
        total = fields["MemTotal"]
        avail = fields.get("MemAvailable", fields.get("MemFree", 0))
        return total - avail, total
    except (OSError, KeyError, ValueError, IndexError):
        return None


def system_memory_usage() -> tuple[int, int] | None:
    """(used, limit) preferring the container's cgroup over the host."""
    return _read_cgroup_v2() or _read_meminfo()


def process_rss_bytes(pid: int) -> int:
    """Resident set size of one process (0 if unreadable/gone)."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


class MemoryMonitor:
    """Threshold check over an injectable reading (reference:
    memory_monitor.h IsUsageAboveThreshold)."""

    def __init__(
        self,
        usage_threshold: float,
        read_fn: Callable[[], tuple[int, int] | None] | None = None,
    ):
        self.usage_threshold = usage_threshold
        self._read = read_fn or system_memory_usage

    def usage_fraction(self) -> float | None:
        r = self._read()
        if not r or r[1] <= 0:
            return None
        used, limit = r
        return used / limit

    def is_over_threshold(self) -> bool:
        frac = self.usage_fraction()
        return frac is not None and frac > self.usage_threshold
