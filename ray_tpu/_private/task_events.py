"""Task-event buffering: per-worker event log flushed to the GCS.

Equivalent of the reference's core-worker task event buffer
(reference: src/ray/core_worker/task_event_buffer.cc — events buffered
in-process, flushed periodically to GcsTaskManager
src/ray/gcs/gcs_server/gcs_task_manager.h:326). Events power the state API
(`list_tasks`, `summarize_tasks`) and the chrome timeline.
"""
from __future__ import annotations

import threading
import time
from typing import Any

_FLUSH_INTERVAL_S = 0.5
_MAX_BUFFER = 1000


class TaskEventBuffer:
    def __init__(self, gcs_client, worker_id_hex: str, node_id_hex: str):
        self._gcs = gcs_client
        self._worker_id = worker_id_hex
        self._node_id = node_id_hex
        self._lock = threading.Lock()
        self._buffer: list[dict] = []
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True, name="task-events"
        )
        self._thread.start()

    def record(
        self,
        *,
        task_id: bytes,
        job_id: bytes,
        name: str,
        event: str,  # SUBMITTED | RUNNING | FINISHED | FAILED
        task_type: str,
        extra: dict[str, Any] | None = None,
    ) -> None:
        e = {
            "task_id": task_id.hex(),
            "job_id": job_id.hex(),
            "name": name,
            "event": event,
            "type": task_type,
            "worker_id": self._worker_id,
            "node_id": self._node_id,
            "ts": time.time(),
        }
        if extra:
            e.update(extra)
        with self._lock:
            self._buffer.append(e)
            if len(self._buffer) >= _MAX_BUFFER:
                buf, self._buffer = self._buffer, []
            else:
                buf = None
        if buf:
            self._send(buf)

    def _flush_loop(self) -> None:
        while not self._stopped.wait(_FLUSH_INTERVAL_S):
            self.flush()

    def flush(self) -> None:
        with self._lock:
            buf, self._buffer = self._buffer, []
        if buf:
            self._send(buf)

    def _send(self, events: list[dict]) -> None:
        try:
            self._gcs.call("add_task_events", {"events": events})
        except Exception:  # noqa: BLE001 — observability must never kill work
            pass

    def stop(self) -> None:
        self._stopped.set()
        self.flush()
