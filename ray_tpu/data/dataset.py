"""Dataset: lazy, distributed, Arrow-blocked data pipelines.

Equivalent of the reference Dataset (reference: python/ray/data/dataset.py:178
— map_batches :397, iter_batches :3499, streaming_split :1149) built on the
ray_tpu task core. The plan is a list of logical ops; consecutive one-to-one
ops fuse into single tasks per block; all-to-all ops (repartition /
random_shuffle / sort / groupby) run as two-stage num_returns=N exchanges
(reference: _internal/push_based_shuffle.py).
"""
from __future__ import annotations

import functools
import math
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu._private import task_spec as ts
from ray_tpu.data import executor as ex
from ray_tpu.data.block import (
    ITEM_COL,
    BlockAccessor,
    batch_to_table,
    format_batch,
)
from ray_tpu.data.compute import ActorPoolStrategy, TaskPoolStrategy
from ray_tpu.data.context import DataContext

# ---------------------------------------------------------------------------
# logical ops
# ---------------------------------------------------------------------------


class _Op:
    pass


class _Read(_Op):
    def __init__(self, sources: List[Any], read_fn: Callable[[Any], pa.Table]):
        self.sources = sources
        self.read_fn = read_fn


class _FromBundles(_Op):
    def __init__(self, bundles: List[ex.RefBundle]):
        self.bundles = bundles


class _MapBlock(_Op):
    """Any one-to-one block transform (map/filter/flat_map/map_batches/
    project); fusable. With `compute` set (an ActorPoolStrategy), `fn` is a
    FACTORY returning the block transform — instantiated once per pool actor
    — and the op forms its own (non-fused) stage."""

    def __init__(self, fn: Callable[[pa.Table], pa.Table], name: str,
                 compute=None):
        self.fn = fn
        self.name = name
        self.compute = compute


class _Limit(_Op):
    def __init__(self, n: int):
        self.n = n


class _AllToAll(_Op):
    """Two-stage exchange. map_fn(table, n, idx) -> n tables;
    reduce_fn(list) -> table. n_out resolved at execution (callable takes
    current bundle list)."""

    def __init__(self, map_fn, reduce_fn, n_out, name: str,
                 needs_bundles: bool = False, prepare=None,
                 keep_empty: bool = False, prepare_streaming=None):
        self.map_fn = map_fn
        self.reduce_fn = reduce_fn
        self.n_out = n_out
        self.name = name
        # prepare(bundles) -> (map_fn, reduce_fn, n_out): built once metas
        # of the input bundles are known (sort boundaries, repartition ranges)
        self.prepare = prepare
        self.keep_empty = keep_empty  # exact-n ops keep empty output blocks
        # prepare_streaming() -> (map_fn, reduce_fn, n_out): available when
        # the op needs NOTHING from the materialized input set — the
        # executor then pipelines shuffle-maps against the live upstream
        # instead of inserting a barrier (executor.run_all_to_all_pipelined)
        self.prepare_streaming = prepare_streaming


class _Union(_Op):
    def __init__(self, others: List["Dataset"]):
        self.others = others


class _Zip(_Op):
    def __init__(self, other: "Dataset"):
        self.other = other


# ---------------------------------------------------------------------------


def _chain(fns: List[Callable]) -> Callable:
    if len(fns) == 1:
        return fns[0]

    def chained(x):
        for f in fns:
            x = f(x)
        return x

    return chained


class Dataset:
    """Lazy dataset. All transforms return a new Dataset sharing upstream
    plan; execution happens on consumption (iter/take/count/write/...)."""

    def __init__(self, plan: List[_Op], ctx: Optional[DataContext] = None):
        self._plan = plan
        self._ctx = ctx or DataContext.get_current()
        self._cached: Optional[List[ex.RefBundle]] = None
        self._schema: Optional[pa.Schema] = None

    # -- plan building ------------------------------------------------------

    def _with(self, op: _Op) -> "Dataset":
        return Dataset(self._plan + [op], self._ctx)

    def _map_op(self, fn, name) -> "Dataset":
        return self._with(_MapBlock(fn, name))

    # -- transforms (one-to-one, fused) ------------------------------------

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        def do(table: pa.Table) -> pa.Table:
            rows = [fn(r) for r in BlockAccessor(table).iter_rows()]
            return pa.Table.from_pylist(rows) if rows else table.slice(0, 0)

        return self._map_op(do, "map")

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        def do(table: pa.Table) -> pa.Table:
            mask = [bool(fn(r)) for r in BlockAccessor(table).iter_rows()]
            return table.filter(pa.array(mask, type=pa.bool_()))

        return self._map_op(do, "filter")

    def flat_map(self, fn: Callable[[dict], List[dict]]) -> "Dataset":
        def do(table: pa.Table) -> pa.Table:
            rows: List[dict] = []
            for r in BlockAccessor(table).iter_rows():
                rows.extend(fn(r))
            return pa.Table.from_pylist(rows) if rows else table.slice(0, 0)

        return self._map_op(do, "flat_map")

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        batch_format: Optional[str] = None,
        fn_kwargs: Optional[dict] = None,
        fn_constructor_args: Optional[tuple] = None,
        fn_constructor_kwargs: Optional[dict] = None,
        compute=None,
        concurrency=None,
        **_ignored,
    ) -> "Dataset":
        """Apply fn to batches (reference: dataset.py:397). fn receives the
        batch in `batch_format` (numpy dict default / pandas / pyarrow) and
        returns same-ish; batch_size splits within a block.

        A CLASS `fn` is stateful: it runs on an autoscaling actor pool
        (default ActorPoolStrategy(1, 1); pass `compute=` or `concurrency=`
        to size it), constructed once per actor with fn_constructor_args —
        the reference's ActorPoolMapOperator path (compute.py:71)."""
        fmt = batch_format or self._ctx.default_batch_format
        kwargs = fn_kwargs or {}
        is_class = isinstance(fn, type)

        if concurrency is not None and compute is None:
            if isinstance(concurrency, (tuple, list)):
                compute = ActorPoolStrategy(int(concurrency[0]),
                                            int(concurrency[1]))
            else:
                compute = ActorPoolStrategy(int(concurrency), int(concurrency))
        if is_class and compute is None:
            compute = ActorPoolStrategy()
        if compute is not None and not isinstance(compute, ActorPoolStrategy):
            if isinstance(compute, TaskPoolStrategy):
                compute = None
            else:
                raise TypeError(f"unsupported compute strategy: {compute!r}")
        if is_class and compute is None:
            raise ValueError("a callable class requires an ActorPoolStrategy")
        if compute is not None and not is_class and fn_constructor_args:
            raise ValueError("fn_constructor_args requires a class fn")

        def apply_batches(callable_fn, table: pa.Table) -> pa.Table:
            n = table.num_rows
            if n == 0:
                return table
            size = batch_size or n
            outs = []
            for start in range(0, n, size):
                piece = table.slice(start, min(size, n - start))
                out = callable_fn(format_batch(piece, fmt), **kwargs)
                outs.append(batch_to_table(out))
            return BlockAccessor.concat(outs)

        if compute is None:
            return self._map_op(lambda t: apply_batches(fn, t), "map_batches")

        ctor_args = fn_constructor_args or ()
        ctor_kwargs = fn_constructor_kwargs or {}

        def make_fn():
            inst = fn(*ctor_args, **ctor_kwargs) if is_class else fn
            return lambda t: apply_batches(inst, t)

        return self._with(_MapBlock(make_fn, "map_batches", compute=compute))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def do(table: pa.Table) -> pa.Table:
            batch = BlockAccessor(table).to_numpy()
            col = np.asarray(fn(batch))
            return table.append_column(name, pa.array(col))

        return self._map_op(do, "add_column")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self._map_op(lambda t: t.drop_columns(cols), "drop_columns")

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self._map_op(lambda t: t.select(cols), "select_columns")

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def do(table: pa.Table) -> pa.Table:
            return table.rename_columns(
                [mapping.get(c, c) for c in table.column_names]
            )

        return self._map_op(do, "rename_columns")

    def limit(self, n: int) -> "Dataset":
        return self._with(_Limit(n))

    # -- transforms (all-to-all) -------------------------------------------

    def repartition(self, num_blocks: int) -> "Dataset":
        """Exact re-split into num_blocks, preserving global row order
        (reference: dataset.py repartition shuffle=False path)."""

        def prepare(bundles):
            rows = [m.num_rows for _, m in bundles]
            offsets = np.concatenate([[0], np.cumsum(rows)])
            total = int(offsets[-1])
            # target global row ranges per output block
            bounds = [round(total * j / num_blocks) for j in range(num_blocks + 1)]

            def map_fn(table, n, idx):
                lo = int(offsets[idx])
                out = []
                for j in range(n):
                    s = max(bounds[j] - lo, 0)
                    e = min(bounds[j + 1] - lo, table.num_rows)
                    out.append(table.slice(s, max(e - s, 0)))
                return out

            def reduce_fn(parts):
                return BlockAccessor.concat(parts)

            return map_fn, reduce_fn, num_blocks

        return self._with(_AllToAll(None, None, None, "repartition",
                                    prepare=prepare, keep_empty=True))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        """Global row shuffle as a 2-stage exchange (reference:
        dataset.py random_shuffle → push_based_shuffle). With an explicit
        `num_blocks` the exchange PIPELINES against upstream (shuffle-map
        tasks start while earlier stages still stream); otherwise the
        output block count matches the input, which requires a barrier to
        count the inputs first."""
        def build(n_out):
            # seed drawn at EXECUTION (build runs once per plan execution),
            # so re-iterating an unseeded shuffle re-randomizes
            base = seed if seed is not None else np.random.randint(0, 2**31)

            def map_fn(table, n, idx):
                rng = np.random.default_rng(base * 100003 + idx)
                assign = rng.integers(0, n, table.num_rows)
                return [table.filter(pa.array(assign == j)) for j in range(n)]

            def reduce_fn(parts):
                t = BlockAccessor.concat(parts)
                if t.num_rows == 0:
                    return t
                rng = np.random.default_rng(base + 17)
                return t.take(pa.array(rng.permutation(t.num_rows)))

            return map_fn, reduce_fn, n_out

        if num_blocks is not None:
            return self._with(_AllToAll(
                None, None, None, "random_shuffle",
                prepare_streaming=lambda: build(num_blocks)))
        return self._with(_AllToAll(
            None, None, None, "random_shuffle",
            prepare=lambda bundles: build(max(1, len(bundles)))))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Sample-partitioned distributed sort (reference: dataset.py sort →
        _internal/planner/exchange/sort_task_spec.py boundary sampling)."""

        def prepare(bundles):
            n_out = max(1, len(bundles))
            # boundary sampling: fetch a small sample of the key column from
            # each block, pick n_out-1 quantile boundaries
            samples = []
            sample_refs = [
                ex._exec_block.options(num_returns=2).remote(
                    ts.dumps_function(
                        lambda t, k=key: BlockAccessor(t).sample(20, seed=0)
                        .select([k])
                    ),
                    ref,
                )
                for ref, _ in bundles
            ]
            for block_ref, _meta in sample_refs:
                t = ray_tpu.get(block_ref, timeout=600)
                samples.append(t.column(key).to_numpy(zero_copy_only=False))
            allv = np.sort(np.concatenate(samples))
            qs = [allv[int(len(allv) * j / n_out)] for j in range(1, n_out)]

            def map_fn(table, n, idx):
                col = table.column(key).to_numpy(zero_copy_only=False)
                part = np.searchsorted(np.asarray(qs), col, side="right")
                if descending:
                    part = (n - 1) - part
                return [table.filter(pa.array(part == j)) for j in range(n)]

            def reduce_fn(parts):
                t = BlockAccessor.concat(parts)
                if t.num_rows == 0:
                    return t
                return BlockAccessor(t).sort(key, descending)

            return map_fn, reduce_fn, n_out

        return self._with(_AllToAll(None, None, None, "sort", prepare=prepare))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(_Union(list(others)))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(_Zip(other))

    def random_sample(self, fraction: float, *, seed=None) -> "Dataset":
        def do(table: pa.Table) -> pa.Table:
            rng = np.random.default_rng(seed)
            mask = rng.random(table.num_rows) < fraction
            return table.filter(pa.array(mask))

        return self._map_op(do, "random_sample")

    # -- execution ----------------------------------------------------------

    def _stream(self) -> Iterator[ex.RefBundle]:
        """Execute the plan, yielding output bundles as they materialize."""
        if self._cached is not None:
            yield from self._cached
            return

        ctx = self._ctx
        stream: Optional[Iterator[ex.RefBundle]] = None
        sources: Optional[List[Any]] = None
        read_fn: Optional[Callable] = None
        fns: List[Callable] = []
        limit: Optional[int] = None

        def flush() -> Iterator[ex.RefBundle]:
            nonlocal stream, sources, read_fn, fns, limit
            if sources is not None:
                chain = _chain([read_fn] + fns) if fns else read_fn
                out = ex.run_oneone_stage(iter(sources), ts.dumps_function(chain),
                                          ctx, limit_rows=limit)
            elif fns:
                chain = _chain(fns)
                upstream = stream

                def srcs():
                    for ref, _m in upstream:
                        yield ref

                out = ex.run_oneone_stage(srcs(), ts.dumps_function(chain),
                                          ctx, limit_rows=limit)
            else:
                out = stream if stream is not None else iter(())
            if limit is not None:
                out = _truncate(out, limit)
            sources, read_fn, fns, limit = None, None, [], None
            return out

        def barrier() -> List[ex.RefBundle]:
            return list(flush())

        for op in self._plan:
            if isinstance(op, _Read):
                sources, read_fn = list(op.sources), op.read_fn
            elif isinstance(op, _FromBundles):
                stream = iter(op.bundles)
            elif isinstance(op, _MapBlock):
                if op.compute is not None:
                    # actor stage: own (non-fused) stage over an actor pool
                    upstream = flush()

                    def srcs(u=upstream):
                        for ref, _m in u:
                            yield ref

                    stream = ex.run_actor_stage(
                        srcs(), ts.dumps_function(op.fn), op.compute, ctx,
                        upstream_live=True)
                    continue
                if limit is not None:
                    # a map after a limit must see only the limited rows —
                    # flush so the truncation happens before this fn
                    stream = flush()
                fns.append(op.fn)
            elif isinstance(op, _Limit):
                limit = op.n if limit is None else min(limit, op.n)
            elif isinstance(op, _AllToAll):
                if op.prepare_streaming is not None:
                    # no barrier: shuffle-maps launch while upstream streams
                    map_fn, reduce_fn, n_out = op.prepare_streaming()
                    stream = ex.run_all_to_all_pipelined(
                        flush(), ts.dumps_function(map_fn),
                        ts.dumps_function(reduce_fn), n_out, ctx,
                        keep_empty=op.keep_empty)
                    continue
                bundles = barrier()
                map_fn, reduce_fn, n_out = op.prepare(bundles)
                stream = iter(ex.run_all_to_all(
                    bundles, ts.dumps_function(map_fn),
                    ts.dumps_function(reduce_fn), n_out, ctx,
                    keep_empty=op.keep_empty))
            elif isinstance(op, _Union):
                bundles = barrier()
                tail = [iter(o._stream()) for o in op.others]

                def chained(b=bundles, t=tail):
                    yield from b
                    for it in t:
                        yield from it

                stream = chained()
            elif isinstance(op, _Zip):
                left = barrier()
                right = list(op.other._stream())
                stream = iter(_zip_bundles(left, right, ctx))
            else:
                raise AssertionError(op)

        yield from flush()

    def materialize(self) -> "Dataset":
        """Execute fully and pin blocks (reference: dataset.py materialize)."""
        if self._cached is None:
            self._cached = list(self._stream())
        return self

    # -- consumption --------------------------------------------------------

    def count(self) -> int:
        self.materialize()
        return sum(m.num_rows for _, m in self._cached)

    def num_blocks(self) -> int:
        self.materialize()
        return len(self._cached)

    def size_bytes(self) -> int:
        self.materialize()
        return sum(m.size_bytes for _, m in self._cached)

    def schema(self) -> Optional[pa.Schema]:
        if self._schema is None:
            for ref, _m in self._stream():
                t = ray_tpu.get(ref, timeout=600)
                self._schema = t.schema
                break
        return self._schema

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def take(self, n: int = 20) -> List[dict]:
        out: List[dict] = []
        for ref, _m in self._stream():
            t = ray_tpu.get(ref, timeout=600)
            for row in BlockAccessor(t).iter_rows():
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[dict]:
        out: List[dict] = []
        for ref, _m in self._stream():
            out.extend(BlockAccessor(ray_tpu.get(ref, timeout=600)).to_pylist())
        return out

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[dict]:
        for ref, _m in self._stream():
            yield from BlockAccessor(ray_tpu.get(ref, timeout=600)).iter_rows()

    def to_pandas(self):
        tables = [ray_tpu.get(r, timeout=600) for r, _ in self._stream()]
        return BlockAccessor.concat(tables).to_pandas() if tables else None

    def to_arrow(self) -> pa.Table:
        tables = [ray_tpu.get(r, timeout=600) for r, _ in self._stream()]
        return BlockAccessor.concat(tables)

    def to_numpy(self) -> Dict[str, np.ndarray]:
        return BlockAccessor(self.to_arrow()).to_numpy()

    def to_arrow_refs(self) -> List["ray_tpu.ObjectRef"]:
        self.materialize()
        return [r for r, _ in self._cached]

    # -- iteration (the Train ingestion path) -------------------------------

    def iterator(self) -> "DataIterator":
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(self)

    def iter_batches(self, **kw) -> Iterator:
        return self.iterator().iter_batches(**kw)

    def iter_torch_batches(self, **kw) -> Iterator:
        return self.iterator().iter_torch_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator:
        return self.iterator().iter_jax_batches(**kw)

    # -- splits -------------------------------------------------------------

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """Split into n datasets at block granularity; equal=True rebalances
        to exactly-equal row counts via the repartition exchange (reference:
        dataset.py split/split_proportionately)."""
        src = self
        if equal:
            total = self.count()
            per = total // n
            src = self.limit(per * n).repartition(n)
        src.materialize()
        bundles = src._cached
        if equal:
            parts = [[b] for b in bundles]
        else:
            parts = [bundles[i::n] for i in range(n)]
        return [Dataset([_FromBundles(p)], self._ctx) for p in parts]

    def streaming_split(self, n: int, *, equal: bool = True,
                        locality_hints=None) -> List["DataIterator"]:
        """Per-consumer iterators for train workers (reference:
        dataset.py:1149). Shards are fixed up front; each DataIterator is
        picklable (holds block refs) so it ships to worker actors."""
        return [d.iterator() for d in self.split(n, equal=equal)]

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed=None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        # materialize ONCE and split the pinned blocks — limit/_drop_first on
        # the raw plan would each re-execute it (a fresh unseeded shuffle per
        # branch would leak rows between the splits)
        ds.materialize()
        base = Dataset([_FromBundles(list(ds._cached))], self._ctx)
        total = ds.count()
        n_test = int(total * test_size) if test_size < 1 else int(test_size)
        return base._drop_first(n_test), base.limit(n_test)

    def _drop_first(self, n: int) -> "Dataset":
        # keep per-input-block outputs: n_out = len(bundles), identity routing
        def prepare2(bundles):
            rows = [m.num_rows for _, m in bundles]
            offsets = np.concatenate([[0], np.cumsum(rows)])
            n_out = max(1, len(bundles))

            def map_fn(table, nn, idx):
                lo = int(offsets[idx])
                s = min(max(n - lo, 0), table.num_rows)
                out = [table.slice(0, 0)] * nn
                out[idx % nn] = table.slice(s)
                return out

            return map_fn, BlockAccessor.concat, n_out

        return self._with(_AllToAll(None, None, None, "drop_first",
                                    prepare=prepare2))

    # -- writes -------------------------------------------------------------

    def _write(self, path: str, writer: Callable[[pa.Table, str], None],
               ext: str) -> List[str]:
        import os

        os.makedirs(path, exist_ok=True)
        self.materialize()
        paths = []
        for i, (ref, _m) in enumerate(self._cached):
            t = ray_tpu.get(ref, timeout=600)
            p = os.path.join(path, f"part-{i:05d}.{ext}")
            writer(t, p)
            paths.append(p)
        return paths

    def write_parquet(self, path: str) -> List[str]:
        import pyarrow.parquet as pq

        return self._write(path, lambda t, p: pq.write_table(t, p), "parquet")

    def write_csv(self, path: str) -> List[str]:
        import pyarrow.csv as pcsv

        return self._write(path, lambda t, p: pcsv.write_csv(t, p), "csv")

    def write_json(self, path: str) -> List[str]:
        def w(t, p):
            import json

            with open(p, "w") as f:
                for row in t.to_pylist():
                    f.write(json.dumps(row) + "\n")

        return self._write(path, w, "json")

    def write_tfrecords(self, path: str) -> List[str]:
        """Rows -> tf.train.Example TFRecord files (reference:
        dataset.py write_tfrecords), via the in-tree tf-free codec."""
        def w(t, p):
            from ray_tpu.data.tfrecord import encode_example, write_records

            write_records(p, (encode_example(row) for row in t.to_pylist()))

        return self._write(path, w, "tfrecords")

    def write_webdataset(self, path: str) -> List[str]:
        """Rows -> WebDataset tar shards, one per block (reference:
        dataset.py write_webdataset). Column names become member suffixes;
        the sample key is the row's __key__ column or the row index.
        bytes pass through; str/int/float are utf-8; dict/list go as
        .json members (suffix forced if the column isn't named json)."""
        import io
        import json as _json
        import os
        import tarfile

        def w(t, p):
            # fallback keys are shard-qualified ("part-00001-000042"): the
            # per-block row index alone would collide across shards, and
            # __key__ is WebDataset's sample identity under concatenation
            shard = os.path.splitext(os.path.basename(p))[0]
            with tarfile.open(p, "w") as tf:
                for i, row in enumerate(t.to_pylist()):
                    key = str(row.pop("__key__", f"{shard}-{i:06d}"))
                    for col, val in row.items():
                        if val is None:
                            continue
                        if isinstance(val, bytes):
                            data = val
                        elif isinstance(val, (dict, list)):
                            data = _json.dumps(val).encode()
                            if col != "json" and not col.endswith(".json"):
                                col = col + ".json"
                        else:
                            data = str(val).encode()
                        info = tarfile.TarInfo(f"{key}.{col}")
                        info.size = len(data)
                        tf.addfile(info, io.BytesIO(data))

        return self._write(path, w, "tar")

    def write_mongo(self, uri: str, database: str, collection: str, *,
                    client_factory=None) -> int:
        """insert_many every block's rows (reference: dataset.py
        write_mongo / MongoDatasink). ``client_factory`` as in read_mongo.
        Returns the document count written."""
        from ray_tpu.data.datasource import _mongo_client

        self.materialize()
        client = _mongo_client(uri, client_factory, "write_mongo")
        total = 0
        try:
            coll = client[database][collection]
            for ref, _meta in self._cached:
                rows = ray_tpu.get(ref, timeout=600).to_pylist()
                if rows:
                    coll.insert_many(rows)
                    total += len(rows)
        finally:
            client.close()
        return total

    def write_sql(self, sql: str, connection_factory) -> int:
        """INSERT every row through a DBAPI-2 statement with positional
        placeholders, one executemany per block (reference: dataset.py
        write_sql / SQLDatasink). Returns the row count written."""
        self.materialize()
        total = 0
        conn = connection_factory()
        try:
            cur = conn.cursor()
            for ref, meta in self._cached:
                t = ray_tpu.get(ref, timeout=600)
                rows = [tuple(r.values()) for r in t.to_pylist()]
                if rows:
                    cur.executemany(sql, rows)
                    total += len(rows)
            conn.commit()
        finally:
            conn.close()
        return total

    # -- misc ---------------------------------------------------------------

    def stats(self) -> str:
        self.materialize()
        return (f"Dataset(blocks={len(self._cached)}, "
                f"rows={sum(m.num_rows for _, m in self._cached)}, "
                f"bytes={sum(m.size_bytes for _, m in self._cached)})")

    def __repr__(self) -> str:
        names = [getattr(op, "name", type(op).__name__.strip("_")) for op in self._plan]
        return f"Dataset({' -> '.join(names)})"


def _truncate(stream: Iterator[ex.RefBundle], n: int) -> Iterator[ex.RefBundle]:
    """Cap a bundle stream at n rows, slicing the boundary block."""
    seen = 0
    for ref, meta in stream:
        if seen + meta.num_rows <= n:
            seen += meta.num_rows
            yield ref, meta
        else:
            keep = n - seen
            if keep > 0:
                t = ray_tpu.get(ref, timeout=600).slice(0, keep)
                yield ex.put_block(t)
            seen = n
        if seen >= n:
            return


def _zip_bundles(left: List[ex.RefBundle], right: List[ex.RefBundle],
                 ctx) -> List[ex.RefBundle]:
    """Row-align right blocks to left block boundaries, then column-concat
    blockwise (reference: dataset.py zip)."""
    lrows = [m.num_rows for _, m in left]
    # realign right side to left's row ranges
    rtabs = [ray_tpu.get(r, timeout=600) for r, _ in right]
    rall = BlockAccessor.concat(rtabs) if rtabs else pa.table({})
    total_l = sum(lrows)
    if rall.num_rows != total_l:
        raise ValueError(
            f"zip requires equal row counts: {total_l} vs {rall.num_rows}")
    out: List[ex.RefBundle] = []
    off = 0
    for (lref, lmeta) in left:
        lt = ray_tpu.get(lref, timeout=600)
        rt = rall.slice(off, lmeta.num_rows)
        off += lmeta.num_rows
        merged = lt
        for name in rt.column_names:
            col = rt.column(name)
            if name in merged.column_names:
                name = name + "_1"
            merged = merged.append_column(name, col)
        out.append(ex.put_block(merged))
    return out


# ---------------------------------------------------------------------------
# groupby
# ---------------------------------------------------------------------------


class GroupedData:
    """Hash-partitioned groupby (reference: python/ray/data/grouped_data.py):
    aggregations run as a two-stage exchange — per-block partial aggregate,
    hash-route by key, combine."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, col_fns: Dict[str, tuple]) -> Dataset:
        """col_fns: out_col -> (in_col, partial, combine) where partial
        aggregates within a block and combine merges partials.

        Hash partitioning depends on nothing from the materialized input
        set, so the exchange PIPELINES: partial-aggregate maps launch as
        upstream blocks arrive (executor.run_all_to_all_pipelined) with a
        fixed reducer fan-out."""
        key = self._key
        n_out_fixed = 8  # hash-partition fan-out; empties are filtered

        def build(n_out):

            def map_fn(table, n, idx):
                # partial aggregate per key within this block, then route by
                # hash(key) so each reducer owns disjoint keys
                import pandas as pd

                df = BlockAccessor(table).to_pandas()
                if df.empty:
                    empty = pa.table({})
                    return [empty] * n
                g = df.groupby(key, sort=False)
                partial = {key: [k for k, _ in g]}
                for out_col, (in_col, pfn, _cfn) in col_fns.items():
                    partial[out_col] = [pfn(sub[in_col]) for _, sub in g]
                pt = pa.table(partial)
                keys = pt.column(key).to_pandas()
                h = pd.util.hash_pandas_object(keys, index=False).to_numpy()
                assign = (h % n).astype(np.int64)
                return [pt.filter(pa.array(assign == j)) for j in range(n)]

            def reduce_fn(parts):
                import pandas as pd

                parts = [p for p in parts if p.num_rows]
                if not parts:
                    return pa.table({})
                df = BlockAccessor(BlockAccessor.concat(parts)).to_pandas()
                g = df.groupby(key, sort=False)
                out = {key: [k for k, _ in g]}
                for out_col, (_in, _pfn, cfn) in col_fns.items():
                    out[out_col] = [cfn(sub[out_col]) for _, sub in g]
                t = pa.table(out)
                return BlockAccessor(t).sort(key)

            return map_fn, reduce_fn, n_out

        return self._ds._with(_AllToAll(
            None, None, None, "groupby",
            prepare_streaming=lambda: build(n_out_fixed)))

    def count(self) -> Dataset:
        return self._agg({"count()": (self._key, lambda s: len(s),
                                      lambda s: s.sum())})

    def sum(self, col: str) -> Dataset:
        return self._agg({f"sum({col})": (col, lambda s: s.sum(),
                                          lambda s: s.sum())})

    def min(self, col: str) -> Dataset:
        return self._agg({f"min({col})": (col, lambda s: s.min(),
                                          lambda s: s.min())})

    def max(self, col: str) -> Dataset:
        return self._agg({f"max({col})": (col, lambda s: s.max(),
                                          lambda s: s.max())})

    def mean(self, col: str) -> Dataset:
        """mean via sum+count partials combined at reduce."""
        key = self._key

        out = self._agg({
            f"__sum({col})": (col, lambda s: s.sum(), lambda s: s.sum()),
            f"__cnt({col})": (col, lambda s: len(s), lambda s: s.sum()),
        })

        def finish(batch: dict) -> dict:
            return {
                key: batch[key],
                f"mean({col})": batch[f"__sum({col})"] / batch[f"__cnt({col})"],
            }

        return out.map_batches(finish, batch_format="numpy")

    def map_groups(self, fn: Callable) -> Dataset:
        """Apply fn(pandas.DataFrame) -> DataFrame/dict per group."""
        key = self._key

        def prepare(bundles):
            n_out = max(1, min(len(bundles), 8))

            def map_fn(table, n, idx):
                import pandas as pd

                if table.num_rows == 0:
                    return [table.slice(0, 0)] * n
                keys = table.column(key).to_pandas()
                h = pd.util.hash_pandas_object(keys, index=False).to_numpy()
                assign = (h % n).astype(np.int64)
                return [table.filter(pa.array(assign == j)) for j in range(n)]

            def reduce_fn(parts):
                import pandas as pd

                parts = [p for p in parts if p.num_rows]
                if not parts:
                    return pa.table({})
                df = BlockAccessor(BlockAccessor.concat(parts)).to_pandas()
                outs = []
                for _k, sub in df.groupby(key, sort=True):
                    r = fn(sub)
                    if isinstance(r, dict):
                        r = pd.DataFrame(r)
                    outs.append(r)
                return pa.Table.from_pandas(pd.concat(outs),
                                            preserve_index=False)

            return map_fn, reduce_fn, n_out

        return self._ds._with(_AllToAll(None, None, None, "map_groups",
                                        prepare=prepare))
