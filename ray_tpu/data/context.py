"""Per-dataset execution context (reference: python/ray/data/context.py
DataContext — global-ish singleton of execution knobs, copied onto each
dataset at creation)."""
from __future__ import annotations

import dataclasses
import threading
from typing import ClassVar, Optional


@dataclasses.dataclass
class DataContext:
    # target size of one block produced by reads/repartitions
    target_max_block_size: int = 128 * 1024 * 1024
    # default read parallelism when the datasource doesn't imply one
    read_parallelism: int = 8
    # max concurrently in-flight block tasks in the streaming executor
    # (backpressure; reference streaming_executor resource-limits this
    # dynamically — we use a fixed window scaled to cluster CPUs at run time)
    max_in_flight_tasks: int = 0  # 0 = auto (2x cluster CPUs)
    # default batch format for map_batches when unspecified
    default_batch_format: str = "numpy"
    # seed for operations that accept none (None = nondeterministic)
    seed: int | None = None

    _current: ClassVar[Optional["DataContext"]] = None
    _lock: ClassVar[threading.Lock] = threading.Lock()

    @staticmethod
    def get_current() -> "DataContext":
        with DataContext._lock:
            if DataContext._current is None:
                DataContext._current = DataContext()
            return DataContext._current
