"""TFRecord + tf.train.Example codec, with no TensorFlow dependency.

Equivalent of the reference's TFRecordDatasource (reference:
python/ray/data/datasource/tfrecords_datasource.py — which parses
tf.train.Example records, via tf or a pure-python fallback). TFRecord is
the format TPU training corpora usually arrive in, so the reader cannot
depend on a library this image doesn't ship: both the record framing
(length / masked-crc32c / payload / masked-crc32c) and the Example
protobuf (Features -> map<string, Feature> -> bytes/float/int64 lists)
are implemented here directly from the public wire formats.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

# ---------------------------------------------------------------- crc32c

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    if not _CRC_TABLE:
        poly = 0x82F63B78  # Castagnoli, reflected
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------- protobuf

def _write_varint(out: bytearray, v: int) -> None:
    while True:
        bits = v & 0x7F
        v >>= 7
        if v:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _tag(field: int, wire: int) -> int:
    return (field << 3) | wire


def _encode_len_delimited(out: bytearray, field: int, payload: bytes) -> None:
    _write_varint(out, _tag(field, 2))
    _write_varint(out, len(payload))
    out += payload


def _encode_feature(values: list) -> bytes:
    """One tf.train.Feature: bytes_list=1 / float_list=2 / int64_list=3.
    `values` is pre-normalized to bytes/str, float, or int elements."""
    inner = bytearray()
    if values and isinstance(values[0], (bytes, str)):
        for v in values:
            _encode_len_delimited(
                inner, 1, v.encode() if isinstance(v, str) else v)
        kind = 1
    elif values and isinstance(values[0], float):
        packed = struct.pack(f"<{len(values)}f", *values)
        _encode_len_delimited(inner, 1, packed)
        kind = 2
    else:
        packed = bytearray()
        for v in values:
            _write_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)
        _encode_len_delimited(inner, 1, bytes(packed))
        kind = 3
    feature = bytearray()
    _encode_len_delimited(feature, kind, bytes(inner))
    return bytes(feature)


def encode_example(row: Dict[str, Any]) -> bytes:
    """dict -> serialized tf.train.Example. Scalars become 1-element
    lists (the Example convention); numpy arrays flatten."""
    import numpy as np

    features = bytearray()
    for key in sorted(row):
        value = row[key]
        if isinstance(value, np.ndarray):
            values = list(value.reshape(-1))
        elif isinstance(value, (list, tuple)):
            values = list(value)
        else:
            values = [value]
        if values and isinstance(values[0], (np.floating, float)):
            values = [float(v) for v in values]
        elif values and isinstance(values[0], (np.integer, int)) and not isinstance(values[0], bool):
            values = [int(v) for v in values]
        entry = bytearray()
        _encode_len_delimited(entry, 1, key.encode())
        _encode_len_delimited(entry, 2, _encode_feature(values))
        _encode_len_delimited(features, 1, bytes(entry))
    example = bytearray()
    _encode_len_delimited(example, 1, bytes(features))
    return bytes(example)


def _decode_feature(buf: bytes) -> list:
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        assert wire == 2, f"unexpected wire type {wire} in Feature"
        ln, pos = _read_varint(buf, pos)
        payload = buf[pos:pos + ln]
        pos += ln
        if field == 1:    # BytesList
            out, p = [], 0
            while p < len(payload):
                t, p = _read_varint(payload, p)
                assert t >> 3 == 1
                n, p = _read_varint(payload, p)
                out.append(payload[p:p + n])
                p += n
            return out
        if field == 2:    # FloatList (packed, or repeated unpacked)
            out, p = [], 0
            while p < len(payload):
                t, p = _read_varint(payload, p)
                if t & 7 == 2:
                    n, p = _read_varint(payload, p)
                    out += list(struct.unpack(f"<{n // 4}f",
                                              payload[p:p + n]))
                    p += n
                else:  # wire 5: single fixed32
                    out.append(struct.unpack("<f", payload[p:p + 4])[0])
                    p += 4
            return out
        if field == 3:    # Int64List
            out, p = [], 0
            while p < len(payload):
                t, p = _read_varint(payload, p)
                if t & 7 == 2:
                    n, p = _read_varint(payload, p)
                    end = p + n
                    while p < end:
                        v, p = _read_varint(payload, p)
                        out.append(v - (1 << 64) if v >= (1 << 63) else v)
                else:  # wire 0: unpacked varint
                    v, p = _read_varint(payload, p)
                    out.append(v - (1 << 64) if v >= (1 << 63) else v)
            return out
    return []


def decode_example(buf: bytes) -> Dict[str, list]:
    """serialized tf.train.Example -> {name: list of bytes/float/int}."""
    out: Dict[str, list] = {}
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        if tag >> 3 != 1 or tag & 7 != 2:
            raise ValueError("not a tf.train.Example (bad Features field)")
        ln, pos = _read_varint(buf, pos)
        features = buf[pos:pos + ln]
        pos += ln
        fpos = 0
        while fpos < len(features):
            ftag, fpos = _read_varint(features, fpos)
            assert ftag >> 3 == 1 and ftag & 7 == 2
            fln, fpos = _read_varint(features, fpos)
            entry = features[fpos:fpos + fln]
            fpos += fln
            # map entry: key=1 (string), value=2 (Feature)
            key = value = None
            epos = 0
            while epos < len(entry):
                etag, epos = _read_varint(entry, epos)
                eln, epos = _read_varint(entry, epos)
                payload = entry[epos:epos + eln]
                epos += eln
                if etag >> 3 == 1:
                    key = payload.decode()
                else:
                    value = payload
            if key is not None:
                out[key] = _decode_feature(value or b"")
    return out


# --------------------------------------------------------- record framing

def write_records(path: str, payloads: Iterator[bytes]) -> int:
    """Write framed TFRecords; returns the record count."""
    n = 0
    with open(path, "wb") as f:
        for payload in payloads:
            header = struct.pack("<Q", len(payload))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(payload)
            f.write(struct.pack("<I", _masked_crc(payload)))
            n += 1
    return n


def read_records(path: str, verify_crc: bool = True) -> Iterator[bytes]:
    def must_read(f, n: int, what: str) -> bytes:
        buf = f.read(n)
        if len(buf) < n:
            raise ValueError(f"truncated TFRecord ({what}) in {path}")
        return buf

    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", must_read(f, 4, "length crc"))
            payload = must_read(f, length, "payload")
            (pcrc,) = struct.unpack("<I", must_read(f, 4, "data crc"))
            if verify_crc:
                if _masked_crc(header) != hcrc:
                    raise ValueError(f"TFRecord length-crc mismatch in {path}")
                if _masked_crc(payload) != pcrc:
                    raise ValueError(f"TFRecord data-crc mismatch in {path}")
            yield payload
