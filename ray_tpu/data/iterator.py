"""DataIterator: batched, prefetched consumption of a Dataset.

Equivalent of the reference DataIterator (reference: python/ray/data/
iterator.py:103 iter_batches, :288 iter_torch_batches). TPU-first additions:
`iter_jax_batches` double-buffers `jax.device_put` so the next batch's
host→HBM transfer overlaps the current step (the reference's
iter_torch_batches→GPU path, re-imagined for XLA transfer semantics).
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import BlockAccessor, format_batch


class DataIterator:
    """Picklable batch iterator over a dataset's blocks. Created driver-side
    (materializes the shard's block refs) and shipped to train workers."""

    def __init__(self, dataset=None, bundles=None):
        if bundles is None:
            dataset.materialize()
            bundles = list(dataset._cached)
        # hold (ref, num_rows); refs are picklable so the iterator ships
        self._bundles = [(ref, meta.num_rows) for ref, meta in bundles]

    def __getstate__(self):
        return {"bundles": self._bundles}

    def __setstate__(self, state):
        self._bundles = state["bundles"]

    def count(self) -> int:
        return sum(n for _, n in self._bundles)

    # -- core batch loop ----------------------------------------------------

    def _iter_tables(self, prefetch: int) -> Iterator[pa.Table]:
        """Fetch blocks with a background prefetch thread."""
        refs = [r for r, _ in self._bundles]
        if not refs:
            return
        q: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
        stop = threading.Event()

        def offer(item) -> bool:
            # bounded put that aborts when the consumer abandoned the
            # iterator (early break from a training loop) — a plain q.put
            # would block this thread forever holding a fetched block
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def feeder():
            try:
                for r in refs:
                    if stop.is_set():
                        return
                    if not offer(("ok", ray_tpu.get(r, timeout=600))):
                        return
                offer(("done", None))
            except BaseException as e:  # surfaced on the consumer side
                offer(("err", e))

        t = threading.Thread(target=feeder, daemon=True,
                             name="ray_tpu-data-feeder")
        t.start()
        try:
            while True:
                kind, val = q.get()
                if kind == "done":
                    return
                if kind == "err":
                    raise val
                yield val
        finally:
            stop.set()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
        prefetch_batches: int = 1,
    ) -> Iterator[Any]:
        """Yield batches of exactly batch_size rows (coalescing across block
        boundaries). With local_shuffle_buffer_size, rows are drawn uniformly
        at random from a sliding buffer of at least that many rows, so rows DO
        cross batch boundaries (reference: iterator.py local shuffle buffer)."""
        carry: Optional[pa.Table] = None
        rng = (np.random.default_rng(local_shuffle_seed)
               if local_shuffle_buffer_size else None)

        def draw(table: pa.Table, k: int):
            """Randomly sample k rows out of `table`; return (batch, rest)."""
            idx = rng.permutation(table.num_rows)
            return (table.take(pa.array(idx[:k])),
                    table.take(pa.array(np.sort(idx[k:]))))

        min_hold = (local_shuffle_buffer_size or 0)
        for t in self._iter_tables(prefetch_batches):
            carry = t if carry is None else BlockAccessor.concat([carry, t])
            if batch_size is None:
                yield format_batch(carry, batch_format)
                carry = None
                continue
            while carry is not None and carry.num_rows - min_hold >= batch_size:
                if rng is not None:
                    batch, carry = draw(carry, batch_size)
                else:
                    batch, carry = (carry.slice(0, batch_size),
                                    carry.slice(batch_size))
                yield format_batch(batch, batch_format)
        if batch_size is None:
            return
        # drain the shuffle hold-back + remainder
        while carry is not None and carry.num_rows >= batch_size:
            if rng is not None:
                batch, carry = draw(carry, batch_size)
            else:
                batch, carry = (carry.slice(0, batch_size),
                                carry.slice(batch_size))
            yield format_batch(batch, batch_format)
        if carry is not None and carry.num_rows and not drop_last:
            if rng is not None:
                carry = carry.take(pa.array(rng.permutation(carry.num_rows)))
            yield format_batch(carry, batch_format)

    def iter_rows(self) -> Iterator[dict]:
        for t in self._iter_tables(1):
            yield from BlockAccessor(t).iter_rows()

    # -- framework sinks ----------------------------------------------------

    def iter_torch_batches(self, *, dtypes=None, device=None, **kw) -> Iterator:
        import torch

        for batch in self.iter_batches(batch_format="numpy", **kw):
            out = {}
            for k, v in batch.items():
                tv = torch.as_tensor(np.ascontiguousarray(v))
                if dtypes is not None:
                    tv = tv.to(dtypes[k] if isinstance(dtypes, dict) else dtypes)
                if device is not None:
                    tv = tv.to(device)
                out[k] = tv
            yield out

    def iter_jax_batches(
        self,
        *,
        sharding=None,
        dtypes=None,
        prefetch: int = 2,
        **kw,
    ) -> Iterator[Dict[str, Any]]:
        """Yield batches as device arrays. Transfers are issued `prefetch`
        batches ahead so host→HBM copy overlaps compute (XLA async
        dispatch); with `sharding` (a jax.sharding.Sharding) each batch is
        laid out across the mesh for SPMD ingestion."""
        import jax

        def put(batch):
            out = {}
            for k, v in batch.items():
                if dtypes is not None:
                    dt = dtypes[k] if isinstance(dtypes, dict) else dtypes
                    v = v.astype(dt)
                out[k] = (jax.device_put(v, sharding) if sharding is not None
                          else jax.device_put(v))
            return out

        it = self.iter_batches(batch_format="numpy", **kw)
        buf: List[dict] = []
        for batch in it:
            buf.append(put(batch))  # issues async transfer
            if len(buf) > max(0, prefetch):
                yield buf.pop(0)
        yield from buf

    def materialize(self):
        from ray_tpu.data.dataset import Dataset, _FromBundles
        from ray_tpu.data.executor import BlockMeta

        bundles = [(r, BlockMeta(n, 0)) for r, n in self._bundles]
        ds = Dataset([_FromBundles(bundles)])
        return ds
