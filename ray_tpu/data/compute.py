"""Compute strategies for map operators (reference:
python/ray/data/_internal/compute.py — TaskPoolStrategy vs ActorPoolStrategy,
and _internal/execution/operators/actor_pool_map_operator.py for the
autoscaling pool semantics).

Tasks are the default. An ``ActorPoolStrategy`` runs the transform on a pool
of long-lived actors so stateful callables (a loaded model, a tokenizer, a
jitted TPU inference fn) are constructed ONCE per actor and reused across
blocks — the operator TPU batch-inference pipelines need.
"""
from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class TaskPoolStrategy:
    """Stateless per-block tasks (the default)."""


@dataclasses.dataclass
class ActorPoolStrategy:
    """Autoscaling pool of stateful block-transform actors.

    The pool starts at ``min_size`` and grows (up to ``max_size``) whenever
    every live actor already has ``max_tasks_in_flight_per_actor`` blocks
    queued and more input is waiting; it never shrinks mid-stage (actors are
    killed when the stage drains). Mirrors the reference's
    ``ActorPoolMapOperator`` scaling rule without its rate heuristics.

    Resource safety: the executor reserves one upstream task slot when the
    pool feeds from a live stage (capping the pool below the cluster's CPU
    count), and a pool whose configured minimum wouldn't leave that slot
    free runs AFTER upstream materializes instead — a pool sized to the
    whole cluster completes either way (executor.run_actor_stage).
    """

    min_size: int = 1
    max_size: Optional[int] = None  # None = min_size (fixed pool)
    max_tasks_in_flight_per_actor: int = 2
    num_cpus: float = 1.0
    resources: Optional[dict] = None

    def __post_init__(self):
        if self.min_size < 1:
            raise ValueError("min_size must be >= 1")
        if self.max_size is None:
            self.max_size = self.min_size
        if self.max_size < self.min_size:
            raise ValueError("max_size must be >= min_size")
        if self.max_tasks_in_flight_per_actor < 1:
            raise ValueError("max_tasks_in_flight_per_actor must be >= 1")
