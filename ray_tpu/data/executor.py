"""Streaming block executor.

All heavy lifting runs as ray_tpu tasks over object-store blocks — the same
division of labor as the reference, where Ray Data is a pure-Python library
whose operators execute as tasks/actors over plasma blocks (reference:
python/ray/data/_internal/execution/streaming_executor.py:49, operators under
_internal/execution/operators/).

Design differences, TPU-first and core-native:
- consecutive one-to-one ops (read/map/filter/flat_map/map_batches/limit)
  are FUSED into a single task per block (reference fuses via
  logical/rules/operator_fusion.py); all-to-all ops (shuffle, sort,
  repartition) are stage barriers built from num_returns=N map tasks and
  N reduce tasks (reference: _internal/push_based_shuffle.py).
- the one-to-one pipeline is a generator: blocks stream out as their tasks
  finish (bounded in-flight window for backpressure), so iter_batches
  consumes while upstream tasks still run.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Iterator, List, Optional, Tuple

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu._private import task_spec as ts
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.context import DataContext


class BlockMeta:
    __slots__ = ("num_rows", "size_bytes")

    def __init__(self, num_rows: int, size_bytes: int):
        self.num_rows = num_rows
        self.size_bytes = size_bytes

    def __repr__(self):
        return f"BlockMeta(rows={self.num_rows}, bytes={self.size_bytes})"


# (block_ref, BlockMeta) — the executor's currency
RefBundle = Tuple["ray_tpu.ObjectRef", BlockMeta]

# ---------------------------------------------------------------------------
# worker-side stage runner: deserialize the fused fn chain once per blob
# ---------------------------------------------------------------------------

_STAGE_CACHE: dict = {}
_STAGE_CACHE_LOCK = threading.Lock()


def _load_stage(blob: bytes) -> Callable:
    key = hashlib.sha1(blob).digest()
    with _STAGE_CACHE_LOCK:
        fn = _STAGE_CACHE.get(key)
        if fn is None:
            fn = ts.loads_function(blob)
            if len(_STAGE_CACHE) > 256:
                _STAGE_CACHE.clear()
            _STAGE_CACHE[key] = fn
    return fn


def _meta_of(table: pa.Table) -> BlockMeta:
    return BlockMeta(table.num_rows, table.nbytes)


@ray_tpu.remote
def _exec_block(stage_blob: bytes, source: Any):
    """Run a fused one-to-one chain. `source` is an upstream block (Arrow
    table) or a read-task argument; the chain's first fn knows which."""
    fn = _load_stage(stage_blob)
    table = fn(source)
    return table, _meta_of(table)


@ray_tpu.remote
def _exec_shuffle_map(stage_blob: bytes, n: int, idx: int, source: Any):
    """Partition one block into n pieces; returned as n separate objects so
    each reducer fetches only its shard (push-based shuffle, reference:
    data/_internal/push_based_shuffle.py)."""
    fn = _load_stage(stage_blob)
    parts = fn(source, n, idx)
    assert len(parts) == n
    if n == 1:
        return parts[0]
    return tuple(parts)


@ray_tpu.remote
def _exec_reduce(stage_blob: bytes, *parts):
    fn = _load_stage(stage_blob)
    table = fn(list(parts))
    return table, _meta_of(table)


# ---------------------------------------------------------------------------
# driver-side streaming pipeline
# ---------------------------------------------------------------------------


def _window_size(ctx: DataContext) -> int:
    if ctx.max_in_flight_tasks:
        return ctx.max_in_flight_tasks
    try:
        cpus = ray_tpu.cluster_resources().get("CPU", 4)
    except Exception:
        cpus = 4
    return max(2, int(cpus) * 2)


def run_oneone_stage(
    sources: Iterator[Any],
    stage_blob: bytes,
    ctx: DataContext,
    limit_rows: Optional[int] = None,
) -> Iterator[RefBundle]:
    """Stream `sources` (read args or block refs) through one fused task per
    source. Yields bundles as tasks complete (in completion order); keeps at
    most `window` tasks in flight; stops submitting once `limit_rows` rows
    have already been yielded."""
    window = _window_size(ctx)
    inflight: dict = {}  # meta_ref -> (seq, block_ref)
    done: dict = {}  # seq -> RefBundle, completed but not yet yielded
    sources = iter(sources)
    exhausted = False
    submitted = 0
    next_seq = 0  # output preserves submission (plan) order
    yielded_rows = 0

    def submit_one() -> bool:
        nonlocal exhausted, submitted
        try:
            src = next(sources)
        except StopIteration:
            exhausted = True
            return False
        block_ref, meta_ref = _exec_block.options(num_returns=2).remote(
            stage_blob, src
        )
        inflight[meta_ref] = (submitted, block_ref)
        submitted += 1
        return True

    while True:
        while (not exhausted and len(inflight) < window
               and (limit_rows is None or yielded_rows < limit_rows)):
            if not submit_one():
                break
        if not inflight and not done:
            return
        if inflight:
            ready, _ = ray_tpu.wait(list(inflight.keys()), num_returns=1,
                                    timeout=600)
            for meta_ref in ready:
                seq, block_ref = inflight.pop(meta_ref)
                meta: BlockMeta = ray_tpu.get(meta_ref, timeout=600)
                done[seq] = (block_ref, meta)
        while next_seq in done:
            block_ref, meta = done.pop(next_seq)
            next_seq += 1
            if meta.num_rows == 0:
                continue
            yielded_rows += meta.num_rows
            yield block_ref, meta


class _PoolWorker:
    """Stateful block-transform actor: the factory blob is deserialized and
    CALLED once at construction (instantiating the user's callable class
    there), then every block reuses the instance (reference:
    actor_pool_map_operator.py — _MapWorker)."""

    def __init__(self, factory_blob: bytes):
        self._fn = ts.loads_function(factory_blob)()

    def run(self, source: Any):
        table = self._fn(source)
        # put the block from the actor so only (ref, meta) crosses back to
        # the driver — the block itself stays in the object store
        return ray_tpu.put(table), _meta_of(table)

    def ping(self):
        return True


def run_actor_stage(
    sources: Iterator[Any],
    factory_blob: bytes,
    strategy,
    ctx: DataContext,
    limit_rows: Optional[int] = None,
    upstream_live: bool = True,
) -> Iterator[RefBundle]:
    """Stream blocks through an autoscaling pool of `_PoolWorker` actors.

    Scale-up rule: if every live actor is saturated (max_tasks_in_flight
    queued) and input remains, add an actor, up to strategy.max_size.
    Output preserves submission order, same as run_oneone_stage.

    Resource-aware admission (reference: streaming executor resource
    budgets, _internal/execution/resource_manager.py): when the input is a
    LIVE task stage (`upstream_live`), the pool may never occupy every CPU
    — at least one is reserved so upstream tasks keep producing. A pool
    whose configured minimum wouldn't fit that budget falls back to
    materializing the upstream FIRST (barrier), then running at full
    width: slower than pipelining, but it completes instead of
    deadlocking pool-vs-upstream.
    """
    opts = dict(num_cpus=strategy.num_cpus)
    if strategy.resources:
        opts["resources"] = strategy.resources
    Worker = ray_tpu.remote(**opts)(_PoolWorker)

    per_actor = max(float(strategy.num_cpus), 1e-9)
    try:
        total_cpus = float(ray_tpu.cluster_resources().get("CPU", 4.0))
    except Exception:
        total_cpus = 4.0
    pool_cap = max(1, int(total_cpus // per_actor))
    if upstream_live:
        live_cap = int((total_cpus - 1.0) // per_actor)
        if live_cap < max(1, strategy.min_size):
            # pool (at its configured minimum) + one upstream task slot
            # don't fit: run upstream to completion first, then pool at
            # full width — the barrier removes the CPU contention
            sources = iter(list(sources))
        else:
            pool_cap = live_cap
    max_pool = max(1, min(strategy.max_size, pool_cap))
    min_pool = max(1, min(strategy.min_size, max_pool))

    pool = [Worker.remote(factory_blob) for _ in range(min_pool)]
    load = {id(a): 0 for a in pool}  # actor -> queued block count
    by_id = {id(a): a for a in pool}
    inflight: dict = {}  # result_ref -> (seq, actor_id)
    done: dict = {}  # seq -> RefBundle
    sources = iter(sources)
    exhausted = False
    submitted = 0
    next_seq = 0
    yielded_rows = 0
    cap = strategy.max_tasks_in_flight_per_actor

    def pick_actor():
        aid = min(load, key=lambda k: load[k])
        if load[aid] >= cap:
            if len(pool) < max_pool:
                a = Worker.remote(factory_blob)
                pool.append(a)
                load[id(a)] = 0
                by_id[id(a)] = a
                return id(a)
            return None
        return aid

    def submit_one() -> bool:
        nonlocal exhausted, submitted
        aid = pick_actor()
        if aid is None:
            return False
        try:
            src = next(sources)
        except StopIteration:
            exhausted = True
            return False
        ref = by_id[aid].run.remote(src)
        inflight[ref] = (submitted, aid)
        load[aid] += 1
        submitted += 1
        return True

    try:
        while True:
            while (not exhausted
                   and (limit_rows is None or yielded_rows < limit_rows)):
                if not submit_one():
                    break
            if not inflight and not done:
                return
            if inflight:
                ready, _ = ray_tpu.wait(list(inflight.keys()), num_returns=1,
                                        timeout=600)
                for ref in ready:
                    seq, aid = inflight.pop(ref)
                    load[aid] -= 1
                    block_ref, meta = ray_tpu.get(ref, timeout=600)
                    done[seq] = (block_ref, meta)
            while next_seq in done:
                block_ref, meta = done.pop(next_seq)
                next_seq += 1
                if meta.num_rows == 0:
                    continue
                yielded_rows += meta.num_rows
                yield block_ref, meta
    finally:
        for a in pool:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def run_all_to_all_pipelined(
    bundles: Iterator[RefBundle],
    map_blob: bytes,
    reduce_blob: bytes,
    n_out: int,
    ctx: DataContext,
    keep_empty: bool = False,
) -> Iterator[RefBundle]:
    """Pipelined exchange: shuffle-map tasks launch as upstream bundles
    ARRIVE (overlapping the map phase with whatever still runs upstream),
    and reduce outputs stream to the consumer in completion order. Usable
    whenever n_out and map_fn don't depend on the materialized input set
    (reference: streaming_executor.py — all-to-all operators participate in
    the pipelined topology instead of acting as global barriers). The
    reduce phase still requires every map output for its shard — that
    barrier is inherent to the exchange, not the executor."""
    window = _window_size(ctx)
    map_out: List[List] = []  # [map_i][part_j] -> ref
    inflight: list = []  # completion markers (part-0 refs) for backpressure
    for i, (block_ref, _meta) in enumerate(bundles):
        refs = _exec_shuffle_map.options(num_returns=n_out).remote(
            map_blob, n_out, i, block_ref
        )
        if n_out == 1:
            refs = [refs]
        map_out.append(list(refs))
        inflight.append(refs[0])
        while len(inflight) >= window:
            # bounded in-flight maps: wait for any to land before pulling
            # more input (backpressure against a fast upstream). Loop so a
            # timeout can't silently grow the window; zero progress raises
            # like the reduce phase below.
            ready, inflight = ray_tpu.wait(inflight, num_returns=1,
                                           timeout=600)
            if not ready:
                raise TimeoutError(
                    "all-to-all map phase made no progress for 600s "
                    f"({len(inflight)} shuffle maps outstanding)")
    n_in = len(map_out)
    if n_in == 0:
        return
    pending: dict = {}  # meta_ref -> (j, block_ref)
    for j in range(n_out):
        parts = [map_out[i][j] for i in range(n_in)]
        block_ref, meta_ref = _exec_reduce.options(num_returns=2).remote(
            reduce_blob, *parts
        )
        pending[meta_ref] = (j, block_ref)
    while pending:
        ready, _ = ray_tpu.wait(list(pending.keys()), num_returns=1,
                                timeout=600)
        if not ready:
            raise TimeoutError(
                "all-to-all made no progress for 600s "
                f"({len(pending)} reducers outstanding)")
        for meta_ref in ready:
            j, block_ref = pending.pop(meta_ref)
            meta = ray_tpu.get(meta_ref, timeout=600)
            if keep_empty or meta.num_rows > 0:
                yield block_ref, meta


def run_all_to_all(
    bundles: List[RefBundle],
    map_blob: bytes,
    reduce_blob: bytes,
    n_out: int,
    ctx: DataContext,
    keep_empty: bool = False,
) -> List[RefBundle]:
    """Two-stage exchange: every input block is partitioned into n_out pieces
    (num_returns=n_out), then reducer j combines piece j of every map output."""
    n_in = len(bundles)
    if n_in == 0:
        return []
    map_out: List[List] = []  # [map_i][part_j] -> ref
    for i, (block_ref, _) in enumerate(bundles):
        refs = _exec_shuffle_map.options(num_returns=n_out).remote(
            map_blob, n_out, i, block_ref
        )
        if n_out == 1:
            refs = [refs]
        map_out.append(list(refs))
    out: List[Optional[RefBundle]] = [None] * n_out
    pending: dict = {}  # meta_ref -> (j, block_ref)
    for j in range(n_out):
        parts = [map_out[i][j] for i in range(n_in)]
        block_ref, meta_ref = _exec_reduce.options(num_returns=2).remote(
            reduce_blob, *parts
        )
        pending[meta_ref] = (j, block_ref)
    # drain reducers in completion order; the 600s window is a
    # NO-PROGRESS timeout (it resets whenever any reducer finishes), so
    # long serial makespans on small clusters still complete
    while pending:
        ready, _ = ray_tpu.wait(list(pending.keys()), num_returns=1,
                                timeout=600)
        if not ready:
            raise TimeoutError(
                "all-to-all made no progress for 600s "
                f"({len(pending)} reducers outstanding)")
        for meta_ref in ready:
            j, block_ref = pending.pop(meta_ref)
            out[j] = (block_ref, ray_tpu.get(meta_ref, timeout=600))
    if keep_empty:
        # repartition(n)/split(n) promise exactly n output blocks even when
        # some are empty
        return out
    return [b for b in out if b[1].num_rows > 0]


def put_block(table: pa.Table) -> RefBundle:
    return ray_tpu.put(table), _meta_of(table)


def fetch_block(bundle: RefBundle) -> pa.Table:
    return ray_tpu.get(bundle[0], timeout=600)
