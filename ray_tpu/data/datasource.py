"""Datasources: creation + file reads (reference: python/ray/data/
read_api.py and datasource/ — parquet/csv/json/text/numpy/range/items).
Each read op is (sources, read_fn): one fused task per source."""
from __future__ import annotations

import glob as globmod
import math
import os
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import ITEM_COL, BlockAccessor, batch_to_table
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, _FromBundles, _Read
from ray_tpu.data import executor as ex


def _resolve_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(c in p for c in "*?["):
            out.extend(sorted(globmod.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths}")
    return out


def range(n: int, *, parallelism: int = -1) -> Dataset:
    """Integers [0, n) in `parallelism` blocks (reference: read_api.py
    range — column name 'id')."""
    import builtins

    ctx = DataContext.get_current()
    p = parallelism if parallelism > 0 else min(ctx.read_parallelism, max(1, n))
    bounds = [round(n * i / p) for i in builtins.range(p + 1)]
    sources = [(bounds[i], bounds[i + 1]) for i in builtins.range(p)]

    def read(span) -> pa.Table:
        lo, hi = span
        return pa.table({"id": np.arange(lo, hi, dtype=np.int64)})

    return Dataset([_Read(sources, read)])


def range_tensor(n: int, *, shape=(1,), parallelism: int = -1) -> Dataset:
    ctx = DataContext.get_current()
    p = parallelism if parallelism > 0 else min(ctx.read_parallelism, max(1, n))
    import builtins

    bounds = [round(n * i / p) for i in builtins.range(p + 1)]
    sources = [(bounds[i], bounds[i + 1]) for i in builtins.range(p)]

    def read(span) -> pa.Table:
        lo, hi = span
        base = np.arange(lo, hi, dtype=np.int64).reshape((-1,) + (1,) * len(shape))
        data = np.broadcast_to(base, (hi - lo,) + tuple(shape)).copy()
        return batch_to_table({"data": data})

    return Dataset([_Read(sources, read)])


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    ctx = DataContext.get_current()
    import builtins

    p = parallelism if parallelism > 0 else min(ctx.read_parallelism,
                                                max(1, len(items)))
    chunk = math.ceil(len(items) / p) if items else 1
    sources = [items[i:i + chunk] for i in builtins.range(0, len(items), chunk)]

    def read(chunk_items) -> pa.Table:
        if chunk_items and isinstance(chunk_items[0], dict):
            return pa.Table.from_pylist(chunk_items)
        return pa.table({ITEM_COL: pa.array(chunk_items)})

    return Dataset([_Read(sources or [[]], read)])


def from_numpy(arrs, column: str = "data") -> Dataset:
    if isinstance(arrs, np.ndarray):
        arrs = [arrs]
    sources = list(arrs)

    def read(arr) -> pa.Table:
        return batch_to_table({column: arr})

    return Dataset([_Read(sources, read)])


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    bundles = [ex.put_block(pa.Table.from_pandas(df, preserve_index=False))
               for df in dfs]
    return Dataset([_FromBundles(bundles)])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return Dataset([_FromBundles([ex.put_block(t) for t in tables])])


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 parallelism: int = -1) -> Dataset:
    files = _resolve_paths(paths)

    def read(path) -> pa.Table:
        import pyarrow.parquet as pq

        return pq.read_table(path, columns=columns)

    return Dataset([_Read(files, read)])


def read_csv(paths, *, parallelism: int = -1, **csv_kwargs) -> Dataset:
    files = _resolve_paths(paths)

    def read(path) -> pa.Table:
        import pyarrow.csv as pcsv

        return pcsv.read_csv(path, **csv_kwargs)

    return Dataset([_Read(files, read)])


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    """JSONL files (reference: read_api.py read_json)."""
    files = _resolve_paths(paths)

    def read(path) -> pa.Table:
        import json

        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return pa.Table.from_pylist(rows) if rows else pa.table({})

    return Dataset([_Read(files, read)])


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    files = _resolve_paths(paths)

    def read(path) -> pa.Table:
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        return pa.table({"text": pa.array(lines)})

    return Dataset([_Read(files, read)])


def read_numpy(paths, *, parallelism: int = -1) -> Dataset:
    files = _resolve_paths(paths)

    def read(path) -> pa.Table:
        return batch_to_table({"data": np.load(path)})

    return Dataset([_Read(files, read)])


def read_images(paths, *, size: Optional[tuple] = None,
                mode: str = "RGB", parallelism: int = -1) -> Dataset:
    """Image directory → {'image': uint8 HWC tensor, 'path': str}
    (reference: datasource/image_datasource.py)."""
    files = [p for p in _resolve_paths(paths)
             if p.lower().endswith((".png", ".jpg", ".jpeg", ".bmp", ".gif"))]

    def read(path) -> pa.Table:
        from PIL import Image

        img = Image.open(path).convert(mode)
        if size is not None:
            img = img.resize(size)
        arr = np.asarray(img)[None, ...]
        t = batch_to_table({"image": arr})
        return t.append_column("path", pa.array([path]))

    return Dataset([_Read(files, read)])


def read_tfrecords(paths, *, verify_crc: bool = True,
                   parallelism: int = -1) -> Dataset:
    """TFRecord files of tf.train.Example records — the standard TPU
    training-corpus format (reference: datasource/tfrecords_datasource.py).
    No TensorFlow dependency: framing and Example protobufs are decoded
    in-tree (ray_tpu/data/tfrecord.py). Single-element lists unwrap to
    scalars, matching the reference's read behavior; bytes stay bytes."""
    files = _resolve_paths(paths)

    def read(path) -> pa.Table:
        from ray_tpu.data.tfrecord import decode_example, read_records

        rows = []
        for payload in read_records(path, verify_crc=verify_crc):
            row = {}
            for key, values in decode_example(payload).items():
                row[key] = values[0] if len(values) == 1 else values
            rows.append(row)
        return pa.Table.from_pylist(rows) if rows else pa.table({})

    return Dataset([_Read(files, read)])


def from_huggingface(hf_dataset) -> Dataset:
    """A `datasets.Dataset` (in-memory arrow) -> Dataset (reference:
    read_api.py from_huggingface / huggingface_datasource.py). Requires
    the `datasets` package only in the sense that you already have one of
    its objects; conversion rides its public arrow surface."""
    if getattr(hf_dataset, "_indices", None) is not None:
        # select/filter/shuffle/train_test_split record their row mapping
        # in _indices while .data keeps the FULL table — materialize the
        # selection first or we'd return rows the user filtered out
        hf_dataset = hf_dataset.flatten_indices()
    table = getattr(getattr(hf_dataset, "data", None), "table", None)
    if table is None:
        # older/newer datasets versions: .data may BE the table, or fall
        # back to arrow export
        table = getattr(hf_dataset, "data", None)
        if not isinstance(table, pa.Table):
            if hasattr(hf_dataset, "to_pandas"):
                return from_pandas(hf_dataset.to_pandas())
            raise TypeError(
                f"cannot extract an arrow table from {type(hf_dataset)!r}")
    return from_arrow(table.combine_chunks())


def read_huggingface(path: str) -> Dataset:
    """A `datasets.Dataset.save_to_disk()` directory -> Dataset. The
    on-disk layout is arrow IPC stream files (data-*.arrow) + json
    manifests, so this reads WITHOUT the datasets package installed;
    when it is importable, load_from_disk handles layout variations."""
    try:
        import datasets  # noqa: F401 — prefer the native loader

        return from_huggingface(datasets.load_from_disk(path))
    except ImportError:
        pass
    files = [p for p in _resolve_paths(path) if p.endswith(".arrow")]
    if not files:
        raise FileNotFoundError(
            f"no .arrow data files under {path!r} — not a saved HF dataset?")

    def read(p) -> pa.Table:
        import pyarrow.ipc as ipc

        with open(p, "rb") as f:
            try:
                return ipc.open_stream(f).read_all()
            except pa.ArrowInvalid:
                f.seek(0)
                return ipc.open_file(f).read_all()

    return Dataset([_Read(files, read)])


def read_sql(sql: str, connection_factory) -> Dataset:
    """Rows from a DBAPI-2 query as ONE read task (reference:
    read_api.py read_sql / sql_datasource.py — which likewise executes an
    un-shardable query serially; shard by issuing multiple read_sql calls
    with WHERE-partitioned queries and `Dataset.union`). The zero-arg
    ``connection_factory`` runs inside the task, so it works with
    sqlite3, psycopg2, mysql-connector, ..."""

    def read(_src) -> pa.Table:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        return pa.Table.from_pylist(
            [dict(zip(cols, r)) for r in rows]) if rows else pa.table({})

    return Dataset([_Read([sql], read)])


def read_webdataset(paths, *, decode: bool = True,
                    suffixes: Optional[List[str]] = None,
                    parallelism: int = -1) -> Dataset:
    """WebDataset tar shards -> one row per sample (reference:
    read_api.py read_webdataset / webdataset_datasource.py). A sample is
    the group of tar members sharing the basename before the FIRST dot;
    the remainder ("json", "txt", "cls", "jpg", ...) becomes the column
    name. No `webdataset` dependency — the layout is plain tar. With
    ``decode=True`` the conventional text-ish suffixes are decoded
    (json -> object, txt -> str, cls -> int); images and everything else
    stay raw bytes for a downstream `map_batches` to decode. ``suffixes``
    keeps only the listed columns (plus __key__)."""
    import tarfile

    files = _resolve_paths(paths)

    def _decode(suffix: str, data: bytes):
        if not decode:
            return data
        if suffix == "json" or suffix.endswith(".json"):
            import json as _json

            return _json.loads(data)
        if suffix in ("txt", "text"):
            return data.decode("utf-8")
        if suffix in ("cls", "cls2", "index", "id"):
            return int(data.decode("utf-8").strip())
        return data

    def read(path) -> pa.Table:
        # Group by KEY, not by adjacency: tars written by parallel
        # producers (or re-packed) can interleave members of different
        # samples, and adjacency grouping silently yielded duplicate
        # partial rows per key. First-seen order is preserved; the same
        # (key, column) member appearing twice is ambiguous data and
        # raises instead of silently keeping one.
        samples: "OrderedDict[str, dict]" = OrderedDict()
        with tarfile.open(path) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                name = member.name.split("/")[-1]
                if name.startswith("."):
                    continue
                key, dot, suffix = name.partition(".")
                if not dot:
                    continue
                row = samples.get(key)
                if row is None:
                    row = samples[key] = {"__key__": key}
                # a write-side dict/list column lands as "<col>.json" —
                # restore the original column name after decoding
                col = suffix[:-5] if suffix.endswith(".json") else suffix
                if suffixes is not None and col not in suffixes:
                    continue
                if col in row:
                    raise ValueError(
                        f"webdataset shard {path!r}: sample {key!r} has "
                        f"more than one member for column {col!r}"
                    )
                row[col] = _decode(suffix, tf.extractfile(member).read())
        rows = list(samples.values())
        return pa.Table.from_pylist(rows) if rows else pa.table({})

    return Dataset([_Read(files, read)])


def _mongo_client(uri: str, client_factory, op: str):
    """The one place the pymongo-or-factory decision lives (read + write
    paths must construct clients identically)."""
    if client_factory is not None:
        return client_factory()
    try:
        import pymongo
    except ImportError as e:
        raise ImportError(
            f"{op} needs the pymongo package (not in this image) or an "
            "explicit client_factory") from e
    return pymongo.MongoClient(uri)


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: Optional[list] = None,
               client_factory=None) -> Dataset:
    """Documents from a MongoDB collection, optionally through an
    aggregation pipeline (reference: read_api.py read_mongo /
    mongo_datasource.py, which shards by partitioning _id ranges — here
    one read task per pipeline; shard by unioning range-filtered calls).
    ``client_factory`` (a zero-arg callable returning a pymongo-shaped
    client) makes this testable without a server; it defaults to
    ``pymongo.MongoClient(uri)`` and fails fast when pymongo is absent."""

    def read(_src) -> pa.Table:
        client = _mongo_client(uri, client_factory, "read_mongo")
        try:
            coll = client[database][collection]
            docs = list(coll.aggregate(pipeline) if pipeline
                        else coll.find({}))
        finally:
            client.close()
        import datetime
        import decimal

        arrow_ok = (str, int, float, bool, list, dict, bytes, type(None),
                    datetime.datetime, datetime.date, decimal.Decimal)
        for d in docs:
            # drop only non-arrow-convertible _id values (pymongo ObjectId);
            # a $group pipeline's _id IS the group key and must survive —
            # including date/Decimal group keys, which arrow handles
            if "_id" in d and not isinstance(d["_id"], arrow_ok):
                del d["_id"]
        return pa.Table.from_pylist(docs) if docs else pa.table({})

    return Dataset([_Read([f"{database}.{collection}"], read)])


def read_binary_files(paths, *, include_paths: bool = False,
                      parallelism: int = -1) -> Dataset:
    """One row per file with its raw bytes (reference:
    python/ray/data/read_api.py read_binary_files) — the generic ingest for
    audio/archives/protos that downstream map_batches decode."""
    files = _resolve_paths(paths)

    def read(path) -> pa.Table:
        with open(path, "rb") as f:
            data = f.read()
        cols = {"bytes": pa.array([data], type=pa.binary())}
        if include_paths:
            cols["path"] = pa.array([path])
        return pa.table(cols)

    return Dataset([_Read(files, read)])
