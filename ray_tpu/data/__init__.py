"""ray_tpu.data — distributed Arrow-blocked data pipelines on the task core.

Equivalent of the reference data library (reference: python/ray/data/ —
Dataset dataset.py:178, streaming executor _internal/execution/
streaming_executor.py:49). All block transforms run as ray_tpu tasks over
object-store blocks; ingestion ends in `iter_jax_batches` device feeding.
"""
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.compute import ActorPoolStrategy, TaskPoolStrategy
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.datasource import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_csv,
    read_binary_files,
    read_huggingface,
    read_images,
    read_json,
    read_mongo,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)

__all__ = [
    "ActorPoolStrategy",
    "TaskPoolStrategy",
    "BlockAccessor",
    "DataContext",
    "DataIterator",
    "Dataset",
    "GroupedData",
    "from_arrow",
    "from_huggingface",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_csv",
    "read_binary_files",
    "read_huggingface",
    "read_images",
    "read_json",
    "read_mongo",
    "read_numpy",
    "read_parquet",
    "read_sql",
    "read_text",
    "read_tfrecords",
    "read_webdataset",
]


from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("data")
del _rlu
