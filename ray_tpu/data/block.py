"""Blocks: the unit of data the executor moves through the object store.

A Block is a pyarrow.Table (reference: python/ray/data/block.py:216 —
Block = Arrow/pandas table; ours is Arrow-only internally, with pandas /
numpy views materialized at the API boundary). BlockAccessor gives the
format-agnostic operations the planner and operators need.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

# Column name used when the user data is a bare sequence of scalars/arrays
# (reference uses the same convention, data/_internal/util.py "item").
ITEM_COL = "item"


def _to_table(data: Any) -> pa.Table:
    """Normalize user data (table / pandas / dict of columns / list of rows /
    list of scalars) into an Arrow table."""
    if isinstance(data, pa.Table):
        return data
    if hasattr(data, "to_arrow"):  # e.g. polars-like
        return data.to_arrow()
    try:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:
        pass
    if isinstance(data, dict):
        arrays, fields = [], []
        for k, v in data.items():
            v = np.asarray(v)
            if v.ndim > 1:
                # tensor column: fixed-size-list array, element shape kept in
                # field metadata so to_numpy() restores (N, *shape)
                arr = _tensor_to_arrow(v)
                meta = {b"tensor_shape": ",".join(map(str, v.shape[1:])).encode()}
                fields.append(pa.field(k, arr.type, metadata=meta))
                arrays.append(arr)
            else:
                arr = pa.array(v)
                fields.append(pa.field(k, arr.type))
                arrays.append(arr)
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))
    if isinstance(data, list):
        if data and isinstance(data[0], dict):
            return pa.Table.from_pylist(data)
        return pa.table({ITEM_COL: pa.array(data)})
    raise TypeError(f"cannot convert {type(data)} to a Block")


def _tensor_to_arrow(arr: np.ndarray) -> pa.Array:
    """Store an (N, ...) ndarray as an Arrow FixedSizeListArray (flattened),
    shape carried in the field metadata by the accessor on read-back via
    reshape. For ragged/complex cases fall back to object pickling per row."""
    n = arr.shape[0]
    flat = np.ascontiguousarray(arr).reshape(n, -1)
    inner = pa.array(flat.reshape(-1))
    fsl = pa.FixedSizeListArray.from_arrays(inner, flat.shape[1])
    return fsl


class BlockAccessor:
    """Format-agnostic view over one Arrow table block (reference:
    data/block.py BlockAccessor / _internal/arrow_block.py)."""

    def __init__(self, table: pa.Table):
        self._t = table

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(_to_table(block))

    @property
    def table(self) -> pa.Table:
        return self._t

    def num_rows(self) -> int:
        return self._t.num_rows

    def size_bytes(self) -> int:
        return self._t.nbytes

    def schema(self) -> pa.Schema:
        return self._t.schema

    def slice(self, start: int, end: int) -> pa.Table:
        return self._t.slice(start, end - start)

    def to_pandas(self):
        return self._t.to_pandas()

    def to_numpy(self, columns: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
        cols = columns or self._t.column_names
        out = {}
        for name in cols:
            col = self._t.column(name)
            if pa.types.is_fixed_size_list(col.type):
                arrs = col.combine_chunks()
                if isinstance(arrs, pa.ChunkedArray):
                    arrs = arrs.chunk(0)
                width = col.type.list_size
                flat = arrs.flatten().to_numpy(zero_copy_only=False)
                field = self._t.schema.field(name)
                meta = field.metadata or {}
                if b"tensor_shape" in meta:
                    shape = tuple(
                        int(d) for d in meta[b"tensor_shape"].decode().split(",")
                        if d)
                    out[name] = flat.reshape((len(col),) + shape)
                else:
                    out[name] = flat.reshape(len(col), width)
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def to_pylist(self) -> List[dict]:
        return self._t.to_pylist()

    def iter_rows(self) -> Iterable[dict]:
        for batch in self._t.to_batches():
            yield from batch.to_pylist()

    def take_rows(self, indices: np.ndarray) -> pa.Table:
        return self._t.take(pa.array(indices))

    def sample(self, n: int, seed: Optional[int] = None) -> pa.Table:
        rng = np.random.default_rng(seed)
        n = min(n, self._t.num_rows)
        idx = rng.choice(self._t.num_rows, size=n, replace=False)
        return self.take_rows(idx)

    def sort(self, key: str, descending: bool = False) -> pa.Table:
        order = "descending" if descending else "ascending"
        idx = pc.sort_indices(self._t, sort_keys=[(key, order)])
        return self._t.take(idx)

    @staticmethod
    def concat(tables: List[pa.Table]) -> pa.Table:
        nonempty = [t for t in tables if t.num_rows > 0]
        if not nonempty:
            # preserve schema of all-empty inputs (repartition edge blocks)
            for t in tables:
                if t.schema.names:
                    return t.slice(0, 0)
            return pa.table({})
        return pa.concat_tables(nonempty, promote_options="permissive")


def format_batch(table: pa.Table, batch_format: str):
    """Materialize a block slice in the format map_batches/iter_batches asked
    for (reference: data/_internal/batcher + block accessor to_batch_format)."""
    acc = BlockAccessor(table)
    if batch_format in ("pyarrow", "arrow"):
        return table
    if batch_format == "pandas":
        return acc.to_pandas()
    if batch_format in ("numpy", "default", None):
        return acc.to_numpy()
    raise ValueError(f"unknown batch_format {batch_format!r}")


def batch_to_table(batch: Any) -> pa.Table:
    return _to_table(batch)
