"""GPT-2 family — the flagship transformer, mesh-parallel from the ground up.

Model config matches GPT-2 125M (BASELINE.json config 3: "JaxTrainer GPT-2
125M data-parallel"). Written as pure-JAX param pytrees with a parallel
tree of *logical axis names* so every parallelism strategy in
ray_tpu/parallel (dp/fsdp/tp/sp) is a rules-table change, not a model
change. Transformer blocks are stacked and iterated with `lax.scan` —
one compiled block body regardless of depth (XLA-friendly control flow).

Dtype policy: params f32, activations bf16, loss/softmax f32.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.layers import gelu, layer_norm
from ray_tpu.parallel.sharding import ShardingRules, with_logical_constraint


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304  # 50257 padded to a multiple of 128 for the MXU
    max_seq_len: int = 1024
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_mlp: int = 3072
    dropout: float = 0.0  # dropout-free by default (modern practice)
    dtype: Any = jnp.bfloat16
    attention: str = "flash"  # flash | xla | ring (training/full-seq path)
    # decode attention backend (serve/llm): auto | xla | pallas — "auto"
    # picks the Pallas paged-attention kernel (ops/paged_attention.py) on
    # TPU and the XLA gather formulation elsewhere. Static in the jitted
    # decode step; threaded from EngineConfig.attention_backend.
    attention_backend: str = "auto"
    # serving quantization ("int8" | "fp8" | None): weights quantized
    # per-channel by the executor (ops/quantization.py) and the paged KV
    # pool stored quantized with per-(token, head) scales. Static in the
    # jitted steps (part of the decode jit-cache key); threaded from
    # EngineConfig.quantization. Training paths ignore it.
    quantization: str | None = None
    remat: bool = False       # jax.checkpoint each block (long-context)
    scan_layers: bool = True  # lax.scan over blocks (one compiled body) vs a
                              # fully unrolled Python loop. Unrolling lets XLA
                              # schedule/fuse across layer boundaries instead
                              # of round-tripping the scan carry: measured
                              # 33%→43% MFU on GPT-2-small bs16/seq1024 on a
                              # v5e — the backward pays the scan tax. Cost:
                              # ~3x compile time; meshes with pipeline
                              # parallelism need the scan form.
    fused_loss: bool = True   # chunked lm-head+CE, no [B,S,V] logits
                              # (single-device path; meshes use the einsum
                              # head so tp can shard the vocab matmul)

    @staticmethod
    def gpt2_small() -> "GPTConfig":
        return GPTConfig()

    @staticmethod
    def gpt2_medium() -> "GPTConfig":
        return GPTConfig(n_layer=24, n_head=16, d_model=1024, d_mlp=4096)

    @staticmethod
    def tiny(vocab_size: int = 512) -> "GPTConfig":
        """Test-size config for CPU meshes."""
        return GPTConfig(
            vocab_size=vocab_size, max_seq_len=128, n_layer=2, n_head=4,
            d_model=64, d_mlp=256,
        )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head


def gpt_init(key: jax.Array, cfg: GPTConfig) -> dict:
    """Initialize params. Block weights carry a leading n_layer axis (for
    lax.scan); GPT-2 init: normal(0.02), residual projections scaled by
    1/sqrt(2*n_layer)."""
    k = iter(jax.random.split(key, 16))
    std = 0.02
    resid_std = std / math.sqrt(2 * cfg.n_layer)
    L, D, H, M, V, S = (
        cfg.n_layer, cfg.d_model, cfg.n_head, cfg.d_mlp,
        cfg.vocab_size, cfg.max_seq_len,
    )

    def norm(key, *shape, scale=std):
        return (jax.random.normal(key, shape, jnp.float32) * scale)

    return {
        "wte": norm(next(k), V, D),
        "wpe": norm(next(k), S, D, scale=std / 2),
        "blocks": {
            "ln1_scale": jnp.ones((L, D), jnp.float32),
            "ln1_bias": jnp.zeros((L, D), jnp.float32),
            "qkv_w": norm(next(k), L, D, 3 * D),
            "qkv_b": jnp.zeros((L, 3 * D), jnp.float32),
            "proj_w": norm(next(k), L, D, D, scale=resid_std),
            "proj_b": jnp.zeros((L, D), jnp.float32),
            "ln2_scale": jnp.ones((L, D), jnp.float32),
            "ln2_bias": jnp.zeros((L, D), jnp.float32),
            "mlp_in_w": norm(next(k), L, D, M),
            "mlp_in_b": jnp.zeros((L, M), jnp.float32),
            "mlp_out_w": norm(next(k), L, M, D, scale=resid_std),
            "mlp_out_b": jnp.zeros((L, D), jnp.float32),
        },
        "ln_f_scale": jnp.ones((D,), jnp.float32),
        "ln_f_bias": jnp.zeros((D,), jnp.float32),
    }


def gpt_param_axes(cfg: GPTConfig | None = None) -> dict:
    """Logical axis names, same tree structure as gpt_init's output.

    "embed" maps to fsdp (ZeRO-3 sharding), "mlp"/"heads"/"vocab" to tp —
    see parallel/sharding.py DEFAULT_RULES. "layer" is never sharded.
    """
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": {
            "ln1_scale": (None, "embed"),
            "ln1_bias": (None, "embed"),
            "qkv_w": (None, "embed", "mlp"),
            "qkv_b": (None, "mlp"),
            "proj_w": (None, "mlp", "embed"),
            "proj_b": (None, "embed"),
            "ln2_scale": (None, "embed"),
            "ln2_bias": (None, "embed"),
            "mlp_in_w": (None, "embed", "mlp"),
            "mlp_in_b": (None, "mlp"),
            "mlp_out_w": (None, "mlp", "embed"),
            "mlp_out_b": (None, "embed"),
        },
        "ln_f_scale": ("embed",),
        "ln_f_bias": ("embed",),
    }


def gpt_quant_axes(cfg: GPTConfig | None = None) -> dict:
    """Per-leaf amax reduction axis for serving weight quantization, same
    tree structure as gpt_init's output (``ops/quantization.py
    quantize_params``). The axis is each matmul's CONTRACTION axis so the
    scale is per-output-channel; -1 keeps the leaf in full precision
    (biases, layer norms — tiny and numerically load-bearing). ``wte``
    reduces over embed: per-vocab-row scales serve both the gather and
    the tied lm head (which contracts embed per vocab row)."""
    return {
        "wte": 1,
        "wpe": 1,
        "blocks": {
            "ln1_scale": -1,
            "ln1_bias": -1,
            "qkv_w": 1,
            "qkv_b": -1,
            "proj_w": 1,
            "proj_b": -1,
            "ln2_scale": -1,
            "ln2_bias": -1,
            "mlp_in_w": 1,
            "mlp_in_b": -1,
            "mlp_out_w": 1,
            "mlp_out_b": -1,
        },
        "ln_f_scale": -1,
        "ln_f_bias": -1,
    }


def _attn_qkv(x, bp, cfg: GPTConfig):
    """ln1 + fused QKV projection. x: [B, S, D] -> q, k, v [B, S, H, hd].
    Shared by the full-sequence block and the KV-cached prefill/decode
    paths (serve/llm) so the projection math exists exactly once."""
    B, S, _ = x.shape
    H, hd = cfg.n_head, cfg.head_dim
    h = layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
    qkv = (h @ bp["qkv_w"].astype(cfg.dtype)) + bp["qkv_b"].astype(cfg.dtype)
    q, kk, vv = jnp.split(qkv, 3, axis=-1)
    return (
        q.reshape(B, S, H, hd),
        kk.reshape(B, S, H, hd),
        vv.reshape(B, S, H, hd),
    )


def _attn_residual(x, attn, bp, cfg: GPTConfig):
    """Output projection + residual. attn: [B, S, D] (heads merged)."""
    return x + (attn @ bp["proj_w"].astype(cfg.dtype)) + bp["proj_b"].astype(
        cfg.dtype
    )


def _mlp_residual(x, bp, cfg: GPTConfig, constrain=None):
    h = layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    h = gelu((h @ bp["mlp_in_w"].astype(cfg.dtype)) + bp["mlp_in_b"].astype(cfg.dtype))
    if constrain is not None:
        h = constrain(h, ("batch", "seq", "mlp"))
    return x + (h @ bp["mlp_out_w"].astype(cfg.dtype)) + bp["mlp_out_b"].astype(
        cfg.dtype
    )


def _block(x, bp, cfg: GPTConfig, rules: ShardingRules | None, mesh):
    """One transformer block. x: [B, S, D] in cfg.dtype."""
    B, S, D = x.shape

    def constrain(t, axes):
        if mesh is None:
            return t
        return with_logical_constraint(t, axes, rules, mesh)

    q, kk, vv = _attn_qkv(x, bp, cfg)
    q = q.transpose(0, 2, 1, 3)
    kk = kk.transpose(0, 2, 1, 3)
    vv = vv.transpose(0, 2, 1, 3)
    q = constrain(q, ("batch", "heads", None, None))

    if cfg.attention == "flash":
        attn = flash_attention(q, kk, vv, causal=True)
    elif cfg.attention == "ring":
        from ray_tpu.ops.ring_attention import ring_attention_sharded

        attn = ring_attention_sharded(q, kk, vv, mesh, causal=True)
    else:
        attn = mha_reference(q, kk, vv, causal=True)

    attn = attn.transpose(0, 2, 1, 3).reshape(B, S, D)
    x = _attn_residual(x, attn, bp, cfg)
    x = _mlp_residual(x, bp, cfg, constrain)
    return constrain(x, ("batch", "seq", "embed"))


def gpt_hidden(
    params: dict,
    tokens: jax.Array,
    cfg: GPTConfig,
    *,
    rules: ShardingRules | None = None,
    mesh=None,
) -> jax.Array:
    """tokens [B, S] int32 → final hidden states [B, S, D] (cfg.dtype),
    after the final layer norm (everything but the lm-head)."""
    B, S = tokens.shape
    wte = params["wte"].astype(cfg.dtype)
    if mesh is not None:
        # Gather from a vocab/embed-sharded table forces SPMD's last-resort
        # full rematerialization (replicate + repartition per step). The
        # lookup wants the table replicated anyway — say so EXPLICITLY, so
        # the all-gather happens once where the partitioner can place it,
        # and the gather itself partitions trivially along batch.
        wte = with_logical_constraint(wte, (None, None), rules, mesh)
    x = wte[tokens] + params["wpe"].astype(cfg.dtype)[:S]
    if mesh is not None:
        x = with_logical_constraint(x, ("batch", "seq", "embed"), rules, mesh)

    blocks = params["blocks"]
    body = lambda x, bp: _block(x, bp, cfg, rules, mesh)
    if cfg.remat:
        body = jax.checkpoint(body)
    if cfg.scan_layers:
        x, _ = jax.lax.scan(lambda c, bp: (body(c, bp), None), x, blocks)
    else:
        for i in range(cfg.n_layer):
            x = body(x, jax.tree.map(lambda a: a[i], blocks))

    return layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])


def gpt_forward(
    params: dict,
    tokens: jax.Array,
    cfg: GPTConfig,
    *,
    rules: ShardingRules | None = None,
    mesh=None,
) -> jax.Array:
    """tokens [B, S] int32 → logits [B, S, vocab] (f32)."""
    x = gpt_hidden(params, tokens, cfg, rules=rules, mesh=mesh)
    wte = params["wte"].astype(cfg.dtype)
    if mesh is not None:
        wte = with_logical_constraint(wte, (None, None), rules, mesh)
    # tied embeddings (GPT-2): output projection = wte^T. Inputs stay bf16
    # so the MXU runs at bf16 rate (the lm-head is ~25% of model FLOPs);
    # accumulation and the returned logits are f32 for a stable softmax.
    logits = jnp.einsum(
        "bsd,vd->bsv", x.astype(cfg.dtype), wte,
        preferred_element_type=jnp.float32,
    )
    return logits


def gpt_loss(
    params: dict,
    batch: dict,
    cfg: GPTConfig,
    *,
    rules: ShardingRules | None = None,
    mesh=None,
) -> jax.Array:
    """Next-token cross-entropy. batch: {"tokens": [B, S+1]} or
    {"inputs": [B,S], "targets": [B,S]}."""
    mask = batch.get("mask")
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
        # a [B, S+1] token-aligned mask must shift with the targets; a
        # [B, S] mask is already target-aligned
        if mask is not None and mask.shape[-1] == batch["tokens"].shape[-1]:
            mask = mask[:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    if cfg.fused_loss and mesh is None:
        # single-device path: chunked lm-head + CE with closed-form grads
        # (ops/loss.py) — the [B,S,V] logits tensor never exists, which is
        # what lets bs16-32/seq1024 GPT-2 fit a single v5e chip
        from ray_tpu.ops.loss import fused_lm_head_loss

        x = gpt_hidden(params, inputs, cfg, rules=rules, mesh=mesh)
        B, S, D = x.shape
        return fused_lm_head_loss(
            x.reshape(B * S, D),
            params["wte"],
            targets.reshape(B * S).astype(jnp.int32),
            None if mask is None else mask.reshape(B * S).astype(jnp.float32),
        )
    logits = gpt_forward(params, inputs, cfg, rules=rules, mesh=mesh)
    # target log-prob without materializing a [B,S,V] log_softmax: the
    # gather and the logsumexp reduction fuse into the logits producer
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    ll = picked - lse
    if mask is not None:
        return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1)
    return -jnp.mean(ll)


# ----------------------------------------------------------------------------
# KV-cached inference paths (serve/llm engine). Shapes are static in
# (batch, padded length, blocks-per-seq) so the engine's bucketing bounds
# the XLA compile cache. Cache layout: [n_layer, num_blocks, block_size,
# n_head, head_dim] (ops/kv_cache.py; block 0 is the garbage sink).
# ----------------------------------------------------------------------------


def gpt_prefill(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    lengths: jax.Array,
    block_tables: jax.Array,
    cfg: GPTConfig,
    start: jax.Array | None = None,
    sample: dict | None = None,
):
    """Prompt pass: run the causal forward over right-padded prompts,
    writing every valid position's K/V into the paged cache.

    tokens [B, S] int32, lengths [B] (valid prefix per row; padding rows
    use length 1 + an all-garbage block table), block_tables [B, NB].
    Returns (last-valid-token logits [B, V] f32, cache_k', cache_v');
    with a ``sample`` pytree (ops/sampling.py) sampling fuses into the
    jitted program and (sampled first tokens [B] int32, cache_k',
    cache_v') comes back instead — logits never leave the device.

    ``start=None``: the whole prompt starts at position 0. Under the XLA
    backend attention is the reference kernel over the chunk alone —
    prefill happens once per request at bucketed shapes, where flash's
    grid setup buys nothing; under pallas it runs the fused paged-prefill
    kernel off the just-written cache (the padded context never exists in
    HBM). ``start`` [B] int32 (chunked prefill / prefix-cache hits): row
    b's tokens sit at TRUE positions start[b].. and earlier positions are
    already resident in the paged cache, so positional embeddings index
    the true positions and attention covers the full paged context via
    the ``prefill_attention`` backend dispatcher.
    """
    from ray_tpu.ops.kv_cache import write_kv
    from ray_tpu.ops.paged_attention import prefill_attention, resolve_backend

    B, S = tokens.shape
    D = cfg.d_model
    if start is None:
        pos = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32)[None, :], (B, S)
        )
        x = params["wte"].astype(cfg.dtype)[tokens] + params["wpe"].astype(
            cfg.dtype
        )[:S]
    else:
        pos = start[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
        # padding columns can run past the table; they are masked anyway
        emb_pos = jnp.minimum(pos, cfg.max_seq_len - 1)
        x = params["wte"].astype(cfg.dtype)[tokens] + params["wpe"].astype(
            cfg.dtype
        )[emb_pos]
    valid = jnp.arange(S, dtype=jnp.int32)[None, :] < lengths[:, None]

    def body(x, xs):
        bp, k_layer, v_layer = xs
        q, kk, vv = _attn_qkv(x, bp, cfg)
        k_layer, v_layer = write_kv(
            k_layer, v_layer, kk, vv, pos, block_tables, valid=valid
        )
        # The fresh-KV shortcut attends over the UNQUANTIZED just-computed
        # k/v; under a quantized pool it must not run — chunked re-prefill
        # (failover resume) reads the quantized pool back, and resumed
        # streams stay byte-identical only if the original prefill saw the
        # same quantized values. So quantized prefill always attends off
        # the just-written pool via prefill_attention.
        if (
            start is None
            and cfg.quantization is None
            and resolve_backend(cfg.attention_backend) != "pallas"
        ):
            attn = mha_reference(
                q.transpose(0, 2, 1, 3),
                kk.transpose(0, 2, 1, 3),
                vv.transpose(0, 2, 1, 3),
                causal=True,
            ).transpose(0, 2, 1, 3).reshape(B, S, D)
        else:
            attn = prefill_attention(
                q, k_layer, v_layer, block_tables,
                jnp.where(valid, pos, 0),
                backend=cfg.attention_backend,
            ).reshape(B, S, D)
        x = _attn_residual(x, attn, bp, cfg)
        x = _mlp_residual(x, bp, cfg)
        return x, (k_layer, v_layer)

    x, (cache_k, cache_v) = jax.lax.scan(
        body, x, (params["blocks"], cache_k, cache_v)
    )
    h = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    h_last = h[jnp.arange(B), lengths - 1]  # [B, D]
    logits = jnp.einsum(
        "bd,vd->bv", h_last.astype(cfg.dtype), params["wte"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    if sample is None:
        return logits, cache_k, cache_v
    from ray_tpu.ops.sampling import sample_tokens

    # the new token lands right after the last valid prompt token
    new_pos = (lengths if start is None else start + lengths).astype(
        jnp.int32
    )
    return sample_tokens(logits, new_pos, sample), cache_k, cache_v


def gpt_decode_step(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    positions: jax.Array,
    block_tables: jax.Array,
    cfg: GPTConfig,
    sample: dict | None = None,
):
    """One incremental decode step for a batch of sequences.

    tokens [B] int32 (each sequence's newest token), positions [B] (its
    logical position), block_tables [B, NB]. Writes the token's K/V, then
    attends over the gathered paged context (mask includes self). Padding
    rows point at the garbage block with position 0.
    Returns (next-token logits [B, V] f32, cache_k', cache_v'); with a
    ``sample`` pytree the logits never leave the device — returns
    (sampled tokens [B] int32, cache_k', cache_v').
    """
    from ray_tpu.ops.kv_cache import write_kv
    from ray_tpu.ops.paged_attention import decode_attention

    B = tokens.shape[0]
    D = cfg.d_model
    x = params["wte"].astype(cfg.dtype)[tokens] + params["wpe"].astype(
        cfg.dtype
    )[positions]
    x = x[:, None, :]  # [B, 1, D]

    def body(x, xs):
        bp, k_layer, v_layer = xs
        q, kk, vv = _attn_qkv(x, bp, cfg)  # [B, 1, H, hd]
        k_layer, v_layer = write_kv(
            k_layer, v_layer, kk[:, 0], vv[:, 0], positions, block_tables
        )
        attn = decode_attention(
            q[:, 0], k_layer, v_layer, block_tables, positions,
            backend=cfg.attention_backend,
        )
        x = _attn_residual(x, attn.reshape(B, 1, D), bp, cfg)
        x = _mlp_residual(x, bp, cfg)
        return x, (k_layer, v_layer)

    x, (cache_k, cache_v) = jax.lax.scan(
        body, x, (params["blocks"], cache_k, cache_v)
    )
    h = layer_norm(x[:, 0], params["ln_f_scale"], params["ln_f_bias"])
    logits = jnp.einsum(
        "bd,vd->bv", h.astype(cfg.dtype), params["wte"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    if sample is None:
        return logits, cache_k, cache_v
    from ray_tpu.ops.sampling import sample_tokens

    return sample_tokens(logits, positions + 1, sample), cache_k, cache_v


def gpt_verify_step(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    starts: jax.Array,
    draft_len: jax.Array,
    block_tables: jax.Array,
    cfg: GPTConfig,
    sample: dict | None = None,
):
    """Speculative-decoding verify pass; see models/llama.py
    ``llama_verify_step`` for the full contract (window layout, K/V
    discipline, packed return). This is the GPT-family twin: learned
    positional embeddings indexed at the true window positions instead of
    RoPE, and the tied-embedding logits head over ALL window positions
    feeding the ``verify_tokens`` epilogue.
    """
    from ray_tpu.ops.kv_cache import write_kv
    from ray_tpu.ops.paged_attention import prefill_attention

    B, W = tokens.shape
    D = cfg.d_model
    pos = starts[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    # padding columns can run past the table; they are masked anyway
    emb_pos = jnp.minimum(pos, cfg.max_seq_len - 1)
    x = params["wte"].astype(cfg.dtype)[tokens] + params["wpe"].astype(
        cfg.dtype
    )[emb_pos]
    valid = (
        jnp.arange(W, dtype=jnp.int32)[None, :] <= draft_len[:, None]
    )

    def body(x, xs):
        bp, k_layer, v_layer = xs
        q, kk, vv = _attn_qkv(x, bp, cfg)
        k_layer, v_layer = write_kv(
            k_layer, v_layer, kk, vv, pos, block_tables, valid=valid
        )
        attn = prefill_attention(
            q, k_layer, v_layer, block_tables, jnp.where(valid, pos, 0),
            backend=cfg.attention_backend,
        ).reshape(B, W, D)
        x = _attn_residual(x, attn, bp, cfg)
        x = _mlp_residual(x, bp, cfg)
        return x, (k_layer, v_layer)

    x, (cache_k, cache_v) = jax.lax.scan(
        body, x, (params["blocks"], cache_k, cache_v)
    )
    h = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])  # [B, W, D]
    logits = jnp.einsum(
        "bwd,vd->bwv", h.astype(cfg.dtype), params["wte"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    if sample is None:
        return logits, cache_k, cache_v
    from ray_tpu.ops.sampling import verify_tokens

    return (
        verify_tokens(logits, starts, tokens, draft_len, sample),
        cache_k,
        cache_v,
    )


def gpt_num_params(cfg: GPTConfig) -> int:
    p = gpt_init(jax.random.PRNGKey(0), cfg)
    return sum(x.size for x in jax.tree.leaves(p))
