"""ResNet-50 — the convnet benchmark model (BASELINE.json config 1:
"DataParallelTrainer ResNet-50"; reference throughput targets in
BASELINE.md from doc/source/train/benchmarks.rst).

Flax linen implementation, NHWC layout (TPU-native conv layout), bf16
compute / f32 BatchNorm statistics. v1.5 variant (stride in the 3x3)
matching torchvision's resnet50 so images/sec comparisons are like-for-like.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any

BN_EPS = 1e-5  # single source of truth — fold_batch_norm must match


class Bottleneck(nn.Module):
    """`folded=True` is the inference variant with BatchNorm absorbed into
    the convs (bias + relu epilogue only, consuming fold_batch_norm's
    params); one structural definition serves both paths so the trees map
    conv-for-conv by construction."""

    features: int
    strides: int = 1
    dtype: Any = jnp.bfloat16
    folded: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=self.folded, dtype=self.dtype)
        bn = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=BN_EPS,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )

        def norm(y, **kw):
            return y if self.folded else bn(**kw)(y)

        residual = x
        y = conv(self.features, (1, 1))(x)
        y = nn.relu(norm(y))
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(norm(y))
        y = conv(self.features * 4, (1, 1))(y)
        # zero-init the last BN scale: identity residual at init
        y = norm(y, scale_init=nn.initializers.zeros)
        if residual.shape != y.shape:
            residual = conv(
                self.features * 4, (1, 1), strides=(self.strides, self.strides),
                name="downsample_conv",
            )(x)
            residual = norm(residual, name="downsample_bn")
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    folded: bool = False  # inference variant: BN folded into the convs

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(
            64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=self.folded, dtype=self.dtype, name="conv_init",
        )(x)
        if not self.folded:
            x = nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=BN_EPS,
                dtype=self.dtype, param_dtype=jnp.float32, name="bn_init",
            )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(64 * 2**i, strides=strides, dtype=self.dtype,
                               folded=self.folded)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


def ResNet50(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, dtype=dtype)


def resnet_init(key: jax.Array, model: ResNet, image_size: int = 224):
    variables = model.init(
        key, jnp.zeros((1, image_size, image_size, 3), jnp.float32), train=True
    )
    return variables["params"], variables["batch_stats"]


def FoldedResNet(stage_sizes, num_classes: int = 1000,
                 dtype=jnp.bfloat16) -> ResNet:
    """BN-free inference variant (W' = W * gamma/sqrt(var+eps) per
    out-channel, b' = beta - mean * gamma/sqrt(var+eps)); consumes
    fold_batch_norm's params. Removes every BN read-modify-write pass from
    the serving graph — the conv epilogue is just bias+relu, which XLA
    fuses into the convolution (VERDICT r3 #4: unfused BN is the ResNet
    HBM ceiling; the training-time equivalent needs running stats and
    stays unfolded)."""
    return ResNet(stage_sizes=stage_sizes, num_classes=num_classes,
                  dtype=dtype, folded=True)


def _fold_one(conv_p: dict, bn_p: dict, bn_s: dict, eps: float) -> dict:
    """Absorb one BatchNorm (scale/bias + running stats) into the conv that
    feeds it."""
    inv = bn_p["scale"] / jnp.sqrt(bn_s["var"] + eps)
    kernel = conv_p["kernel"] * inv  # broadcast over the out-channel axis
    bias = bn_p["bias"] - bn_s["mean"] * inv
    return {"kernel": kernel, "bias": bias}


def fold_batch_norm(params: dict, batch_stats: dict,
                    eps: float = BN_EPS) -> dict:
    """Trained (params, batch_stats) -> folded (ResNet(folded=True)) param
    tree. Pure tree surgery; numerical equivalence to
    model.apply(train=False) is exact up to dtype rounding
    (tests/test_models.py). `eps` must match the model's BatchNorm epsilon
    (BN_EPS for the in-tree ResNet)."""
    out: dict = {
        "conv_init": _fold_one(params["conv_init"], params["bn_init"],
                               batch_stats["bn_init"], eps),
        "head": params["head"],
    }
    for name, block in params.items():
        if not name.startswith("Bottleneck_"):
            continue
        stats = batch_stats[name]
        folded: dict = {}
        for k in range(3):
            folded[f"Conv_{k}"] = _fold_one(
                block[f"Conv_{k}"], block[f"BatchNorm_{k}"],
                stats[f"BatchNorm_{k}"], eps)
        if "downsample_conv" in block:
            folded["downsample_conv"] = _fold_one(
                block["downsample_conv"], block["downsample_bn"],
                stats["downsample_bn"], eps)
        out[name] = folded
    return out


def resnet_loss(params, batch_stats, model, batch, train: bool = True):
    """Cross-entropy + new batch stats. batch: {'image' NHWC, 'label' int}."""
    if train:
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"],
            train=True,
            mutable=["batch_stats"],
        )
        new_stats = mutated["batch_stats"]
    else:
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"],
            train=False,
        )
        new_stats = batch_stats
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    ll = jnp.take_along_axis(logp, batch["label"][:, None], axis=-1)[:, 0]
    acc = jnp.mean((jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
    return -jnp.mean(ll), (new_stats, acc)
