"""ResNet-50 — the convnet benchmark model (BASELINE.json config 1:
"DataParallelTrainer ResNet-50"; reference throughput targets in
BASELINE.md from doc/source/train/benchmarks.rst).

Flax linen implementation, NHWC layout (TPU-native conv layout), bf16
compute / f32 BatchNorm statistics. v1.5 variant (stride in the 3x3)
matching torchvision's resnet50 so images/sec comparisons are like-for-like.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = bn()(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides))(y)
        y = bn()(y)
        y = nn.relu(y)
        y = conv(self.features * 4, (1, 1))(y)
        # zero-init the last BN scale: identity residual at init
        y = bn(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(
                self.features * 4, (1, 1), strides=(self.strides, self.strides),
                name="downsample_conv",
            )(x)
            residual = bn(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(
            64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=self.dtype, name="conv_init",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.dtype, param_dtype=jnp.float32, name="bn_init",
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(64 * 2**i, strides=strides, dtype=self.dtype)(
                    x, train=train
                )
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


def ResNet50(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, dtype=dtype)


def resnet_init(key: jax.Array, model: ResNet, image_size: int = 224):
    variables = model.init(
        key, jnp.zeros((1, image_size, image_size, 3), jnp.float32), train=True
    )
    return variables["params"], variables["batch_stats"]


def resnet_loss(params, batch_stats, model, batch, train: bool = True):
    """Cross-entropy + new batch stats. batch: {'image' NHWC, 'label' int}."""
    if train:
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"],
            train=True,
            mutable=["batch_stats"],
        )
        new_stats = mutated["batch_stats"]
    else:
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"],
            train=False,
        )
        new_stats = batch_stats
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    ll = jnp.take_along_axis(logp, batch["label"][:, None], axis=-1)[:, 0]
    acc = jnp.mean((jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
    return -jnp.mean(ll), (new_stats, acc)
