"""ViT — vision transformer, mesh-parallel like the GPT family.

Model family matching the reference's vision-transformer workloads (the
reference trains ViT via TorchTrainer in its AIR examples,
doc/source/train/examples — the model itself is torchvision's ViT;
Dosovitskiy et al. 2020). Same construction discipline as models/gpt.py:
pure-JAX param pytrees with a parallel tree of logical axis names, blocks
stacked on a leading layer axis and iterated with lax.scan, params f32 /
activations bf16, flash attention (non-causal) on the hot path.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.layers import gelu, layer_norm
from ray_tpu.parallel.sharding import ShardingRules, with_logical_constraint


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_mlp: int = 3072
    channels: int = 3
    dtype: Any = jnp.bfloat16
    # xla by default: ViT sequences are short (num_patches + 1, ALWAYS odd
    # because of the cls token) so XLA's fused attention wins; "flash"
    # engages the Pallas kernel only when the sequence divides its blocks
    attention: str = "xla"  # xla | flash
    remat: bool = False

    @staticmethod
    def base16() -> "ViTConfig":
        return ViTConfig()  # ViT-B/16

    @staticmethod
    def tiny(image_size: int = 32, num_classes: int = 16) -> "ViTConfig":
        """Test-size config for CPU meshes. num_classes defaults to a
        tp-divisible 16 (the head is class-sharded under tensor
        parallelism, like GPT's padded vocab)."""
        return ViTConfig(
            image_size=image_size, patch_size=8, num_classes=num_classes,
            n_layer=2, n_head=4, d_model=64, d_mlp=256,
        )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels


def vit_init(key: jax.Array, cfg: ViTConfig) -> dict:
    k = iter(jax.random.split(key, 16))
    std = 0.02
    resid_std = std / math.sqrt(2 * cfg.n_layer)
    L, D, M = cfg.n_layer, cfg.d_model, cfg.d_mlp

    def norm(key, *shape, scale=std):
        return jax.random.normal(key, shape, jnp.float32) * scale

    return {
        "patch_w": norm(next(k), cfg.patch_dim, D),
        "patch_b": jnp.zeros((D,), jnp.float32),
        "cls": norm(next(k), 1, 1, D),
        "pos": norm(next(k), cfg.num_patches + 1, D, scale=std / 2),
        "blocks": {
            "ln1_scale": jnp.ones((L, D), jnp.float32),
            "ln1_bias": jnp.zeros((L, D), jnp.float32),
            "qkv_w": norm(next(k), L, D, 3 * D),
            "qkv_b": jnp.zeros((L, 3 * D), jnp.float32),
            "proj_w": norm(next(k), L, D, D, scale=resid_std),
            "proj_b": jnp.zeros((L, D), jnp.float32),
            "ln2_scale": jnp.ones((L, D), jnp.float32),
            "ln2_bias": jnp.zeros((L, D), jnp.float32),
            "mlp_in_w": norm(next(k), L, D, M),
            "mlp_in_b": jnp.zeros((L, M), jnp.float32),
            "mlp_out_w": norm(next(k), L, M, D, scale=resid_std),
            "mlp_out_b": jnp.zeros((L, D), jnp.float32),
        },
        "ln_f_scale": jnp.ones((D,), jnp.float32),
        "ln_f_bias": jnp.zeros((D,), jnp.float32),
        "head_w": norm(next(k), D, cfg.num_classes, scale=0.0),  # zero-init
        "head_b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }


def vit_param_axes(cfg: ViTConfig | None = None) -> dict:
    """Logical axis names (same tree as vit_init) — identical block table
    to gpt_param_axes so every dp/fsdp/tp rules set applies unchanged."""
    return {
        "patch_w": (None, "embed"),
        "patch_b": ("embed",),
        "cls": (None, None, "embed"),
        "pos": (None, "embed"),
        "blocks": {
            "ln1_scale": (None, "embed"),
            "ln1_bias": (None, "embed"),
            "qkv_w": (None, "embed", "mlp"),
            "qkv_b": (None, "mlp"),
            "proj_w": (None, "mlp", "embed"),
            "proj_b": (None, "embed"),
            "ln2_scale": (None, "embed"),
            "ln2_bias": (None, "embed"),
            "mlp_in_w": (None, "embed", "mlp"),
            "mlp_in_b": (None, "mlp"),
            "mlp_out_w": (None, "mlp", "embed"),
            "mlp_out_b": (None, "embed"),
        },
        "ln_f_scale": ("embed",),
        "ln_f_bias": ("embed",),
        "head_w": ("embed", "vocab"),
        "head_b": ("vocab",),
    }


def patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] → [B, N, P*P*C] (pure reshape/transpose — XLA fuses
    this into the embedding matmul; no conv needed)."""
    B, H, W, C = images.shape
    P = cfg.patch_size
    h, w = H // P, W // P
    x = images.reshape(B, h, P, w, P, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, h * w, P * P * C)


def _block(x, bp, cfg: ViTConfig, rules, mesh):
    B, S, D = x.shape
    H, hd = cfg.n_head, cfg.head_dim

    def constrain(t, axes):
        if mesh is None:
            return t
        return with_logical_constraint(t, axes, rules, mesh)

    h = layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
    qkv = (h @ bp["qkv_w"].astype(cfg.dtype)) + bp["qkv_b"].astype(cfg.dtype)
    q, kk, vv = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    kk = kk.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    vv = vv.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    q = constrain(q, ("batch", "heads", None, None))

    # the flash kernel needs S divisible by its block size (<=512 clamps
    # the block to S); an incompatible length falls back to XLA attention
    S_len = q.shape[2]
    flash_ok = S_len <= 512 or S_len % 512 == 0
    if cfg.attention == "flash" and flash_ok:
        attn = flash_attention(q, kk, vv, causal=False)
    else:
        attn = mha_reference(q, kk, vv, causal=False)

    attn = attn.transpose(0, 2, 1, 3).reshape(B, S, D)
    x = x + (attn @ bp["proj_w"].astype(cfg.dtype)) + bp["proj_b"].astype(cfg.dtype)

    h = layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    h = gelu((h @ bp["mlp_in_w"].astype(cfg.dtype)) + bp["mlp_in_b"].astype(cfg.dtype))
    h = constrain(h, ("batch", "seq", "mlp"))
    x = x + (h @ bp["mlp_out_w"].astype(cfg.dtype)) + bp["mlp_out_b"].astype(cfg.dtype)
    return constrain(x, ("batch", "seq", "embed"))


def vit_forward(
    params: dict,
    images: jax.Array,
    cfg: ViTConfig,
    *,
    rules: ShardingRules | None = None,
    mesh=None,
) -> jax.Array:
    """images [B, H, W, C] → class logits [B, num_classes] (f32)."""
    B = images.shape[0]
    patches = patchify(images.astype(cfg.dtype), cfg)
    x = (patches @ params["patch_w"].astype(cfg.dtype)
         + params["patch_b"].astype(cfg.dtype))
    cls = jnp.broadcast_to(params["cls"].astype(cfg.dtype), (B, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos"].astype(cfg.dtype)
    if mesh is not None:
        x = with_logical_constraint(x, ("batch", "seq", "embed"), rules, mesh)

    def body(x, bp):
        return _block(x, bp, cfg, rules, mesh), None

    body_fn = jax.checkpoint(body) if cfg.remat else body
    x, _ = jax.lax.scan(body_fn, x, params["blocks"])

    x = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    cls_repr = x[:, 0].astype(jnp.float32)
    return cls_repr @ params["head_w"] + params["head_b"]


def vit_loss(
    params: dict,
    batch: dict,
    cfg: ViTConfig,
    *,
    rules: ShardingRules | None = None,
    mesh=None,
):
    """Cross-entropy + accuracy. batch: {"image" [B,H,W,C], "label" [B]}."""
    logits = vit_forward(params, batch["image"], cfg, rules=rules, mesh=mesh)
    logp = jax.nn.log_softmax(logits)
    ll = jnp.take_along_axis(logp, batch["label"][:, None], axis=-1)[:, 0]
    acc = jnp.mean((jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
    return -jnp.mean(ll), acc


def vit_num_params(cfg: ViTConfig) -> int:
    p = vit_init(jax.random.PRNGKey(0), cfg)
    return sum(x.size for x in jax.tree.leaves(p))
