"""LLaMA-family transformer: RMSNorm + RoPE + GQA + SwiGLU, optional MoE.

Second flagship model family (modern-decoder architecture; the reference
ships no model zoo of its own — its Train/Serve layers wrap torch models —
so this follows the public LLaMA/Mixtral formulation). Same conventions as
models/gpt.py: pure param pytrees, a parallel tree of logical axis names,
`lax.scan` over stacked blocks, params f32 / activations bf16.

GQA: n_kv_head < n_head shares each KV head across n_head//n_kv_head query
heads (KV repeated before the attention kernel — keeps flash/ring kernels
head-uniform). MoE: num_experts > 0 swaps the SwiGLU MLP for a Mixtral-style
top-k expert MLP (ops/moe.py) with the load-balance aux loss summed over
layers.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.layers import rms_norm, rope, rope_cache
from ray_tpu.ops.moe import MoEConfig, moe_forward
from ray_tpu.parallel.sharding import ShardingRules, with_logical_constraint


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 2048
    n_layer: int = 8
    n_head: int = 8
    n_kv_head: int = 4
    d_model: int = 512
    d_mlp: int = 1408  # ~8/3 * d_model rounded to 128 (SwiGLU sizing)
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    attention: str = "flash"  # flash | xla | ring (training/full-seq path)
    # decode attention backend (serve/llm): auto | xla | pallas — see
    # models/gpt.py GPTConfig.attention_backend.
    attention_backend: str = "auto"
    # serving quantization ("int8" | "fp8" | None) — see models/gpt.py
    # GPTConfig.quantization. Threaded from EngineConfig.quantization.
    quantization: str | None = None
    remat: bool = False
    scan_layers: bool = True  # lax.scan over blocks vs unrolled loop (see
                              # models/gpt.py: unrolling dodges the
                              # backward's scan-carry tax; benches unroll,
                              # pipeline meshes keep the scan)
    fused_loss: bool = True   # chunked lm-head+CE on the single-device
                              # path — no [B,S,V] logits (ops/loss.py)
    # MoE (0 = dense SwiGLU)
    num_experts: int = 0
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coeff: float = 0.01

    @staticmethod
    def tiny(vocab_size: int = 512) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=vocab_size, max_seq_len=128, n_layer=2, n_head=4,
            n_kv_head=2, d_model=64, d_mlp=128,
        )

    @staticmethod
    def tiny_moe(vocab_size: int = 512) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=vocab_size, max_seq_len=128, n_layer=2, n_head=4,
            n_kv_head=2, d_model=64, d_mlp=128, num_experts=4, top_k=2,
        )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def kv_groups(self) -> int:
        return self.n_head // self.n_kv_head

    def __post_init__(self):
        if self.n_head % self.n_kv_head:
            raise ValueError("n_head must be a multiple of n_kv_head")


def llama_init(key: jax.Array, cfg: LlamaConfig) -> dict:
    k = iter(jax.random.split(key, 16))
    L, D, M, V = cfg.n_layer, cfg.d_model, cfg.d_mlp, cfg.vocab_size
    hd, Hq, Hkv = cfg.head_dim, cfg.n_head, cfg.n_kv_head
    std = 0.02

    def norm(key, *shape, scale=std):
        return jax.random.normal(key, shape, jnp.float32) * scale

    blocks: dict = {
        "ln1_scale": jnp.ones((L, D), jnp.float32),
        "wq": norm(next(k), L, D, Hq * hd),
        "wk": norm(next(k), L, D, Hkv * hd),
        "wv": norm(next(k), L, D, Hkv * hd),
        "wo": norm(next(k), L, Hq * hd, D, scale=std / (2 * L) ** 0.5),
        "ln2_scale": jnp.ones((L, D), jnp.float32),
    }
    if cfg.num_experts:
        E = cfg.num_experts
        blocks.update(
            {
                "moe_router": norm(next(k), L, D, E),
                # experts use the GELU MLP form of ops/moe.moe_forward
                "moe_w_in": norm(next(k), L, E, D, M, scale=D**-0.5),
                "moe_w_out": norm(next(k), L, E, M, D, scale=M**-0.5),
            }
        )
    else:
        blocks.update(
            {
                # SwiGLU packs gate+up into one [D, 2M] matmul
                "mlp_in": norm(next(k), L, D, 2 * M),
                "mlp_out": norm(next(k), L, M, D, scale=std / (2 * L) ** 0.5),
            }
        )
    return {
        "wte": norm(next(k), V, D),
        "blocks": blocks,
        "ln_f_scale": jnp.ones((D,), jnp.float32),
        "lm_head": norm(next(k), D, V),
    }


def llama_param_axes(cfg: LlamaConfig) -> dict:
    blocks: dict = {
        "ln1_scale": (None, "embed"),
        "wq": (None, "embed", "mlp"),
        "wk": (None, "embed", "mlp"),
        "wv": (None, "embed", "mlp"),
        "wo": (None, "mlp", "embed"),
        "ln2_scale": (None, "embed"),
    }
    if cfg.num_experts:
        blocks.update(
            {
                "moe_router": (None, None, None),
                "moe_w_in": (None, "expert", None, "mlp"),
                "moe_w_out": (None, "expert", "mlp", None),
            }
        )
    else:
        blocks.update(
            {
                "mlp_in": (None, "embed", "mlp"),
                "mlp_out": (None, "mlp", "embed"),
            }
        )
    return {
        "wte": ("vocab", "embed"),
        "blocks": blocks,
        "ln_f_scale": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def llama_quant_axes(cfg: LlamaConfig) -> dict:
    """Per-leaf amax reduction axis for serving weight quantization (see
    models/gpt.py gpt_quant_axes): the contraction axis of each matmul so
    scales are per-output-channel; -1 keeps the leaf in full precision.
    RMSNorm scales stay f32 (tiny, numerically load-bearing); MoE expert
    weights stay f32 because ``moe_forward`` consumes the raw params
    without the ``astype`` dequant seam."""
    blocks: dict = {
        "ln1_scale": -1,
        "wq": 1,
        "wk": 1,
        "wv": 1,
        "wo": 1,
        "ln2_scale": -1,
    }
    if cfg.num_experts:
        blocks.update(
            {"moe_router": -1, "moe_w_in": -1, "moe_w_out": -1}
        )
    else:
        blocks.update({"mlp_in": 1, "mlp_out": 1})
    return {
        "wte": 1,
        "blocks": blocks,
        "ln_f_scale": -1,
        "lm_head": 0,
    }


def _swiglu(x, w_in, w_out, dtype):
    gate_up = x @ w_in.astype(dtype)
    gate, up = jnp.split(gate_up, 2, axis=-1)
    return (jax.nn.silu(gate) * up) @ w_out.astype(dtype)


def _moe_cfg(cfg: LlamaConfig) -> MoEConfig:
    return MoEConfig(
        d_model=cfg.d_model, d_hidden=cfg.d_mlp, num_experts=cfg.num_experts,
        top_k=cfg.top_k, capacity_factor=cfg.capacity_factor,
        aux_loss_coeff=cfg.aux_loss_coeff, dtype=cfg.dtype,
    )


def _attn_qkv(x, bp, cos, sin, cfg: LlamaConfig, positions=None):
    """rms_norm + Q/K/V projections with RoPE applied at the true position
    (``positions`` [B, S] indexes the cos/sin tables; None = 0..S-1).
    Returns q [B, S, Hq, hd] and k, v [B, S, Hkv, hd] — kv heads NOT yet
    repeated, so the KV-cached path (serve/llm) stores the compact GQA
    heads. Shared by the full-sequence block and prefill/decode."""
    B, S, _ = x.shape
    Hq, Hkv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    h = rms_norm(x, bp["ln1_scale"])
    q = (h @ bp["wq"].astype(cfg.dtype)).reshape(B, S, Hq, hd)
    kk = (h @ bp["wk"].astype(cfg.dtype)).reshape(B, S, Hkv, hd)
    vv = (h @ bp["wv"].astype(cfg.dtype)).reshape(B, S, Hkv, hd)
    q = rope(q, cos, sin, positions)
    kk = rope(kk, cos, sin, positions)
    return q, kk, vv


def _ffn_residual(x, bp, cfg: LlamaConfig, constrain=None):
    """ln2 + (SwiGLU | MoE) + residual. Returns (x, aux_loss)."""
    B, S, D = x.shape
    h = rms_norm(x, bp["ln2_scale"])
    if cfg.num_experts:
        flat = h.reshape(B * S, D)
        moe_params = {
            "router": bp["moe_router"],
            "w_in": bp["moe_w_in"],
            "w_out": bp["moe_w_out"],
        }
        out, aux = moe_forward(moe_params, flat, _moe_cfg(cfg))
        return x + out.reshape(B, S, D), aux
    h2 = _swiglu(h, bp["mlp_in"], bp["mlp_out"], cfg.dtype)
    if constrain is not None:
        h2 = constrain(h2, ("batch", "seq", "embed"))
    return x + h2, jnp.zeros((), jnp.float32)


def _block(x, bp, cos, sin, cfg: LlamaConfig, rules, mesh):
    B, S, D = x.shape
    Hq, hd, g = cfg.n_head, cfg.head_dim, cfg.kv_groups

    def constrain(t, axes):
        if mesh is None:
            return t
        return with_logical_constraint(t, axes, rules, mesh)

    q, kk, vv = _attn_qkv(x, bp, cos, sin, cfg)
    # GQA: repeat KV heads to match query heads (kernel stays head-uniform)
    if g > 1:
        kk = jnp.repeat(kk, g, axis=2)
        vv = jnp.repeat(vv, g, axis=2)
    q = q.transpose(0, 2, 1, 3)
    kk = kk.transpose(0, 2, 1, 3)
    vv = vv.transpose(0, 2, 1, 3)
    q = constrain(q, ("batch", "heads", None, None))

    if cfg.attention == "flash":
        attn = flash_attention(q, kk, vv, causal=True)
    elif cfg.attention == "ring":
        from ray_tpu.ops.ring_attention import ring_attention_sharded

        attn = ring_attention_sharded(q, kk, vv, mesh, causal=True)
    else:
        attn = mha_reference(q, kk, vv, causal=True)
    attn = attn.transpose(0, 2, 1, 3).reshape(B, S, Hq * hd)
    x = x + attn @ bp["wo"].astype(cfg.dtype)

    x, aux = _ffn_residual(x, bp, cfg, constrain)
    return constrain(x, ("batch", "seq", "embed")), aux


def llama_hidden(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    rules: ShardingRules | None = None,
    mesh=None,
):
    """tokens [B, S] int32 → (final hidden [B, S, D] after rms_norm,
    summed MoE aux loss)."""
    B, S = tokens.shape
    wte = params["wte"].astype(cfg.dtype)
    if mesh is not None:
        # replicate the table for the token gather (see gpt.py: a gather
        # from a vocab/embed-sharded table triggers SPMD's involuntary full
        # rematerialization fallback every step)
        wte = with_logical_constraint(wte, (None, None), rules, mesh)
    x = wte[tokens]
    if mesh is not None:
        x = with_logical_constraint(x, ("batch", "seq", "embed"), rules, mesh)
    cos, sin = rope_cache(S, cfg.head_dim, cfg.rope_theta)

    def body(carry, bp):
        x, aux_sum = carry
        out, aux = _block(x, bp, cos, sin, cfg, rules, mesh)
        return (out, aux_sum + aux), None

    if cfg.remat:
        body = jax.checkpoint(body)
    init = (x, jnp.zeros((), jnp.float32))
    if cfg.scan_layers:
        (x, aux_sum), _ = jax.lax.scan(body, init, params["blocks"])
    else:
        carry = init
        for i in range(cfg.n_layer):
            carry, _ = body(carry, jax.tree.map(lambda a: a[i],
                                                params["blocks"]))
        x, aux_sum = carry
    return rms_norm(x, params["ln_f_scale"]), aux_sum


def llama_forward(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    rules: ShardingRules | None = None,
    mesh=None,
    return_aux: bool = False,
):
    """tokens [B, S] int32 → logits [B, S, vocab] f32 (+ total MoE aux loss)."""
    x, aux_sum = llama_hidden(params, tokens, cfg, rules=rules, mesh=mesh)
    # bf16 operands keep the vocab matmul on the MXU's fast path;
    # accumulation and the returned logits are f32 for a stable softmax
    logits = jnp.einsum(
        "bsd,dv->bsv", x.astype(cfg.dtype),
        params["lm_head"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    if return_aux:
        return logits, aux_sum
    return logits


def llama_loss(
    params: dict,
    batch: dict,
    cfg: LlamaConfig,
    *,
    rules: ShardingRules | None = None,
    mesh=None,
) -> jax.Array:
    mask = batch.get("mask")
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
        # a [B, S+1] token-aligned mask must shift with the targets; a
        # [B, S] mask is already target-aligned
        if mask is not None and mask.shape[-1] == batch["tokens"].shape[-1]:
            mask = mask[:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    if cfg.fused_loss and mesh is None:
        # single-device path: chunked lm-head + CE (ops/loss.py) — the
        # [B,S,V] logits tensor never exists. lm_head is [D, V]; the
        # transpose folds into the chunk matmuls' dimension numbers.
        from ray_tpu.ops.loss import fused_lm_head_loss

        x, aux = llama_hidden(params, inputs, cfg, rules=rules, mesh=mesh)
        B, S, D = x.shape
        ce = fused_lm_head_loss(
            x.reshape(B * S, D),
            params["lm_head"].T,
            targets.reshape(B * S).astype(jnp.int32),
            None if mask is None else mask.reshape(B * S).astype(jnp.float32),
        )
        return ce + aux
    logits, aux = llama_forward(
        params, inputs, cfg, rules=rules, mesh=mesh, return_aux=True
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        ce = -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1)
    else:
        ce = -jnp.mean(ll)
    return ce + aux


# ----------------------------------------------------------------------------
# KV-cached inference paths (serve/llm engine) — same contract as
# models/gpt.py gpt_prefill/gpt_decode_step. GQA: the cache stores the
# compact n_kv_head heads; repetition to n_head happens inside the
# attention ops. Cache layout [n_layer, num_blocks, block_size, n_kv_head,
# head_dim] (ops/kv_cache.py; block 0 is the garbage sink).
# ----------------------------------------------------------------------------


def llama_prefill(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    lengths: jax.Array,
    block_tables: jax.Array,
    cfg: LlamaConfig,
    start: jax.Array | None = None,
    sample: dict | None = None,
):
    """Prompt pass with paged-cache writes; see gpt_prefill. Returns
    (last-valid-token logits [B, V] f32, cache_k', cache_v') — or, with a
    ``sample`` pytree (ops/sampling.py), (sampled first tokens [B] int32,
    cache_k', cache_v'): sampling fuses into the jitted program and only
    token ids ever cross to host.

    ``start=None`` (the whole-prompt path): RoPE runs at positions 0..S-1;
    under the XLA backend attention is the causal reference kernel over
    the chunk alone, under pallas it is the fused paged-prefill kernel off
    the just-written cache.

    ``start`` [B] int32 (the chunked-prefill / prefix-cache path): row b's
    tokens sit at TRUE positions start[b]..start[b]+lengths[b]-1; earlier
    positions are already resident in the paged cache (a previous chunk,
    or blocks mapped from the prefix cache), so attention covers the full
    paged context via the ``prefill_attention`` backend dispatcher instead
    of looking only at the chunk. RoPE indexes the true positions, exactly
    like decode.
    """
    from ray_tpu.ops.kv_cache import write_kv
    from ray_tpu.ops.paged_attention import prefill_attention, resolve_backend

    B, S = tokens.shape
    D = cfg.d_model
    x = params["wte"].astype(cfg.dtype)[tokens]
    if start is None:
        cos, sin = rope_cache(S, cfg.head_dim, cfg.rope_theta)
        pos = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32)[None, :], (B, S)
        )
        rope_pos = None  # cos/sin already sliced to 0..S-1
    else:
        cos, sin = rope_cache(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
        pos = start[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
        # padding columns can run past the table; they are masked anyway
        rope_pos = jnp.minimum(pos, cfg.max_seq_len - 1)
    valid = jnp.arange(S, dtype=jnp.int32)[None, :] < lengths[:, None]

    def body(x, xs):
        bp, k_layer, v_layer = xs
        q, kk, vv = _attn_qkv(x, bp, cos, sin, cfg, positions=rope_pos)
        k_layer, v_layer = write_kv(
            k_layer, v_layer, kk, vv, pos, block_tables, valid=valid
        )
        # see gpt_prefill: the fresh-KV shortcut is gated off under a
        # quantized pool so prefill attends over the same quantized values
        # a failover re-prefill would read back.
        if (
            start is None
            and cfg.quantization is None
            and resolve_backend(cfg.attention_backend) != "pallas"
        ):
            # mha_reference repeats GQA kv heads internally
            attn = mha_reference(
                q.transpose(0, 2, 1, 3),
                kk.transpose(0, 2, 1, 3),
                vv.transpose(0, 2, 1, 3),
                causal=True,
            )
            attn = attn.transpose(0, 2, 1, 3).reshape(B, S, D)
        else:
            attn = prefill_attention(
                q, k_layer, v_layer, block_tables,
                jnp.where(valid, pos, 0),
                backend=cfg.attention_backend,
            ).reshape(B, S, D)
        x = x + attn @ bp["wo"].astype(cfg.dtype)
        x, _ = _ffn_residual(x, bp, cfg)
        return x, (k_layer, v_layer)

    x, (cache_k, cache_v) = jax.lax.scan(
        body, x, (params["blocks"], cache_k, cache_v)
    )
    h = rms_norm(x, params["ln_f_scale"])
    h_last = h[jnp.arange(B), lengths - 1]  # [B, D]
    logits = jnp.einsum(
        "bd,dv->bv", h_last.astype(cfg.dtype),
        params["lm_head"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    if sample is None:
        return logits, cache_k, cache_v
    from ray_tpu.ops.sampling import sample_tokens

    # the new token lands right after the last valid prompt token
    new_pos = (lengths if start is None else start + lengths).astype(
        jnp.int32
    )
    return sample_tokens(logits, new_pos, sample), cache_k, cache_v


def llama_decode_step(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    positions: jax.Array,
    block_tables: jax.Array,
    cfg: LlamaConfig,
    sample: dict | None = None,
):
    """One incremental decode step; see gpt_decode_step. RoPE is applied at
    the TRUE sequence position via the `positions` arg of ops/layers.rope.
    Returns (next-token logits [B, V] f32, cache_k', cache_v'); with a
    ``sample`` pytree the logits never leave the device — returns
    (sampled tokens [B] int32, cache_k', cache_v')."""
    from ray_tpu.ops.kv_cache import write_kv
    from ray_tpu.ops.paged_attention import decode_attention

    B = tokens.shape[0]
    D = cfg.d_model
    x = params["wte"].astype(cfg.dtype)[tokens][:, None, :]  # [B, 1, D]
    cos, sin = rope_cache(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    pos2d = positions[:, None]  # [B, 1] — rope indexes tables per row

    def body(x, xs):
        bp, k_layer, v_layer = xs
        q, kk, vv = _attn_qkv(x, bp, cos, sin, cfg, positions=pos2d)
        k_layer, v_layer = write_kv(
            k_layer, v_layer, kk[:, 0], vv[:, 0], positions, block_tables
        )
        attn = decode_attention(
            q[:, 0], k_layer, v_layer, block_tables, positions,
            backend=cfg.attention_backend,
        )  # GQA handled inside (cache holds n_kv_head heads)
        x = x + attn.reshape(B, 1, D) @ bp["wo"].astype(cfg.dtype)
        x, _ = _ffn_residual(x, bp, cfg)
        return x, (k_layer, v_layer)

    x, (cache_k, cache_v) = jax.lax.scan(
        body, x, (params["blocks"], cache_k, cache_v)
    )
    h = rms_norm(x[:, 0], params["ln_f_scale"])
    logits = jnp.einsum(
        "bd,dv->bv", h.astype(cfg.dtype), params["lm_head"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    if sample is None:
        return logits, cache_k, cache_v
    from ray_tpu.ops.sampling import sample_tokens

    return sample_tokens(logits, positions + 1, sample), cache_k, cache_v


def llama_verify_step(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    starts: jax.Array,
    draft_len: jax.Array,
    block_tables: jax.Array,
    cfg: LlamaConfig,
    sample: dict | None = None,
):
    """Speculative-decoding verify pass: score a [B, W] window in one call.

    ``tokens`` [B, W] int32 — column 0 is row b's last COMMITTED token
    (true position ``starts`` [B]; its K/V is not yet cached, exactly as in
    a decode step), columns 1..W-1 are drafted candidates; columns past
    ``draft_len`` [B] are padding. The body is the chunked-prefill
    formulation at true positions (RoPE indexed per position, K/V written
    for the valid window, ``prefill_attention`` over the full paged
    context) but keeps logits at ALL window positions instead of the last
    valid one, feeding the ``verify_tokens`` epilogue (ops/sampling.py).

    K/V discipline: valid columns write at their own positions — for
    accepted drafts that IS the correct cache entry (accepted prefix =>
    identical context => identical K/V). Rejected drafts leave garbage
    only BEYOND the committed frontier, where the causal mask
    ``t <= position`` keeps it unattended until the frontier's next window
    overwrites those positions; no rollback pass is needed. Padding
    columns are redirected to the garbage block, so reservations only need
    to cover ``draft_len`` positions past the frontier.

    Returns (packed verdicts [B, W+1] int32 — see ``verify_tokens``,
    cache_k', cache_v'); with ``sample=None`` returns the raw window
    logits [B, W, V] f32 instead of verdicts (debug path).
    """
    from ray_tpu.ops.kv_cache import write_kv
    from ray_tpu.ops.paged_attention import prefill_attention

    B, W = tokens.shape
    D = cfg.d_model
    x = params["wte"].astype(cfg.dtype)[tokens]
    cos, sin = rope_cache(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    pos = starts[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    # padding columns can run past the table; they are masked anyway
    rope_pos = jnp.minimum(pos, cfg.max_seq_len - 1)
    valid = (
        jnp.arange(W, dtype=jnp.int32)[None, :] <= draft_len[:, None]
    )

    def body(x, xs):
        bp, k_layer, v_layer = xs
        q, kk, vv = _attn_qkv(x, bp, cos, sin, cfg, positions=rope_pos)
        k_layer, v_layer = write_kv(
            k_layer, v_layer, kk, vv, pos, block_tables, valid=valid
        )
        attn = prefill_attention(
            q, k_layer, v_layer, block_tables, jnp.where(valid, pos, 0),
            backend=cfg.attention_backend,
        ).reshape(B, W, D)
        x = x + attn @ bp["wo"].astype(cfg.dtype)
        x, _ = _ffn_residual(x, bp, cfg)
        return x, (k_layer, v_layer)

    x, (cache_k, cache_v) = jax.lax.scan(
        body, x, (params["blocks"], cache_k, cache_v)
    )
    h = rms_norm(x, params["ln_f_scale"])  # [B, W, D]
    logits = jnp.einsum(
        "bwd,dv->bwv", h.astype(cfg.dtype),
        params["lm_head"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    if sample is None:
        return logits, cache_k, cache_v
    from ray_tpu.ops.sampling import verify_tokens

    return (
        verify_tokens(logits, starts, tokens, draft_len, sample),
        cache_k,
        cache_v,
    )


def llama_num_params(cfg: LlamaConfig) -> int:
    p = llama_init(jax.random.PRNGKey(0), cfg)
    return sum(x.size for x in jax.tree.leaves(p))
