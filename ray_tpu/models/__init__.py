from ray_tpu.models.gpt import GPTConfig, gpt_forward, gpt_init, gpt_param_axes
from ray_tpu.models.llama import (
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_loss,
    llama_param_axes,
)
from ray_tpu.models.resnet import ResNet50, resnet_init
from ray_tpu.models.vit import (
    ViTConfig,
    vit_forward,
    vit_init,
    vit_loss,
    vit_num_params,
    vit_param_axes,
)

__all__ = [
    "GPTConfig",
    "LlamaConfig",
    "ResNet50",
    "ViTConfig",
    "gpt_forward",
    "gpt_init",
    "gpt_param_axes",
    "llama_forward",
    "llama_init",
    "llama_loss",
    "llama_param_axes",
    "resnet_init",
    "vit_forward",
    "vit_init",
    "vit_loss",
    "vit_num_params",
    "vit_param_axes",
]
