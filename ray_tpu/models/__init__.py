from ray_tpu.models.gpt import GPTConfig, gpt_forward, gpt_init, gpt_param_axes
from ray_tpu.models.resnet import ResNet50, resnet_init

__all__ = [
    "GPTConfig",
    "gpt_forward",
    "gpt_init",
    "gpt_param_axes",
    "ResNet50",
    "resnet_init",
]
