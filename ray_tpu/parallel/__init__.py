from ray_tpu.parallel.mesh import (
    AxisNames,
    MeshSpec,
    build_mesh,
    local_mesh,
)
from ray_tpu.parallel.sharding import (
    ShardingRules,
    logical_to_mesh_axes,
    param_shardings,
    shard_batch_spec,
    shard_params,
    with_logical_constraint,
)

__all__ = [
    "AxisNames",
    "MeshSpec",
    "build_mesh",
    "local_mesh",
    "ShardingRules",
    "logical_to_mesh_axes",
    "param_shardings",
    "shard_batch_spec",
    "shard_params",
    "with_logical_constraint",
]
