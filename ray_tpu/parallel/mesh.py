"""Device meshes: the TPU-native substrate for every parallelism strategy.

The reference has no native TP/PP/SP (SURVEY.md §2.4 — torch DDP/FSDP via
integrations only; reference: python/ray/train/torch/train_loop_utils.py:74
prepare_model→DDP/FSDP). Here parallelism is mesh-first: a single
`jax.sharding.Mesh` with canonical axis names carries data/fsdp/tensor/
sequence/pipeline/expert parallelism; XLA inserts the collectives over
ICI/DCN (the NCCL replacement per SURVEY.md §5.8).

Axis order is chosen so the innermost (fastest-varying, ICI-nearest) axis is
tensor parallelism — TP collectives are latency-bound and must ride the
shortest ICI hops; DP/FSDP gradient reductions are bandwidth-bound and
tolerate the outer axes (DCN across slices in multi-slice deployments).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field


class AxisNames:
    DATA = "dp"       # pure data parallel (replicated params)
    FSDP = "fsdp"     # sharded-data-parallel (ZeRO-3 style param sharding)
    TENSOR = "tp"     # tensor/model parallel
    SEQ = "sp"        # sequence/context parallel (ring attention)
    PIPE = "pp"       # pipeline stages
    EXPERT = "ep"     # MoE expert parallel

    ALL = (DATA, FSDP, PIPE, SEQ, TENSOR, EXPERT)


@dataclass(frozen=True)
class MeshSpec:
    """Logical axis sizes; -1 on at most one axis means 'fill remaining'."""

    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    def sizes(self) -> dict[str, int]:
        return {
            AxisNames.DATA: self.dp,
            AxisNames.FSDP: self.fsdp,
            AxisNames.PIPE: self.pp,
            AxisNames.SEQ: self.sp,
            AxisNames.TENSOR: self.tp,
            AxisNames.EXPERT: self.ep,
        }

    def resolve(self, n_devices: int) -> "MeshSpec":
        if n_devices < 1:
            raise ValueError(
                f"cannot resolve a mesh over {n_devices} devices"
            )
        sizes = self.sizes()
        bad = {k: v for k, v in sizes.items() if v != -1 and v < 1}
        if bad:
            raise ValueError(
                f"mesh axis sizes must be positive ints, or -1 on one "
                f"axis to fill the remaining devices; got {bad}"
            )
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(
                f"at most one mesh axis may be -1, got {wild}"
            )
        fixed = math.prod(v for v in sizes.values() if v != -1)
        named = {k: v for k, v in sizes.items() if v != -1 and v > 1}
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"cannot fill mesh axis {wild[0]!r}: the fixed axes "
                    f"{named or '{}'} multiply to {fixed}, which does "
                    f"not divide the {n_devices} available devices"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes {named or '{}'} multiply to {fixed} but "
                f"{n_devices} devices are available; axis sizes must "
                f"multiply to exactly the device count (use -1 on one "
                f"axis to fill)"
            )
        return MeshSpec(
            dp=sizes[AxisNames.DATA],
            fsdp=sizes[AxisNames.FSDP],
            pp=sizes[AxisNames.PIPE],
            sp=sizes[AxisNames.SEQ],
            tp=sizes[AxisNames.TENSOR],
            ep=sizes[AxisNames.EXPERT],
        )


def build_mesh(spec: MeshSpec, devices=None):
    """Build a Mesh with the canonical 6 named axes (size-1 axes included —
    they cost nothing and keep sharding specs uniform)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    spec = spec.resolve(len(devices))
    sizes = spec.sizes()
    shape = tuple(sizes[a] for a in AxisNames.ALL)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AxisNames.ALL)


def local_mesh(**axis_sizes):
    """Convenience: mesh over all local devices, e.g. local_mesh(dp=-1) or
    local_mesh(dp=2, tp=4)."""
    spec = MeshSpec(**axis_sizes) if axis_sizes else MeshSpec(dp=-1)
    return build_mesh(spec)
