"""Logical-axis sharding rules: params/activations → mesh axes.

The TPU replacement for the reference's wrapper-based parallelism
(reference: train/torch/train_loop_utils.py:74-95 prepare_model wraps
DDP/FSDP around an opaque module). Here models annotate every parameter
with *logical* axis names ("embed", "heads", "mlp", ...); a ShardingRules
table maps logical axes to mesh axes, and `shard_params` materializes
`NamedSharding`s. Changing the parallelism strategy = changing the rules
table — the model code never changes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import AxisNames


# Default logical→mesh rules for transformer-family models.
# fsdp shards the embed (model-dim) axis of every weight — ZeRO-3;
# tp shards heads / mlp-hidden / vocab — Megatron-style.
DEFAULT_RULES: tuple[tuple[str, str | tuple[str, ...] | None], ...] = (
    ("batch", (AxisNames.DATA, AxisNames.FSDP)),
    ("seq", AxisNames.SEQ),
    ("embed", AxisNames.FSDP),
    ("heads", AxisNames.TENSOR),
    ("kv_heads", AxisNames.TENSOR),
    ("mlp", AxisNames.TENSOR),
    ("vocab", AxisNames.TENSOR),
    ("head_dim", None),
    ("expert", AxisNames.EXPERT),
    ("stage", AxisNames.PIPE),
    ("conv_kernel", None),
    ("channels_in", None),
    ("channels_out", AxisNames.TENSOR),
)


@dataclass
class ShardingRules:
    rules: tuple[tuple[str, Any], ...] = DEFAULT_RULES

    def mesh_axes(self, logical_axes: tuple[str | None, ...]) -> P:
        table = dict(self.rules)
        out = []
        used: set[str] = set()
        for ax in logical_axes:
            mapped = table.get(ax) if ax is not None else None
            # drop mesh axes already consumed by an earlier dim (a mesh axis
            # may shard at most one dim of a given array)
            if isinstance(mapped, tuple):
                mapped = tuple(m for m in mapped if m not in used) or None
                if mapped is not None:
                    used.update(mapped)
            elif mapped is not None:
                if mapped in used:
                    mapped = None
                else:
                    used.add(mapped)
            out.append(mapped)
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    def replace(self, **overrides) -> "ShardingRules":
        new_rules = tuple(
            (k, overrides.get(k, v)) for k, v in self.rules
        ) + tuple((k, v) for k, v in overrides.items() if k not in dict(self.rules))
        return ShardingRules(new_rules)


def logical_to_mesh_axes(
    axes_tree: Any, rules: ShardingRules | None = None
) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    rules = rules or ShardingRules()
    return jax.tree.map(
        lambda axes: rules.mesh_axes(axes),
        axes_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x
        ),
    )


def shard_params(params: Any, axes_tree: Any, mesh: Mesh,
                 rules: ShardingRules | None = None) -> Any:
    """Device-put a param pytree with NamedShardings derived from its
    logical axes. Arrays already on-mesh are resharded lazily by XLA."""
    specs = logical_to_mesh_axes(axes_tree, rules)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params,
        specs,
    )


def param_shardings(axes_tree: Any, mesh: Mesh,
                    rules: ShardingRules | None = None) -> Any:
    """NamedSharding pytree (for jit in_shardings/out_shardings)."""
    specs = logical_to_mesh_axes(axes_tree, rules)
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_batch_spec(rules: ShardingRules | None = None, *, seq_sharded: bool = False) -> P:
    """PartitionSpec for [batch, seq, ...] input batches."""
    rules = rules or ShardingRules()
    if seq_sharded:
        return rules.mesh_axes(("batch", "seq"))
    return rules.mesh_axes(("batch", None))


def with_logical_constraint(x, logical_axes: tuple[str | None, ...],
                            rules: ShardingRules | None = None,
                            mesh: Mesh | None = None):
    """Annotate an intermediate activation inside jit (the
    lax.with_sharding_constraint idiom keyed by logical axes). With an
    explicit mesh a NamedSharding is used; otherwise the caller must be
    under a mesh context (jax.sharding.use_mesh)."""
    rules = rules or ShardingRules()
    spec = rules.mesh_axes(logical_axes)
    if mesh is not None:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)
