"""RLlib PPO sampling+training throughput (env steps/sec).

The second north-star metric (BASELINE.json: "RLlib PPO env-steps/sec").
The reference publishes no PPO-throughput number, so this self-baselines
(BASELINE.md notes the same for `ray microbenchmark`): PPO on CartPole
with a local EnvRunner, measuring LIFETIME env steps sampled per second of
wall clock across full train iterations — sampling, GAE, minibatch epochs,
and weight broadcast all included, the same accounting RLlib's
`num_env_steps_sampled_lifetime / time` gives. Prints one JSON line.
"""
from __future__ import annotations

import json
import time

ITERATIONS = 12
WARMUP_ITERS = 2


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_envs_per_runner=16, rollout_length=128)
        .training(minibatch_size=512, num_epochs=4)
        .debugging(seed=0)
        .build()
    )
    for _ in range(WARMUP_ITERS):  # compile + buffer warmup excluded
        algo.train()
    base_steps = algo._total_env_steps
    t0 = time.perf_counter()
    last = {}
    for _ in range(ITERATIONS):
        last = algo.train()
    dt = time.perf_counter() - t0
    steps = algo._total_env_steps - base_steps
    print(json.dumps({
        "ppo_env_steps_per_sec": round(steps / dt, 1),
        "episode_return_mean": round(last.get("episode_return_mean", 0.0), 1),
        "iterations": ITERATIONS,
    }), flush=True)


if __name__ == "__main__":
    main()
