"""RLlib PPO sampling+training throughput (env steps/sec).

The second north-star metric (BASELINE.json: "RLlib PPO env-steps/sec").
The reference publishes no PPO-throughput number, so this self-baselines
(BASELINE.md notes the same for `ray microbenchmark`): PPO on CartPole
with a local EnvRunner, measuring LIFETIME env steps sampled per second of
wall clock across full train iterations — sampling, GAE, minibatch epochs,
and weight broadcast all included, the same accounting RLlib's
`num_env_steps_sampled_lifetime / time` gives. Prints one JSON line.
"""
from __future__ import annotations

import json
import time

ITERATIONS = 12
WARMUP_ITERS = 2


def _measure(cfg_builder, iterations: int) -> tuple[float, dict]:
    algo = cfg_builder.build()
    for _ in range(WARMUP_ITERS):  # compile + buffer warmup excluded
        algo.train()
    base_steps = algo._total_env_steps
    t0 = time.perf_counter()
    last = {}
    for _ in range(iterations):
        last = algo.train()
    dt = time.perf_counter() - t0
    steps = algo._total_env_steps - base_steps
    algo.stop()
    return steps / dt, last


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    mlp_rate, last = _measure(
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_envs_per_runner=16, rollout_length=128)
        .training(minibatch_size=512, num_epochs=4)
        .debugging(seed=0),
        ITERATIONS,
    )
    # Atari-class companion (VERDICT r3 weak #6: CartPole MLPs prove
    # orchestration, not learner throughput): conv policy over MinAtar-
    # style 10x10x4 frames — the same accounting on an image workload
    conv_iters = max(3, ITERATIONS // 3)
    conv_rate, _ = _measure(
        PPOConfig()
        .environment("MiniBreakout")
        .env_runners(num_envs_per_runner=8, rollout_length=128)
        .training(minibatch_size=256, num_epochs=2,
                  frame_shape=(10, 10, 4))
        .debugging(seed=0),
        conv_iters,
    )
    # rollout-only conv rate: isolates the EnvRunner path (jitted CPU
    # inference + batched boundary bootstraps) from the learner's conv
    # gradients, which on a 1-core CPU box dominate total time but run
    # on the TPU in production
    from ray_tpu.rllib.env_runner import EnvRunner
    from ray_tpu.rllib.rl_module import ConvActorCriticModule

    runner = EnvRunner(
        "MiniBreakout",
        lambda d, a: ConvActorCriticModule(d, a, frame_shape=(10, 10, 4)),
        num_envs=8, rollout_length=128, seed=0)
    w = runner.module.init(0)
    runner.set_weights(w)
    runner.sample()  # warm the per-step jit shape
    # warm every bootstrap bucket the padded boundary batch can hit, so
    # a timed rollout crossing a power-of-two bucket never pays a fresh
    # XLA compile inside the clock
    import numpy as np
    for bucket in (32, 64, 128, 256, 512, 1024):
        runner.module.forward_np(w, np.zeros((bucket, 400), np.float32))
    t0 = time.perf_counter()
    n = sum(runner.sample()["rewards"].size for _ in range(4))
    conv_rollout_rate = n / (time.perf_counter() - t0)
    print(json.dumps({
        "ppo_env_steps_per_sec": round(mlp_rate, 1),
        "ppo_conv_env_steps_per_sec": round(conv_rate, 1),
        "ppo_conv_rollout_only_steps_per_sec": round(conv_rollout_rate, 1),
        "episode_return_mean": round(last.get("episode_return_mean", 0.0), 1),
        "iterations": ITERATIONS,
        "conv_iterations": conv_iters,
    }), flush=True)


if __name__ == "__main__":
    main()
