"""GPT-2 (125M) single-chip train-step benchmark — the headline metric.

Transformers are the workload TPUs are bought for; this measures a jitted
next-token training step (flash-attention Pallas kernel, bf16 activations,
donated buffers) and reports tokens/sec + MFU.

MFU convention: model FLOPs = 6 * n_params * tokens per train step (PaLM
appendix-B style, attention excluded — conservative), divided by the chip's
peak bf16 rate. The reference publishes no MFU (or any TPU number) for its
trainers (doc/source/train/benchmarks.rst), so the bar here is the absolute
one this repo sets for itself: >= 0.35 on a single chip.

Runnable standalone: `python -m ray_tpu.benchmarks.gpt_mfu` prints one JSON
line (used by bench.py as the headline entry).
"""
from __future__ import annotations

import json
import os
import time
from functools import partial
from typing import Callable


def run_gpt_bench(
    batch_size: int = 16,
    seq_len: int = 1024,
    steps: int = 40,
    warmup: int = 4,
    chunk: int = 8,
    peak_tflops: float | None = None,
    publish: Callable[[dict], None] | None = None,
    config: str = "gpt2_small",
    remat: bool = False,
) -> dict:
    """Measure jitted GPT train-step throughput. `publish` receives partial
    results after every chunk so a watchdog can report mid-run progress."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.gpt import (
        GPTConfig, gpt_init, gpt_loss, gpt_num_params,
    )

    dev = jax.devices()[0]
    platform = dev.platform
    if peak_tflops is None:
        peak_tflops = chip_peak_tflops(dev)

    cfg = getattr(GPTConfig, config)() if config != "tiny" else GPTConfig.tiny()
    # the bench runs the unrolled layer loop: XLA schedules across layer
    # boundaries instead of paying the scan-carry tax in the backward
    # (33%→43% MFU on v5e bs16/seq1024; see docs/MICROBENCHMARKS.md)
    cfg = dataclasses.replace(cfg, scan_layers=env_bool("BENCH_GPT_SCAN"))
    if remat:
        # last-rung fallback for smaller-HBM chips: per-block
        # rematerialization trades ~1 extra forward for dropping the
        # saved per-layer residuals (scan or unrolled alike)
        cfg = dataclasses.replace(cfg, remat=True)
    if seq_len > cfg.max_seq_len:
        # long-context bench shapes: grow the positional table (a shorter
        # context slices down free)
        cfg = dataclasses.replace(cfg, max_seq_len=seq_len)
    n_params = gpt_num_params(cfg)
    model_label = _model_label(config, n_params)
    params = gpt_init(jax.random.PRNGKey(0), cfg)

    tx = optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)
    opt_state = tx.init(params)

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(gpt_loss)(params, batch, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    key = jax.random.PRNGKey(1)
    batch = {
        "tokens": jax.random.randint(
            key, (batch_size, seq_len + 1), 0, cfg.vocab_size, jnp.int32
        ),
    }
    tokens_per_step = batch_size * seq_len

    def make_result(tps: float, tag: str = "") -> dict:
        achieved = tps * 6.0 * n_params / 1e12
        mfu = achieved / peak_tflops if peak_tflops else 0.0
        return {
            "metric": f"{model_label}_train_tokens_per_sec_per_chip_{platform}{tag}",
            "value": round(tps, 1),
            "unit": "tokens/sec",
            # no reference GPT/MFU number exists (BASELINE.md) — the bar is
            # the self-set 35% MFU target, so vs_baseline = mfu / 0.35
            "vs_baseline": round(mfu / 0.35, 3) if peak_tflops else 0.0,
            "mfu": round(mfu, 4),
            "achieved_tflops": round(achieved, 1),
            "chip_peak_tflops": peak_tflops,
            "n_params": n_params,
            "batch_size": batch_size,
            "seq_len": seq_len,
            "remat": remat,
        }

    for _ in range(warmup):
        params, opt_state, loss = train_step(params, opt_state, batch)
    # value fetch, not block_until_ready: the axon-tunneled platform treats
    # block_until_ready as a no-op; only materializing forces execution
    float(loss)

    done = 0
    t0 = time.perf_counter()
    while done < steps:
        n = min(chunk, steps - done)
        for _ in range(n):
            params, opt_state, loss = train_step(params, opt_state, batch)
        float(loss)  # forces the chunk's chain via dataflow dependency
        done += n
        dt = time.perf_counter() - t0
        if publish is not None:
            publish(make_result(tokens_per_step * done / dt))
    dt = time.perf_counter() - t0
    return make_result(tokens_per_step * steps / dt)


def _model_label(config: str, n_params: int) -> str:
    """Metric label derived from the ACTUAL benched config, never hardcoded:
    a tiny-config fallback run must not be labeled as the 125M headline."""
    canonical = {"gpt2_small": "gpt2_125m", "gpt2_medium": "gpt2_350m"}
    if config in canonical:
        return canonical[config]
    if n_params >= 1e6:
        return f"gpt2_{config}_{n_params / 1e6:.0f}m"
    return f"gpt2_{config}_{n_params / 1e3:.0f}k"


# Known per-chip peak bf16 TFLOP/s by device_kind substring (shared with
# bench.py; ordering matters — first substring match wins).
CHIP_PEAK_TFLOPS = [
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0),
    ("v5e", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]


def env_bool(name: str) -> bool:
    """Shared falsy-string parse so 'False'/'no'/'off'/'0' all disable."""
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "no", "off"
    )


def gpt_env_kwargs() -> dict:
    """BENCH_GPT_* env overrides as run_gpt_bench kwargs — the one parser
    both entry points (bench.py and this module's main) share. A falsy
    BENCH_GPT_REMAT contributes nothing, so it cannot make the kwargs
    truthy and suppress bench.py's OOM fallback ladder."""
    kwargs: dict = {}
    for name, key in (("BENCH_GPT_BS", "batch_size"),
                      ("BENCH_GPT_SEQ", "seq_len"),
                      ("BENCH_GPT_STEPS", "steps")):
        if os.environ.get(name):
            kwargs[key] = int(os.environ[name])
    if os.environ.get("BENCH_GPT_CONFIG"):
        kwargs["config"] = os.environ["BENCH_GPT_CONFIG"]
    if env_bool("BENCH_GPT_REMAT"):
        kwargs["remat"] = True
    return kwargs


def chip_peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in CHIP_PEAK_TFLOPS:
        if sub in kind:
            return peak
    if device.platform == "cpu":
        return 0.5  # nominal; MFU on CPU is not meaningful
    return 275.0  # assume v4-class if unknown


def main() -> None:
    # the axon sitecustomize overrides jax_platforms at interpreter start;
    # a JAX_PLATFORMS=cpu request must be re-asserted in-process
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(run_gpt_bench(**gpt_env_kwargs())), flush=True)


if __name__ == "__main__":
    main()
