"""Trainer-orchestration overhead: JaxTrainer report() plumbing vs a bare loop.

The reference's real acceptance bar is orchestration overhead ≤ ~2.5% vs
the native distributed backend (reference: doc/source/train/benchmarks.rst:56
Torch parity tables).

Contention-robust design (round 5): the round-4 version timed the bare loop
in the driver and the framework loop in a worker, minutes apart — on a busy
1-core box the two windows saw different load and the artifact measured the
weather (6.41% one round, −0.5% the round before). Now BOTH arms run inside
the SAME JaxTrainer worker process as interleaved ~30 ms 50-step blocks in
ABBA order (B F F B per cycle; each pair's halves are physically adjacent,
in either order, so box load cancels within the pair and report()'s deferred
driver-side work is billed to each arm equally often). Both arms run the
identical jitted step and materialize the loss once per block; the framework
arm additionally calls ``report()``. The reported overhead is the
25%-trimmed mean of the per-pair deltas over the mean bare-block time.
Prints one JSON line.
"""
from __future__ import annotations

import json
import time

BLOCK_STEPS = 50
N_BLOCKS = 600  # alternating arms -> N_BLOCKS/2 paired samples
DIM = 256


def _build_step():
    import jax
    import jax.numpy as jnp
    import optax

    jax.config.update("jax_platforms", "cpu")
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (DIM, DIM)) * 0.02
    x = jax.random.normal(jax.random.PRNGKey(1), (64, DIM))
    y = jax.random.normal(jax.random.PRNGKey(2), (64, DIM))
    tx = optax.sgd(1e-3)
    opt = tx.init(w)

    @jax.jit
    def step(w, opt):
        def loss_fn(w):
            return jnp.mean((jnp.tanh(x @ w) @ w.T - y) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(w)
        up, opt = tx.update(g, opt)
        return optax.apply_updates(w, up), opt, loss

    return step, w, opt


def _paired_loop(report) -> dict:
    """Alternate (bare, framework) 50-step blocks in THIS process.

    Both arms run the identical jitted step and materialize the loss once
    per block — a native loop logs at some cadence too, and an unsynced arm
    would measure JAX dispatch-queue depth, not framework cost. The only
    difference is that the framework arm also calls ``report()``. Blocks are
    ~tens of ms and interleaved, so box-load swings hit both arms'
    samples alike; the caller takes a trimmed mean of adjacent-pair deltas,
    which shrugs off preemption spikes that land between a pair's halves.
    """
    step, w, opt = _build_step()
    w, opt, loss = step(w, opt)  # compile
    float(loss)

    def block(use_report: bool):
        nonlocal w, opt
        t0 = time.perf_counter()
        for _ in range(BLOCK_STEPS):
            w, opt, loss = step(w, opt)
        metrics = {"loss": float(loss)}
        if use_report:
            report(metrics)
        return time.perf_counter() - t0

    # ABBA ordering (B F F B per cycle), not strict alternation: report()'s
    # deferred driver-side processing steals cycles from whichever block
    # runs NEXT, and under B F B F that is always a bare block — which
    # systematically inflates the bare arm and can push measured overhead
    # negative. Under ABBA each arm follows a report equally often.
    bare_times, fw_times = [], []
    for k in range(N_BLOCKS):
        is_fw = k % 4 in (1, 2)
        (fw_times if is_fw else bare_times).append(block(is_fw))
    return {"bare_times": bare_times, "fw_times": fw_times}


def run_paired() -> dict:
    import ray_tpu
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig, report

    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)

    def loop(config):
        stats = _paired_loop(report=report)
        report(stats)

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="overhead-bench"),
    ).fit()
    if result.error:
        raise RuntimeError(result.error)
    return {
        "bare_times": list(result.metrics["bare_times"]),
        "fw_times": list(result.metrics["fw_times"]),
    }


def _trimmed_mean(xs, trim=0.25):
    xs = sorted(xs)
    k = int(len(xs) * trim)
    core = xs[k : len(xs) - k] or xs
    return sum(core) / len(core)


def main() -> None:
    stats = run_paired()
    # The i-th bare block is paired with the i-th framework block — under
    # ABBA ordering the two halves of every pair are physically adjacent
    # (~30 ms apart, in either order), so box-load swings cancel within the
    # pair; the 25%-trimmed mean of the paired deltas then discards pairs
    # where a preemption slice landed between the halves. This estimator had
    # the lowest run-to-run variance observed on a load-1.8 single-core box
    # (raw per-arm medians and mins both swing ±1.5% there).
    deltas = [f - b for b, f in zip(stats["bare_times"], stats["fw_times"])]
    mean_bare = _trimmed_mean(stats["bare_times"])
    mean_delta = _trimmed_mean(deltas)
    print(
        json.dumps(
            {
                "blocks_per_arm": N_BLOCKS // 2,
                "block_steps": BLOCK_STEPS,
                "bare_block_ms": round(mean_bare * 1e3, 2),
                "paired_delta_us": round(mean_delta * 1e6, 1),
                "trainer_overhead_pct": round(
                    mean_delta / mean_bare * 100.0, 2
                ),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
